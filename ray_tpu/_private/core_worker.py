"""Core worker — the per-process task/actor/object runtime.

Capability parity with the reference's core worker (reference:
src/ray/core_worker/core_worker.h:182 — SubmitTask core_worker.cc:1995,
Get :1326, HandlePushTask :3672; task_submission/normal_task_submitter.h:87;
task_submission/actor_task_submitter.h:69; store_provider/memory_store/
memory_store.h:48; reference_counter.h:44). Linked into every driver and
worker process; drivers run it on a background asyncio thread, workers run it
on the process main loop.

Data plane design: small objects ride RPC replies into the owner's in-process
memory store; large objects are sealed into the executing node's shared-memory
store and the owner records the location (ownership-based object directory,
reference: ownership_object_directory.h). `get` of a remote object asks the
local daemon to pull it chunk-wise into the local store, then maps it
zero-copy.
"""

from __future__ import annotations

import asyncio
import collections
from ray_tpu._private.aio import spawn
import functools
import logging
import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import cloudpickle

from ray_tpu._private import fastpath as _fp
from ray_tpu._private import flight_recorder
from ray_tpu._private import hops
from ray_tpu._private import protocol as pb
from ray_tpu._private import serialization as ser
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.errors import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    ObjectStoreFullError,
    RayTpuError,
    RpcError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.protocol import ResourceSet, SchedulingStrategy, TaskSpec
from ray_tpu.runtime.object_store import META_ERROR, META_NORMAL, ShmObjectStore
from ray_tpu.runtime.rpc import RpcClient, RpcConnectionLost, RpcServer

logger = logging.getLogger(__name__)


def _trace_inject():
    """Outgoing trace context (None when tracing is off — the common case
    costs one function call and an env lookup)."""
    from ray_tpu.util.tracing import inject_context

    return inject_context()


_DERIVE_CTX_CACHE = None


def _tracing_DERIVE_CTX():
    # cached: this sits on the traced fast-lane eligibility check
    global _DERIVE_CTX_CACHE
    if _DERIVE_CTX_CACHE is None:
        from ray_tpu.util.tracing import DERIVE_CTX

        _DERIVE_CTX_CACHE = DERIVE_CTX
    return _DERIVE_CTX_CACHE

MODE_DRIVER = "driver"
MODE_WORKER = "worker"

_FP_EMPTY_ARGS = b"\x90"  # msgpack []


def _fp_pack_args(wire_args: list) -> bytes:
    """Wire args as one msgpack value for the native spec encoder (fast-lane
    args are inline-only entries, typically tiny)."""
    if not wire_args:
        return _FP_EMPTY_ARGS
    import msgpack

    return msgpack.packb(wire_args, use_bin_type=True)

_current_core_worker: Optional["CoreWorker"] = None


def get_core_worker() -> "CoreWorker":
    if _current_core_worker is None:
        raise RayTpuError("ray_tpu.init() has not been called in this process")
    return _current_core_worker


def set_core_worker(cw: Optional["CoreWorker"]) -> None:
    global _current_core_worker
    _current_core_worker = cw


def compute_lease_key(resources: "ResourceSet", strategy,
                      env_key: str = "") -> Optional[tuple]:
    """Scheduling key: tasks of the same shape can reuse one lease
    (reference: normal_task_submitter.h SchedulingKey lease pools —
    including the runtime-env hash: an env-isolated worker must never
    serve another env's tasks). None → never pool: SPREAD tasks must
    spread across nodes, and reusing one granted worker would pin them."""
    if strategy.kind == pb.STRATEGY_SPREAD:
        return None
    return (
        tuple(sorted(resources.to_wire().items())),
        tuple(sorted(
            (k, str(v)) for k, v in strategy.to_wire().items()
        )),
        env_key,
    )


class ObjectRef:
    """A reference to a (possibly not-yet-computed) remote object.

    Reference: the ObjectRef/ObjectID surface of python/ray/_raylet.pyx and
    the distributed ref counting of src/ray/core_worker/reference_counter.h:44.
    Pickling an ObjectRef registers a borrow with the owner; dropping the last
    reference in a process releases it.
    """

    __slots__ = ("_id", "_owner_address", "_owner_worker_id", "_released", "__weakref__")

    def __init__(self, object_id: ObjectID, owner_address: str, owner_worker_id: bytes,
                 *, _register: bool = True):
        self._id = object_id
        self._owner_address = owner_address
        self._owner_worker_id = owner_worker_id
        self._released = False
        if _register and _current_core_worker is not None:
            _current_core_worker.ref_counter.add_local(self)

    def object_id(self) -> ObjectID:
        return self._id

    def hex(self) -> str:
        return self._id.hex()

    def binary(self) -> bytes:
        return self._id.binary()

    @property
    def owner_address(self) -> str:
        return self._owner_address

    def __reduce__(self):
        ser.note_contained_ref(self)
        return (
            _deserialize_object_ref,
            (self._id.binary(), self._owner_address, self._owner_worker_id),
        )

    def __del__(self):
        if not self._released and _current_core_worker is not None:
            try:
                _current_core_worker.ref_counter.remove_local(self)
            except Exception:  # noqa: BLE001 — interpreter shutdown
                pass

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self._id.hex()})"

    # Allow `await ref` inside async actors.
    def __await__(self):
        cw = get_core_worker()
        return cw.get_async(self).__await__()


def _deserialize_object_ref(id_bytes: bytes, owner_address: str, owner_worker_id: bytes):
    ref = ObjectRef(ObjectID(id_bytes), owner_address, owner_worker_id, _register=False)
    if _current_core_worker is not None:
        _current_core_worker.ref_counter.on_ref_deserialized(ref)
    return ref


class ReferenceCounter:
    """Tracks local reference counts and cross-process borrows.

    Reference: src/ray/core_worker/reference_counter.h:44. Owned objects are
    freed when (local refs == 0) and (known borrowers == 0); borrower
    processes notify the owner on first deserialization and on release.

    Borrows are TRANSITIVE BY CONSTRUCTION: a ref forwarded B -> C makes C
    register with the OWNER directly (the owner address rides inside every
    serialized ref), so chained borrowers need no per-hop protocol — the
    piece of the reference's 2.6k-line borrow machinery that exists to
    merge borrower lists up the chain is structural here. The no-premature-
    free invariant across the forwarding window holds because the
    forwarding task's submission pins the ref (serialize_args `_pyref`)
    until the task completed, which is after the receiver registered.

    Owner-side borrows are keyed by borrower ADDRESS so borrows held by
    DEAD borrower processes can be reconciled: a borrower that dies
    without remove_borrow would otherwise pin the object forever
    (reference: reference_counter borrower-death cleanup via pubsub;
    here a slow reaper probes borrower liveness over the RPC plane)."""

    def __init__(self, cw: "CoreWorker"):
        self.cw = cw
        self.local_counts: Dict[bytes, int] = {}
        # owned objects: oid -> {borrower_address: count}
        self.borrower_counts: Dict[bytes, Dict[str, int]] = {}
        self.borrowed_owners: Dict[bytes, str] = {}  # oid -> owner address
        self._lock = threading.Lock()

    def add_local(self, ref: ObjectRef):
        with self._lock:
            self.local_counts[ref.binary()] = self.local_counts.get(ref.binary(), 0) + 1

    def remove_local(self, ref: ObjectRef):
        ref._released = True
        with self._lock:
            key = ref.binary()
            n = self.local_counts.get(key, 0) - 1
            if n > 0:
                self.local_counts[key] = n
                return
            self.local_counts.pop(key, None)
        self.cw.schedule(self._on_zero_local(ref))

    async def _on_zero_local(self, ref: ObjectRef):
        key = ref.binary()
        with self._lock:
            if self.local_counts.get(key, 0) > 0:
                return
        if self.cw.owns(ref):
            with self._lock:
                if self.borrower_counts.get(key):
                    return
            await self.cw.free_owned_object(ref.object_id())
        else:
            owner = self.borrowed_owners.pop(key, None)
            if owner:
                await self.cw.notify_owner(owner, "remove_borrow", key)

    def on_ref_deserialized(self, ref: ObjectRef):
        """First sight of a borrowed ref in this process."""
        with self._lock:
            first = ref.binary() not in self.local_counts
            self.local_counts[ref.binary()] = self.local_counts.get(ref.binary(), 0) + 1
        if not self.cw.owns(ref) and first:
            self.borrowed_owners[ref.binary()] = ref.owner_address
            self.cw.schedule(
                self.cw.notify_owner(ref.owner_address, "add_borrow", ref.binary())
            )

    # owner side
    def add_borrower(self, oid: bytes, borrower: str = ""):
        with self._lock:
            per = self.borrower_counts.setdefault(oid, {})
            per[borrower] = per.get(borrower, 0) + 1

    def remove_borrower(self, oid: bytes, borrower: str = ""):
        drop = False
        with self._lock:
            per = self.borrower_counts.get(oid)
            if per is None:
                return
            n = per.get(borrower, 0) - 1
            if n <= 0:
                per.pop(borrower, None)
            else:
                per[borrower] = n
            if not per:
                self.borrower_counts.pop(oid, None)
                drop = self.local_counts.get(oid, 0) == 0
        if drop:
            self.cw.schedule(self.cw.free_owned_object(ObjectID(oid)))

    def drop_borrower_process(self, borrower: str) -> int:
        """Reconcile every borrow held by a (dead) borrower process; frees
        objects whose last reference that was. Returns how many borrows
        were dropped."""
        to_free = []
        dropped = 0
        with self._lock:
            for oid in list(self.borrower_counts):
                per = self.borrower_counts[oid]
                if borrower in per:
                    dropped += per.pop(borrower)
                    if not per:
                        self.borrower_counts.pop(oid, None)
                        if self.local_counts.get(oid, 0) == 0:
                            to_free.append(oid)
        for oid in to_free:
            self.cw.schedule(self.cw.free_owned_object(ObjectID(oid)))
        return dropped

    def borrower_addresses(self) -> set:
        with self._lock:
            return {b for per in self.borrower_counts.values() for b in per}


class MemoryStore:
    """In-process store for small owned objects and pending futures.

    Reference: src/ray/core_worker/store_provider/memory_store/memory_store.h:48.
    Values are kept serialized (bytes, metadata); futures resolve when a task
    reply or put lands.
    """

    def __init__(self, loop: asyncio.AbstractEventLoop):
        self.loop = loop
        self.objects: Dict[bytes, Tuple[bytes, int]] = {}
        self.locations: Dict[bytes, dict] = {}  # oid -> {"daemon": addr, "node_id": hex}
        self.futures: Dict[bytes, List[asyncio.Future]] = {}

    def put(self, oid: bytes, data: bytes, meta: int):
        self.objects[oid] = (data, meta)
        for fut in self.futures.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    def set_location(self, oid: bytes, location: dict):
        self.locations[oid] = location
        for fut in self.futures.pop(oid, []):
            if not fut.done():
                fut.set_result(True)

    def fail(self, oid: bytes, exc: Exception):
        data = ser.serialize(exc).to_bytes()
        self.put(oid, data, META_ERROR)

    def contains(self, oid: bytes) -> bool:
        return oid in self.objects or oid in self.locations

    def wait_future(self, oid: bytes) -> asyncio.Future:
        fut = self.loop.create_future()
        if self.contains(oid):
            fut.set_result(True)
        else:
            self.futures.setdefault(oid, []).append(fut)
        return fut

    def delete(self, oid: bytes):
        self.objects.pop(oid, None)
        self.locations.pop(oid, None)


class StreamState:
    """Owner-side state of one streaming-generator task (reference:
    src/ray/core_worker/task_manager.h:88 ObjectRefStream).

    The executor reports items strictly in order (it awaits each report ack),
    so `produced` is a contiguous count. `consumed` advances as the user's
    iterator takes refs; the executor blocks when produced - consumed exceeds
    the task's backpressure threshold."""

    __slots__ = ("task_id", "produced", "consumed", "next_read", "end",
                 "waiters", "consume_waiters", "cancelled")

    def __init__(self, task_id: bytes):
        self.task_id = task_id
        self.produced = 0
        self.consumed = 0
        self.next_read = 0
        self.end: Optional[int] = None
        self.waiters: List[asyncio.Future] = []     # item-available / end
        self.consume_waiters: List[Tuple[int, asyncio.Future]] = []
        self.cancelled = False

    def wake_all(self):
        for fut in self.waiters:
            if not fut.done():
                fut.set_result(True)
        self.waiters.clear()

    def wake_consumers(self, force: bool = False):
        keep = []
        for until, fut in self.consume_waiters:
            if force or self.consumed >= until or self.cancelled or self.end is not None:
                if not fut.done():
                    fut.set_result(True)
            else:
                keep.append((until, fut))
        self.consume_waiters = keep


class ObjectRefGenerator:
    """Iterator over the return refs of a `num_returns="streaming"` task.

    Reference: python/ray/_raylet.pyx ObjectRefGenerator. Sync iteration from
    driver threads; async iteration inside async actors. Dropping the
    generator cancels the producer and frees unconsumed items. Not
    serializable — consume it in the owning process."""

    def __init__(self, cw: "CoreWorker", task_id: bytes):
        self._cw = cw
        self._task_id = task_id

    def __iter__(self):
        return self

    def __next__(self) -> "ObjectRef":
        ref = self._cw.run_sync(self._cw.stream_next(self._task_id))
        if ref is None:
            raise StopIteration
        return ref

    def __aiter__(self):
        return self

    async def __anext__(self) -> "ObjectRef":
        ref = await self._cw.stream_next(self._task_id)
        if ref is None:
            raise StopAsyncIteration
        return ref

    def completed(self) -> bool:
        st = self._cw._streams.get(self._task_id)
        return st is None or (st.end is not None and st.next_read >= st.end)

    def __reduce__(self):
        raise TypeError(
            "ObjectRefGenerator is not serializable; iterate it in the "
            "process that created it"
        )

    def __del__(self):
        cw = self._cw
        if cw is not None and not cw._closed and self._task_id in cw._streams:
            try:
                cw.schedule(cw.stream_drop(self._task_id))
            except Exception:  # noqa: BLE001 — interpreter shutdown
                pass


class _ActorRestartedWhileQueued(Exception):
    """Raised out of _await_push_turn when the actor's incarnation advanced
    while this spec was parked: it must be restamped, not pushed stale."""


class ActorHandleState:
    """Caller-side per-actor submission state (reference:
    actor_task_submitter.h:69 — ordered sequence numbers, address cache)."""

    __slots__ = ("actor_id", "seq", "address", "client", "state", "death_cause",
                 "event", "creation_keepalive", "incarnation", "ever_alive",
                 "push_queue", "pump_running", "push_next", "push_incarnation",
                 "push_waiters", "concurrent", "applied_version")

    def __init__(self, actor_id: bytes):
        self.actor_id = actor_id
        self.seq = 0
        # last applied (num_restarts, state-rank) version: state updates
        # arrive over BOTH pubsub and get_actor_info polls, whose replies
        # can reorder under load — a stale RESTARTING applied after the
        # fresh ALIVE would bump the incarnation spuriously and reset seq
        # numbering into the executor's duplicate-reply cache (found by the
        # chaos harness: two distinct calls returning one cached result)
        self.applied_version: tuple = (-1, -1)
        # push coalescing: (spec, future) entries drained by one pump task
        # into push_task_batch RPCs (reference: pipelined actor PushTask)
        self.push_queue: collections.deque = collections.deque()
        self.pump_running = False
        # in-order push release (reference: SequentialActorSubmitQueue sends
        # in sequence order): seq k+1 is never handed to the pump before k
        # was pushed or terminally failed, so the executor's reorder buffer
        # only ever spans in-flight deliveries — an args-gated predecessor
        # (upstream still computing in an actor DAG) can take arbitrarily
        # long without tripping the executor's lost-predecessor timeout.
        self.push_next = 1
        self.push_incarnation = 0
        self.push_waiters: Dict[int, asyncio.Future] = {}
        # async/threaded/concurrency-group actor: executions overlap on the
        # worker, so replies must not be coupled into batched pushes
        self.concurrent = False
        # bumped on every ALIVE transition to a replacement worker; per-
        # incarnation seq numbering restarts at 1 (reference: restart epoch
        # in actor_task_submitter.h). The first ALIVE keeps incarnation 0 so
        # tasks submitted while the actor was still PENDING stay ordered.
        self.incarnation = 0
        self.ever_alive = False
        self.address = ""
        self.client: Optional[RpcClient] = None
        self.state = pb.ACTOR_PENDING
        self.death_cause = ""
        self.event: Optional[asyncio.Event] = None
        # Pins ObjectRefs for constructor args promoted to the object store:
        # restarts re-resolve the creation args, so these live until the
        # actor is terminally DEAD (dropping the last ref earlier would free
        # the owned object and hang the actor's __init__).
        self.creation_keepalive: list = []


class CoreWorker:
    """The runtime: owns RPC endpoints, stores, submitters, and executors."""

    def __init__(
        self,
        mode: str,
        control_address: str,
        daemon_address: str,
        store_name: str,
        node_id_hex: str,
        job_id: JobID,
        loop: asyncio.AbstractEventLoop,
        worker_id: Optional[WorkerID] = None,
    ):
        self.mode = mode
        self.loop = loop
        # resolved lazily: the loop may not be running yet; compared by
        # thread id because asyncio.get_running_loop() throws (expensively)
        # on every non-loop-thread call
        self._loop_thread_id: Optional[int] = None
        self.job_id = job_id
        self.worker_id = worker_id or WorkerID.from_random()
        self.node_id_hex = node_id_hex
        self.control_address = control_address
        self.daemon_address = daemon_address
        # store_name=None → remote-client mode (reference: Ray Client,
        # python/ray/util/client): a driver with no host shm store; object
        # reads/writes ride daemon RPCs instead of mmap. Everything else
        # (tasks, actors, ownership, PGs) is the normal driver path.
        self.store = ShmObjectStore(store_name) if store_name else None
        self.store_name = store_name
        self.control = RpcClient(control_address, name=f"{mode}->cs")
        self.daemon = RpcClient(daemon_address, name=f"{mode}->daemon")
        self.server = RpcServer(name=f"{mode}-{self.worker_id.hex()[:6]}")
        self.address: str = ""
        self.memory_store = MemoryStore(loop)
        self.ref_counter = ReferenceCounter(self)
        self.current_task_id = TaskID.for_driver(job_id)
        self._task_index = 0
        self._put_index = 0
        self._actor_index = 0
        self._lock = threading.Lock()
        # submitter state
        self._streams: Dict[bytes, StreamState] = {}
        # task-id -> {"state", "worker", "cancelled", "atask", "return_oids",
        # "spec"} for ray_tpu.cancel (reference: normal_task_submitter
        # CancelTask / actor_task_submitter queued-task cancellation)
        self._submissions: Dict[bytes, dict] = {}
        self._return_to_task: Dict[bytes, bytes] = {}
        # recovery plane (reference: object_recovery_manager.h): lineage
        # cache + per-object recovery state machine, driven by authoritative
        # death notices from the control store (see _private.recovery)
        from ray_tpu._private.recovery import ObjectRecoveryManager

        self.recovery = ObjectRecoveryManager(self)
        # external "nodes"-channel listeners (e.g. the elastic train
        # controller's resize triggers): called with every node notice
        # AFTER the worker's own handling; exceptions are swallowed so a
        # listener can never wedge recovery
        self._node_listeners: list = []
        # subscriber-side pubsub gap detection: channel -> last publish seq
        # seen (every control-store notice is stamped with _seq)
        self._channel_seq: Dict[str, Optional[int]] = {
            "nodes": None, "workers": None,
        }
        # node-table version cursor (scale plane): reconciles after a seq
        # gap — including IN-STREAM jumps from the store's bounded-backlog
        # shedding — pull get_nodes_delta(cursor) instead of the full table
        self._node_table_version = -1
        self._gap_reconcile_task = None
        # pre-gap cursor pinned at gap-detection time (the reconcile task
        # runs deferred; by then the cursor has advanced past the shed
        # window); also re-armed by gaps landing while a reconcile flies
        self._nodes_reconcile_from: Optional[int] = None
        # workers-channel version cursor: worker-death notices carry `_wv`
        # and reconcile via get_workers_delta(cursor) — the same versioned-
        # delta plane the node table rides (the legacy list_dead_workers
        # snapshot path is gone). Versions are persisted store-side, so the
        # cursor survives a control-store failover and the post-failover
        # reconcile replays exactly the missed deaths.
        self._worker_table_version = -1
        self._workers_reconcile_from: Optional[int] = None
        # granted-but-idle worker leases by scheduling key, reused by the
        # next same-shaped task (reference: normal_task_submitter lease
        # pools). Each entry: {"idle": [lease...], "waiters": deque[Future]}.
        # Released leases hand off DIRECTLY to a waiting submission —
        # parking while submissions queue at the daemon would deadlock
        # capacity behind the sweep period. Idle leases swept by
        # _lease_pool_sweep.
        self._lease_pools: Dict[tuple, dict] = {}
        # cross-thread submission handoff: driver-thread .remote() appends
        # here and wakes the loop once per burst, not once per task (each
        # call_soon_threadsafe pays a socketpair write)
        self._xthread_submits: collections.deque = collections.deque()
        self._xthread_scheduled = False
        # pipelined push batching (reference: normal_task_submitter.h:226):
        # ready specs queue per scheduling key; feeders drain the queue in
        # push_task_batch RPCs, one leased worker per feeder at a time
        self._push_queues: Dict[tuple, collections.deque] = {}
        self._push_feeders: Dict[tuple, int] = {}
        # native control-plane fast path (reference: the _raylet.pyx
        # submit_task seam): specs encode to wire msgpack in C++ on the
        # CALLER thread and ride a lock-free ring per scheduling key; the
        # feeders pop batches and ship one preassembled frame. None → the
        # pure-Python path above is the only path (no compiler, flag off).
        self._fastpath = _fp.new_engine()
        self._fp_rings: Dict[tuple, int] = {}
        self._fp_templates: Dict[tuple, int] = {}
        self._actor_states: Dict[bytes, ActorHandleState] = {}
        self._owned_actor_handles: Dict[bytes, int] = {}
        self._bg_futures: set = set()
        self._worker_clients: Dict[str, RpcClient] = {}
        self._owner_clients: Dict[str, RpcClient] = {}
        # compiled-graph channel plane: rings THIS process reads, exposed
        # for cross-node writers via rpc_chan_write (reference:
        # torch_tensor_accelerator_channel.py — remote channel endpoints)
        self._dag_channels: Dict[tuple, Any] = {}
        self._dag_channel_locks: Dict[tuple, Any] = {}
        self._dag_channel_seqs: Dict[tuple, int] = {}  # idempotency marks
        # executor state (workers only)
        self.executor: Optional["TaskExecutor"] = None
        self._function_cache: Dict[str, Any] = {}
        self._exported: set = set()
        self._inline_max = GLOBAL_CONFIG.get("inline_object_max_bytes")
        from ray_tpu._private.task_events import TaskEventBuffer

        self.task_events = TaskEventBuffer()
        self._telemetry_task: Optional[asyncio.Task] = None
        self._closed = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self):
        self.server.register_service(self)
        self.address = await self.server.start()
        await self.control.connect()
        await self.daemon.connect()
        self.control.subscribe_channel("actors", self._on_actor_update)
        await self.control.call("subscribe", {"channel": "actors"})
        # authoritative failure notices (reference: GCS node/worker-failure
        # pubsub): node deaths drive the recovery manager — lost locations
        # are poisoned and recovery starts on the NOTICE, not on a getter
        # tripping over a stale location; worker deaths reconcile borrows
        # immediately instead of waiting out the reaper's probe cycle
        self.control.subscribe_channel("nodes", self._on_node_notice)
        self.control.subscribe_channel("workers", self._on_worker_notice)
        await self._subscribe_notices()
        # a restarted control store loses server-side subscription state;
        # on resubscribe the reply seq is compared against the last notice
        # we saw — a mismatch means deaths were published while we were
        # away (control-store failover window) and triggers a full
        # node/worker table reconcile instead of trusting the stream
        self.control.on_reconnect(
            lambda: self.control.call("subscribe", {"channel": "actors"})
        )
        self.control.on_reconnect(
            lambda: self._subscribe_notices(resync=True)
        )
        # announce this process's RPC address so owners' borrow reapers can
        # distinguish authoritative death from mere unresponsiveness
        # (reference: the GCS workers table; see _borrow_reaper_loop)
        await self._register_worker_liveness()
        self.control.on_reconnect(self._register_worker_liveness)
        self._telemetry_task = spawn(self._telemetry_loop())
        self._lease_sweep_task = spawn(self._lease_pool_sweep())
        self._borrow_reaper_task = spawn(self._borrow_reaper_loop())
        if self.mode == MODE_WORKER:
            # fate-share with the node daemon (reference: workers die with
            # their raylet — agent_manager/worker fate-sharing). An orphaned
            # worker that outlives its daemon would keep accepting pushes
            # and store returns into a store no daemon serves.
            self._fate_task = spawn(self._daemon_fate_watch())

    async def rpc_ping(self, conn_id: int, payload: dict) -> dict:
        return {"ok": True}

    async def rpc_dump_flight_recorder(self, conn_id: int, payload) -> dict:
        return flight_recorder.dump()

    async def rpc_chaos_set(self, conn_id: int, payload: dict) -> dict:
        """Chaos scenario hook (testing only): apply chaos/testing config
        flags to this worker/driver process at runtime."""
        from ray_tpu._private import chaos as _chaos

        GLOBAL_CONFIG.apply_system_config(payload.get("config", {}))
        _chaos.reset()
        return {"ok": True, "role": _chaos.role()}

    def _note_channel_seq(self, channel: str, message: dict):
        seq = message.get("_seq")
        if seq is not None:
            last = self._channel_seq.get(channel)
            if last is not None and seq > last + 1:
                # in-stream publish gap: the store shed notices to us
                # (bounded per-subscriber backlog) — death records may be
                # among the missing, so reconcile now, not at reconnect
                logger.info("%s-channel in-stream gap (%d -> %d); "
                            "reconciling death records", channel, last, seq)
                if channel == "nodes":
                    # pin the reconcile cursor to the PRE-gap version NOW:
                    # the reconcile task runs deferred, and by then the
                    # gap-revealing notice's _v (past the shed window) has
                    # already advanced _node_table_version — a pull from
                    # there would replay nothing
                    if (self._nodes_reconcile_from is None
                            or self._node_table_version
                            < self._nodes_reconcile_from):
                        self._nodes_reconcile_from = self._node_table_version
                elif channel == "workers":
                    # same pre-gap floor pinning for the workers cursor
                    if (self._workers_reconcile_from is None
                            or self._worker_table_version
                            < self._workers_reconcile_from):
                        self._workers_reconcile_from = \
                            self._worker_table_version
                self._spawn_gap_reconcile()
            self._channel_seq[channel] = seq if last is None else max(last, seq)

    def _spawn_gap_reconcile(self) -> None:
        if (self._gap_reconcile_task is None
                or self._gap_reconcile_task.done()):
            self._gap_reconcile_task = spawn(self._reconcile_death_records())

    async def _subscribe_notices(self, resync: bool = False):
        """Subscribe to the node/worker death channels with gap detection:
        the subscribe reply carries each channel's current publish seq. On
        a reconnect whose seq doesn't match the last notice seen, a death
        published during the outage (control-store failover window) was
        silently lost — run a full node/worker table reconcile so borrows
        and recovery still trigger."""
        gap = False
        pending: Dict[str, int] = {}
        for channel in ("nodes", "workers"):
            # capture the cursor BEFORE the subscribe lands: the instant
            # the store-side subscription exists, stream notices can
            # max-advance the cursor past the missed window, and both the
            # version comparison and the reconcile's from-cursor pull
            # would go blind to the gap
            cursor = (self._node_table_version if channel == "nodes"
                      else self._worker_table_version)
            reply = await self.control.call("subscribe", {"channel": channel})
            server_seq = reply.get("seq")
            if server_seq is None:
                continue
            last = self._channel_seq.get(channel)
            # the ephemeral publish seq alone is NOT a sufficient
            # same-stream check: a failed-over store restarts its seq
            # counters, and if it published exactly as many notices as we
            # had seen, the counters COINCIDE while the content differs.
            # The persisted version cursor (resumed across failovers)
            # breaks the tie.
            version_moved = (reply.get("version") is not None
                             and reply["version"] != cursor)
            if resync and (server_seq != last or version_moved):
                gap = True
                if channel == "nodes":
                    if (self._nodes_reconcile_from is None
                            or cursor < self._nodes_reconcile_from):
                        self._nodes_reconcile_from = cursor
                else:
                    if (self._workers_reconcile_from is None
                            or cursor < self._workers_reconcile_from):
                        self._workers_reconcile_from = cursor
                logger.info(
                    "%s-channel gap detected (last seen %s, server at %s; "
                    "version %s vs cursor %s)",
                    channel, last, server_seq, reply.get("version"), cursor)
            pending[channel] = server_seq
        if resync:
            # failover telemetry: outage as this subscriber saw it, and
            # whether the reconnect landed on a NEW store incarnation (the
            # seq mismatch) rather than a TCP blip to the same one
            from ray_tpu._private import store_ha

            outage = None
            if self.control.last_disconnect_ts is not None:
                outage = time.monotonic() - self.control.last_disconnect_ts
            store_ha.record_store_reconnect(
                "driver" if self.mode == MODE_DRIVER else "worker",
                outage, new_incarnation=gap)
        if gap and not await self._reconcile_death_records():
            # reconcile failed (store still mid-failover): keep the OLD
            # last-seen seqs so the next reconnect re-detects this gap —
            # advancing them now would mark the missed window as seen
            return
        self._channel_seq.update(pending)

    async def _reconcile_death_records(self) -> bool:
        """Replay the authoritative node/worker death tables through the
        same notice handlers the pubsub stream feeds (both are idempotent):
        nothing recorded during a subscription gap stays unseen. Loops
        while fresh gap signals land mid-flight — a reply generated before
        a second shed cannot contain it, and dropping that signal on the
        single-flight guard would lose the window permanently."""
        while True:
            floor = self._nodes_reconcile_from
            self._nodes_reconcile_from = None
            wfloor = self._workers_reconcile_from
            self._workers_reconcile_from = None
            try:
                if GLOBAL_CONFIG.get("node_table_delta_sync"):
                    # cursor pull: exactly the node mutations published
                    # since the pre-gap cursor (same wires the stream
                    # carries, expected-death replica maps included) —
                    # O(missed), not O(nodes)
                    reply = await self.control.call(
                        "get_nodes_delta",
                        {"cursor": floor if floor is not None
                         else self._node_table_version})
                    nodes = reply.get("updates") or reply.get("nodes") or []
                    version = reply.get("version")
                else:
                    nodes = (await self.control.call(
                        "get_all_nodes", {})).get("nodes", [])
                    version = None
                for nw in nodes:
                    self._apply_node_notice(nw)
                if version is not None:
                    # authoritative assignment AFTER the apply: brings the
                    # cursor back DOWN after a store restart's counter
                    # reset (the stream path's monotonic guard never would)
                    self._node_table_version = version
                # workers-channel cursor pull: the deaths published since
                # the pre-gap cursor, replayed through the stream handler
                # (idempotent; the _wv guard drops anything already seen)
                wreply = await self.control.call(
                    "get_workers_delta",
                    {"cursor": wfloor if wfloor is not None
                     else self._worker_table_version})
                dead = wreply.get("updates") or wreply.get("workers") or []
                for rec in dead:
                    self._apply_worker_notice(rec)
                wversion = wreply.get("version")
                if wversion is not None:
                    self._worker_table_version = wversion
                logger.info(
                    "reconciled death records after pubsub gap: %d node(s), "
                    "%d dead worker record(s)", len(nodes), len(dead))
            except Exception:  # noqa: BLE001 — control store mid-failover;
                # re-arm the pre-gap floors (stream notices will advance
                # the live cursors past the missed window, so a later
                # from-cursor pull would replay nothing) and let the next
                # reconnect/gap signal retry from them
                if floor is not None and (
                        self._nodes_reconcile_from is None
                        or floor < self._nodes_reconcile_from):
                    self._nodes_reconcile_from = floor
                if wfloor is not None and (
                        self._workers_reconcile_from is None
                        or wfloor < self._workers_reconcile_from):
                    self._workers_reconcile_from = wfloor
                logger.warning("death-record reconcile failed",
                               exc_info=True)
                return False
            if (self._nodes_reconcile_from is None
                    and self._workers_reconcile_from is None):
                return True

    def _on_node_notice(self, message: dict):
        """Control-store "nodes" pubsub: a DEAD notice is the authoritative
        recovery trigger — poison lost locations (or fail them over to the
        drain replicas carried on an EXPECTED death), kick eager recovery,
        and drop pooled leases/clients aimed at the dead daemon. A DRAINING
        notice reroutes future submissions away immediately so no task
        retry is burned against a node that will refuse the lease."""
        self._note_channel_seq("nodes", message)
        ver = message.get("_v")
        if ver is not None:
            if ver <= self._node_table_version:
                # stale replay: the store's coalescing window can deliver
                # a notice AFTER the reconcile reply that already covered
                # it. A restarted store's lower counter is reset by the
                # reconcile's authoritative post-apply assignment.
                return
            self._node_table_version = ver
        self._apply_node_notice(message)

    def _apply_node_notice(self, message: dict):
        self._fan_out_node_notice(message)
        state = message.get("state")
        daemon_addr = message.get("address", "")
        if state in (pb.NODE_DRAINING, pb.NODE_DEAD):
            flight_recorder.record(
                "node", state,
                node=(message.get("node_id") or b"").hex()[:12],
                expected=(message.get("death") or {}).get("expected"))
        if state == pb.NODE_DRAINING:
            if daemon_addr:
                # cached leases on the draining node would be refused (or
                # worse, accepted and then die at the deadline): reroute new
                # work now, let in-flight tasks finish there
                self._drop_pooled_leases_from(daemon_addr)
            return
        if state != pb.NODE_DEAD:
            return
        node_hex = NodeID(message["node_id"]).hex()
        death = message.get("death") or {}
        self.recovery.on_node_death(
            node_hex, daemon_addr,
            reason=death.get("reason", ""),
            expected=death.get("expected", False),
            replicas=message.get("replicas"),
        )
        if daemon_addr:
            # a cached lease on the dead node would push the next task (or a
            # recovery re-execution) into a store no daemon serves
            self._drop_pooled_leases_from(daemon_addr)

    def add_node_listener(self, cb) -> None:
        """Register a callback for every "nodes" pubsub notice (dict wire
        form). Used by the elastic train controller: a DRAINING notice is
        its shrink trigger, a registered-ALIVE notice its regrow trigger —
        event-driven instead of burning a node-table poll per tick."""
        self._node_listeners.append(cb)

    def remove_node_listener(self, cb) -> None:
        try:
            self._node_listeners.remove(cb)
        except ValueError:
            pass

    def _fan_out_node_notice(self, message: dict):
        for cb in list(self._node_listeners):
            try:
                cb(message)
            except Exception:  # noqa: BLE001 — listeners must never wedge
                logger.warning("node-notice listener failed", exc_info=True)

    def _on_worker_notice(self, message: dict):
        """Control-store "workers" pubsub: a recorded worker/driver death
        reconciles its borrows NOW (the probe-based reaper loop stays as
        the fallback for missed pushes)."""
        self._note_channel_seq("workers", message)
        ver = message.get("_wv")
        if ver is not None:
            if ver <= self._worker_table_version:
                # stale replay: the store's coalescing window can deliver a
                # notice AFTER the reconcile reply that already covered it.
                # A restarted-unpersisted store's lower counter is reset by
                # the reconcile's authoritative post-apply assignment.
                return
            self._worker_table_version = ver
        self._apply_worker_notice(message)

    def _apply_worker_notice(self, message: dict):
        ver = message.get("_wv")
        if ver is not None:
            self._worker_table_version = max(
                self._worker_table_version, ver)
        if not message.get("dead"):
            return
        addr = message.get("address", "")
        if not addr:
            return
        flight_recorder.record("worker", "death_notice", address=addr,
                               reason=message.get("reason") or "")
        dropped = self.ref_counter.drop_borrower_process(addr)
        if dropped:
            logger.info(
                "reaped %d borrow(s) held by dead borrower %s "
                "(authoritative death notice: %s)", dropped, addr,
                message.get("reason") or "unspecified")
        dead = self._owner_clients.pop(addr, None)
        if dead is not None:
            spawn(dead.close())

    async def _register_worker_liveness(self):
        try:
            await self.control.call("register_worker", {
                "worker_id": self.worker_id.binary(),
                "address": self.address,
                "node_id": self.node_id_hex,
                "job_id": self.job_id.binary(),
                "mode": self.mode,
            }, timeout=10)
        except Exception:  # noqa: BLE001 — records are best-effort
            logger.debug("worker liveness registration failed", exc_info=True)

    async def _borrow_reaper_loop(self):
        """Owner-side borrower-death reconciliation (reference:
        reference_counter.h borrower cleanup, driven there by pubsub worker-
        failure notices): probe each borrower address; failed probes only
        TRIGGER a lookup of the control store's authoritative worker/node
        death records — borrows are dropped solely on a recorded death,
        never on timeouts alone. A borrower that is alive but unresponsive
        (GIL-bound native call, long compile, transient partition) keeps
        its borrows indefinitely (ADVICE r5 #2). Probes are cheap (one ping
        per distinct borrower per period) and only run while borrows
        exist."""
        period = GLOBAL_CONFIG.get("borrow_reaper_period_s")
        strikes = GLOBAL_CONFIG.get("borrow_reaper_strikes")
        failures: Dict[str, int] = {}
        while not self._closed:
            await asyncio.sleep(period)
            live = self.ref_counter.borrower_addresses()
            for addr in list(failures):
                if addr not in live:
                    failures.pop(addr, None)
            for addr in live:
                if self._closed:
                    return
                try:
                    client = await self._owner_client(addr)
                    await client.call("ping", {}, timeout=5)
                    failures.pop(addr, None)
                    continue
                except Exception:  # noqa: BLE001 — maybe gone, maybe slow
                    # One missed ping is NOT death: probe a few times before
                    # even bothering the control store.
                    failures[addr] = failures.get(addr, 0) + 1
                    if failures[addr] < strikes:
                        continue
                # Unreachable for `strikes` consecutive probes: consult the
                # authoritative death records. Free ONLY on a recorded
                # worker/node/driver death — an unknown or merely silent
                # address keeps its borrows (leaking beats premature free).
                try:
                    verdict = await self.control.call(
                        "check_worker_liveness", {"address": addr},
                        timeout=10)
                except Exception:  # noqa: BLE001 — control store blip
                    continue
                if not verdict.get("dead"):
                    # alive-but-stalled (or not yet recorded): keep probing
                    # from a clean slate rather than hammering the lookup
                    failures[addr] = 0
                    continue
                failures.pop(addr, None)
                dropped = self.ref_counter.drop_borrower_process(addr)
                if dropped:
                    logger.info(
                        "reaped %d borrow(s) held by dead borrower %s "
                        "(control store confirmed death)", dropped, addr)
                # only THEN retire the pooled client (closing it earlier
                # would fail in-flight RPCs to a live peer)
                dead = self._owner_clients.pop(addr, None)
                if dead is not None:
                    spawn(dead.close())

    async def _telemetry_loop(self):
        """Flush buffered task events (with their drop accounting) to the
        control store, and ship metric DELTAS node-locally: the daemon
        pre-aggregates every worker's series into one per-node set (with a
        cardinality cap) before the control store sees them — at 1000 nodes
        the store accumulates per-node aggregates, not per-worker snapshots
        (reference: task_event_buffer.h periodic GCS flush; the per-node
        metrics agent)."""
        from ray_tpu.util import metrics as metrics_mod

        period = GLOBAL_CONFIG.get("telemetry_flush_period_s")
        # Exactly-once delta shipping: a taken delta batch is FROZEN with a
        # sequence number and re-sent verbatim until acked — receivers
        # dedup by (reporter, seq), so an applied-but-unacked flush (reply
        # lost to a timeout OR a dropped connection) cannot double-count.
        # The destination is fixed for the process (the daemon when one
        # exists, else the store): falling back across destinations on a
        # connection error would escape the per-reporter dedup domain and
        # double-count exactly the batches the machinery exists to protect.
        # An idle interval still sends an EMPTY keepalive report — the
        # store's stale-reporter prune must never collect a live
        # reporter's accumulated totals.
        pending: Optional[list] = None  # [seq, series]
        seq = 0
        while not self._closed:
            await asyncio.sleep(period)
            events, dropped = self.task_events.drain()
            try:
                if events or dropped:
                    await self.control.call(
                        "report_task_events",
                        {"events": events, "dropped": dropped}, timeout=10)
                    events, dropped = [], 0
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — telemetry must never kill the worker
                # control store blip: keep the batch for the next flush
                self.task_events.requeue(events, dropped)
            if pending is None:
                snap = metrics_mod.take_delta()
                if snap:
                    seq += 1
                    pending = [seq, snap]
            payload = {"worker_id": self.worker_id.binary(),
                       "delta": True,
                       "metrics": pending[1] if pending else [],
                       **({"seq": pending[0]} if pending else {})}
            daemon = getattr(self, "daemon", None)
            try:
                if daemon is not None:
                    await daemon.call("report_metrics", payload, timeout=10)
                else:
                    await self.control.call(
                        "report_metrics", payload, timeout=10)
                pending = None
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — retry the SAME frozen batch
                # (same seq) next tick; workers fate-share with the daemon,
                # so a dead destination resolves itself shortly
                pass

    async def _daemon_fate_watch(self):
        """Exit the worker process when its daemon is gone (reference:
        raylet↔worker fate sharing via the IPC socket). Wall-clock window,
        not a probe count: under CPU starvation a loaded daemon can miss
        several short probes while being perfectly alive — the bar matches
        the cluster's own node-death declaration (health_check_timeout_s)."""
        period = GLOBAL_CONFIG.get("health_check_period_s")
        window = GLOBAL_CONFIG.get("health_check_timeout_s") * 1.5
        first_fail = None
        while not self._closed:
            await asyncio.sleep(period)
            try:
                await self.daemon.call("ping", {}, timeout=period * 4)
                first_fail = None
            except Exception:  # noqa: BLE001 — daemon unreachable
                now = time.monotonic()
                if first_fail is None:
                    first_fail = now
                elif now - first_fail >= window:
                    logger.error(
                        "node daemon unreachable for %.0fs; worker exiting "
                        "(fate-sharing)", now - first_fail)
                    os._exit(1)

    async def close(self):
        self._closed = True
        if getattr(self, "_fate_task", None) is not None:
            self._fate_task.cancel()
        if self._telemetry_task is not None:
            self._telemetry_task.cancel()
        if getattr(self, "_lease_sweep_task", None) is not None:
            self._lease_sweep_task.cancel()
        if getattr(self, "_borrow_reaper_task", None) is not None:
            self._borrow_reaper_task.cancel()
        # return every cached lease so the daemons free the capacity now
        # (snapshot: an in-flight submit can insert a pool key mid-await).
        # One shared deadline bounds the whole sweep: against live daemons
        # each return is a millisecond call, and a closing worker must not
        # burn a retry chain per lease on daemons that are already gone —
        # they reclaim leases from the recorded worker death anyway.
        from ray_tpu._private.retry import deadline_from_timeout

        sweep_deadline = deadline_from_timeout(1.5)
        for pool in list(self._lease_pools.values()):
            for lease in list(pool["idle"]):
                if time.monotonic() >= sweep_deadline:
                    break
                try:
                    await self._return_lease_quiet(
                        lease["daemon_address"], lease["lease_id"],
                        deadline=sweep_deadline)
                except Exception:  # noqa: BLE001
                    pass
        self._lease_pools.clear()
        await self.server.stop()
        await self.control.close()
        await self.daemon.close()
        for c in list(self._worker_clients.values()) + list(self._owner_clients.values()):
            await c.close()
        for st in self._actor_states.values():
            if st.client:
                await st.client.close()
        if self.store is not None:
            self.store.close()

    def schedule(self, coro) -> None:
        """Schedule a coroutine from any thread; pins the task (the loop keeps
        only weak task refs — see aio.spawn)."""
        if self._closed:
            coro.close()
            return
        if self._loop_running_here():
            spawn(coro)
        else:
            fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
            self._bg_futures.add(fut)
            fut.add_done_callback(self._bg_futures.discard)

    def _loop_running_here(self) -> bool:
        tid = self._loop_thread_id
        if tid is None:
            try:
                running = asyncio.get_running_loop() is self.loop
            except RuntimeError:
                return False
            if running:
                self._loop_thread_id = threading.get_ident()
            return running
        return tid == threading.get_ident()

    def run_sync(self, coro, timeout: Optional[float] = None):
        """Bridge a coroutine to sync callers (driver public API)."""
        fut = asyncio.run_coroutine_threadsafe(coro, self.loop)
        return fut.result(timeout)

    def owns(self, ref: ObjectRef) -> bool:
        return ref._owner_worker_id == self.worker_id.binary()

    def next_task_id(self) -> TaskID:
        with self._lock:
            self._task_index += 1
            return TaskID.for_task(self.job_id, self.current_task_id, self._task_index)

    # ------------------------------------------------------------------
    # function export/fetch (reference: python/ray/_private/function_manager.py)
    # ------------------------------------------------------------------

    async def export_function(self, key: str, obj: Any):
        if key in self._exported:
            return
        blob = cloudpickle.dumps(obj)
        await self.control.call(
            "kv_put",
            {"ns": "fn", "key": key.encode(), "value": blob, "overwrite": False},
        )
        self._exported.add(key)

    async def fetch_function(self, key: str) -> Any:
        if key in self._function_cache:
            return self._function_cache[key]
        deadline = time.monotonic() + 30
        while True:
            reply = await self.control.call("kv_get", {"ns": "fn", "key": key.encode()})
            if reply["value"] is not None:
                fn = cloudpickle.loads(reply["value"])
                self._function_cache[key] = fn
                return fn
            if time.monotonic() > deadline:
                raise RayTpuError(f"function {key} never appeared in the control store")
            await asyncio.sleep(0.05)

    # ------------------------------------------------------------------
    # put / get / wait
    # ------------------------------------------------------------------

    async def put_object(self, value: Any) -> ObjectRef:
        with self._lock:
            self._put_index += 1
            oid = ObjectID.for_put(self.current_task_id, self._put_index)
        sobj = ser.serialize(value)
        ref = ObjectRef(oid, self.address, self.worker_id.binary())
        if sobj.total_bytes <= self._inline_max:
            self.memory_store.put(oid.binary(), sobj.to_bytes(), META_NORMAL)
        elif self.store is None:
            # remote-client mode: ship the bytes to the adopted daemon's
            # store over RPC (chunked), then record it as the home location
            await self._remote_put(oid, sobj)
            self.memory_store.set_location(
                oid.binary(),
                {"daemon": self.daemon_address, "node_id": self.node_id_hex},
            )
        else:
            view = await self._create_with_spill(oid, sobj.total_bytes)
            sobj.write_into(view)
            view.release()
            self.store.seal(oid)
            self.memory_store.set_location(
                oid.binary(),
                {"daemon": self.daemon_address, "node_id": self.node_id_hex, "local": True},
            )
        return ref

    async def _remote_put(self, oid: ObjectID, sobj: "ser.SerializedObject"):
        """Write a large object into the adopted daemon's store over RPC
        (remote-client mode; reference: ray client server-side puts)."""
        data = sobj.to_bytes()
        reply = await self.daemon.call("create_object", {
            "object_id": oid.binary(), "size": len(data), "meta": META_NORMAL,
        }, timeout=60)
        if not reply.get("ok"):
            raise ObjectStoreFullError(reply.get("error", "create_object failed"))
        if reply.get("exists"):
            return
        chunk = GLOBAL_CONFIG.get("object_chunk_bytes")
        sem = asyncio.Semaphore(8)

        async def write(off: int):
            async with sem:
                r = await self.daemon.call("write_chunk", {
                    "object_id": oid.binary(), "offset": off,
                    "data": data[off:off + chunk],
                }, timeout=60)
                if not r.get("ok"):
                    raise RayTpuError(
                        f"remote put failed mid-transfer: {r.get('error')}"
                    )

        await asyncio.gather(*[write(o) for o in range(0, len(data), chunk)])
        r = await self.daemon.call("seal_object", {"object_id": oid.binary()},
                                   timeout=30)
        if not r.get("ok"):
            # e.g. the daemon swept this create as stale mid-stall: the
            # object does not exist; failing the put here beats handing out
            # a ref that can never resolve
            raise RayTpuError(f"remote put failed to seal: {r.get('error')}")

    async def get_objects(self, refs: Sequence[ObjectRef],
                          timeout: Optional[float] = None) -> List[Any]:
        return list(
            await asyncio.gather(*[self._get_one(r, timeout) for r in refs])
        )

    async def get_async(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        return await self._get_one(ref, timeout)

    async def _get_one(self, ref: ObjectRef, timeout: Optional[float] = None) -> Any:
        oid = ref.binary()
        deadline = None if timeout is None else time.monotonic() + timeout
        if self.owns(ref):
            while True:
                fut = self.memory_store.wait_future(oid)
                await self._await_deadline(fut, deadline, ref)
                if oid in self.memory_store.objects:
                    data, meta = self.memory_store.objects[oid]
                    return self._materialize(data, meta, copy_buffers=False)
                location = self.memory_store.locations.get(oid)
                if location is None:
                    # a concurrent reconstruction cleared the stale location;
                    # loop back and wait for the fresh execution to land
                    await asyncio.sleep(0)
                    continue
                try:
                    self.recovery.note_fetching(oid)
                    value = await self._read_store_object(ref, location, deadline)
                    self.recovery.note_local(oid)
                    return value
                except ObjectLostError:
                    # the store node died with the object; recompute from
                    # lineage and retry with the fresh location (bounded by
                    # the caller's deadline — recovery continues regardless)
                    if not await self._bounded(
                        self.recovery.recover(oid, location.get("node_id")),
                        deadline, ref, "reconstructing",
                    ):
                        raise
        # borrowed: ask the owner (bounded by the caller's deadline)
        return await self._fetch_via_owner(ref, deadline, copy_buffers=False)

    async def _bounded(self, coro, deadline, ref: ObjectRef, what: str):
        """Await `coro`, raising GetTimeoutError past `deadline`. The work
        itself is shielded: a caller timeout never aborts owner-side
        recovery or an in-flight owner RPC."""
        if deadline is None:
            return await coro
        try:
            return await asyncio.wait_for(
                asyncio.shield(spawn(coro)),
                max(0.0, deadline - time.monotonic()),
            )
        except asyncio.TimeoutError:
            raise GetTimeoutError(f"get() timed out {what} {ref.hex()}") from None

    async def _fetch_via_owner(self, ref: ObjectRef, deadline,
                               copy_buffers: bool) -> Any:
        """Borrower-side fetch: ask the owner for the value or its location,
        read the store copy, and on a lost store node ask the owner to
        reconstruct from lineage — all bounded by the caller's deadline
        (owner-side recovery keeps going past a caller timeout)."""
        oid = ref.binary()
        reconstruct_tries = 0
        while True:
            reply = await self._bounded(
                self._call_owner(ref, "get_object", {"object_id": oid}),
                deadline, ref, "waiting for",
            )
            if reply.get("error"):
                raise ObjectLostError(ref.hex(), reply["error"])
            if "data" in reply and reply["data"] is not None:
                return self._materialize(reply["data"], reply["meta"],
                                         copy_buffers=copy_buffers)
            location = reply["location"]
            try:
                return await self._read_store_object(ref, location, deadline)
            except ObjectLostError:
                # ask the owner to rebuild it from lineage, then re-fetch
                reconstruct_tries += 1
                if reconstruct_tries > GLOBAL_CONFIG.get("max_lineage_reconstructions"):
                    raise
                rec = await self._bounded(
                    self._call_owner(ref, "reconstruct_object", {
                        "object_id": oid,
                        "failed_node": location.get("node_id"),
                    }),
                    deadline, ref, "reconstructing",
                )
                if not rec.get("ok"):
                    raise

    async def _await_deadline(self, fut, deadline, ref):
        if deadline is None or fut.done():
            await fut
            return
        # leaner than asyncio.wait_for: one timer handle, no nested timeout
        # context — this sits on the per-ref get() hot path. The future is
        # per-caller (memory_store.wait_future hands out fresh ones), so
        # cancelling it on timeout affects no other getter.
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            fut.cancel()
            raise GetTimeoutError(
                f"get() timed out waiting for {ref.hex()}")
        timer = self.loop.call_later(remaining, fut.cancel)
        try:
            await fut
        except asyncio.CancelledError:
            if fut.cancelled() and time.monotonic() >= deadline - 0.001:
                raise GetTimeoutError(
                    f"get() timed out waiting for {ref.hex()}") from None
            raise
        finally:
            timer.cancel()

    async def _read_store_object(self, ref: ObjectRef, location: dict, deadline) -> Any:
        if self.store is None:
            return await self._remote_read(ref, location, deadline)
        oid = ref.object_id()
        is_local = location.get("node_id") == self.node_id_hex
        # authoritative death notice poisoned this location (see
        # recovery.on_node_death): a still-valid LOCAL copy may exist in
        # this node's store, but a remote pull from the dead daemon would
        # only burn the deadline — fail over to recovery immediately
        if location.get("dead") and not is_local and not self.store.contains(oid):
            why = location.get("death_reason") or "authoritative death record"
            raise ObjectLostError(
                ref.hex(),
                f"store node {location.get('node_id', '')[:8]} is dead "
                f"({why})")
        pulled = False
        # Pin-or-recover loop: between any check and the pinning get() the
        # spill loop may write the object to disk and delete it from shm, so
        # a one-shot contains()/restore decision can hang forever. Each miss
        # retries the applicable recovery (remote pull / spill restore) until
        # the pin lands or the deadline passes.
        last_restore = 0.0
        failed_restores = 0
        while True:
            res = self.store.get(oid)  # pins on success
            if res is not None:
                break
            if deadline is not None and time.monotonic() >= deadline:
                raise GetTimeoutError(f"get() timed out materializing {ref.hex()}")
            if not is_local and not pulled:
                reply = await self.daemon.call(
                    "pull_object",
                    {"object_id": oid.binary(), "from_address": location["daemon"]},
                    timeout=None if deadline is None else max(0.1, deadline - time.monotonic()),
                )
                if not reply.get("ok"):
                    raise ObjectLostError(ref.hex(), reply.get("error", "pull failed"))
                pulled = True
                continue
            # local (or already pulled): possibly spilled to disk. Throttle
            # the restore RPC — the common miss is a producer mid-seal, which
            # the cheap local shm poll below picks up without daemon traffic.
            now = time.monotonic()
            if now - last_restore > 0.2:
                last_restore = now
                reply = await self.daemon.call(
                    "restore_object", {"object_id": oid.binary()}, timeout=30
                )
                if reply.get("ok"):
                    continue
                failed_restores += 1
                # not in shm, not spilled, and given ~5s of mid-seal grace:
                # the object is gone (evicted or never landed) — surface it
                # so the owner's lineage reconstruction can recompute it
                if failed_restores >= 25:
                    raise ObjectLostError(
                        ref.hex(), "object missing from local store and spill dir"
                    )
            await asyncio.sleep(0.002)
        view, meta = res
        if meta == META_ERROR:
            try:
                raise self._deserialize_error(bytes(view))
            finally:
                self.store.release(oid)
        # Zero-copy: buffers alias shm; the store pin is released when the
        # last array aliasing the segment is GC'd (ser._Pin finalizer).
        return ser.deserialize(
            view, copy_buffers=False,
            release=functools.partial(self.store.release, oid),
        )

    async def _remote_read(self, ref: ObjectRef, location: dict, deadline) -> Any:
        """Remote-client mode: materialize a store-resident object by asking
        the adopted daemon to pull it locally, then fetching its bytes in
        chunks over RPC (no shm mapping on this side)."""
        oid = ref.object_id()

        def remaining(default: float) -> float:
            if deadline is None:
                return default
            left = deadline - time.monotonic()
            if left <= 0:
                raise GetTimeoutError(
                    f"get() timed out materializing {ref.hex()} remotely"
                )
            return min(default, max(0.1, left))

        reply = await self.daemon.call(
            "pull_object",
            {"object_id": oid.binary(), "from_address": location["daemon"]},
            timeout=None if deadline is None else remaining(1e9),
        )
        if not reply.get("ok"):
            raise ObjectLostError(ref.hex(), reply.get("error", "pull failed"))
        info = await self.daemon.call(
            "fetch_object_info", {"object_id": oid.binary()},
            timeout=remaining(30),
        )
        if not info.get("found"):
            raise ObjectLostError(ref.hex(), "object vanished after pull")
        size, meta = info["size"], info["metadata"]
        buf = bytearray(size)
        from ray_tpu.runtime.transfer import fetch_chunks

        await fetch_chunks(
            self.daemon.call, oid.binary(), size, buf,
            chunk_bytes=GLOBAL_CONFIG.get("object_chunk_bytes"),
            timeout_for=remaining,
            missing_error=lambda: ObjectLostError(
                ref.hex(), "object vanished mid-read"),
        )
        if meta == META_ERROR:
            raise self._deserialize_error(bytes(buf))
        return ser.deserialize(bytes(buf), copy_buffers=True)

    def _materialize(self, data: bytes, meta: int, copy_buffers: bool) -> Any:
        if meta == META_ERROR:
            raise self._deserialize_error(data)
        return ser.deserialize(data, copy_buffers=copy_buffers)

    def _deserialize_error(self, data) -> Exception:
        try:
            exc = ser.deserialize(data, copy_buffers=True)
            if isinstance(exc, BaseException):
                return exc
            return RayTpuError(str(exc))
        except Exception:  # noqa: BLE001
            return RayTpuError("task failed and its error could not be deserialized")

    async def wait_objects(self, refs: Sequence[ObjectRef], num_returns: int,
                           timeout: Optional[float]) -> Tuple[List[ObjectRef], List[ObjectRef]]:
        pending = {r: None for r in refs}
        ready: List[ObjectRef] = []
        deadline = None if timeout is None else time.monotonic() + timeout

        async def ready_one(r: ObjectRef):
            if self.owns(r):
                await self.memory_store.wait_future(r.binary())
            else:
                await self._call_owner(r, "wait_object", {"object_id": r.binary()})
            return r

        tasks = {spawn(ready_one(r)): r for r in pending}
        try:
            while tasks and len(ready) < num_returns:
                budget = None if deadline is None else max(0.0, deadline - time.monotonic())
                done, _ = await asyncio.wait(
                    tasks, timeout=budget, return_when=asyncio.FIRST_COMPLETED
                )
                if not done:
                    break
                for d in done:
                    r = tasks.pop(d)
                    # retrieve the exception unconditionally (else asyncio
                    # logs "Task exception was never retrieved" for errored
                    # waiters completing past the cap), then cap at
                    # num_returns — ray.wait returns at most num_returns
                    # ready refs; the rest stay in the not-ready list
                    ok = not d.cancelled() and d.exception() is None
                    if ok and len(ready) < num_returns:
                        ready.append(r)
        finally:
            for t in tasks:
                t.cancel()
        not_ready = [r for r in refs if r not in ready]
        return ready, not_ready

    # ------------------------------------------------------------------
    # owner-side object service (serving borrowers and executors)
    # ------------------------------------------------------------------

    async def rpc_get_object(self, conn_id: int, payload: dict) -> dict:
        oid = payload["object_id"]
        await self.memory_store.wait_future(oid)
        if oid in self.memory_store.objects:
            data, meta = self.memory_store.objects[oid]
            return {"data": data, "meta": meta}
        loc = self.memory_store.locations.get(oid)
        if loc is None:
            return {"error": "object not found at owner"}
        return {"data": None, "location": loc}

    async def rpc_get_objects_batch(self, conn_id: int, payload: dict) -> dict:
        """Batched get_object: one RPC for a many-arg task's refs instead
        of one round trip per ref (reference: the 10k-args-per-task
        envelope, release/benchmarks/README.md:27 — per-message overhead
        dominates tiny-arg resolution without this)."""
        oids = payload["object_ids"]
        await asyncio.gather(*[self.memory_store.wait_future(o)
                               for o in oids])
        out = []
        for oid in oids:
            if oid in self.memory_store.objects:
                data, meta = self.memory_store.objects[oid]
                out.append({"data": data, "meta": meta})
                continue
            loc = self.memory_store.locations.get(oid)
            out.append({"error": "object not found at owner"}
                       if loc is None else {"data": None, "location": loc})
        return {"objects": out}

    async def resolve_args_batch(self, wire_args: list) -> list:
        """Executor-side arg resolution with owner-fetch batching: refs
        owned elsewhere and absent from the local store group into
        get_objects_batch calls per owner; inline/local/owned args keep the
        resolve_arg fast paths."""
        results: list = [None] * len(wire_args)
        local_idx: list = []
        by_owner: Dict[str, list] = {}
        for i, a in enumerate(wire_args):
            if "inline" in a:
                results[i] = ser.deserialize(a["inline"], copy_buffers=True)
                continue
            ref = ObjectRef(ObjectID(a["ref"]), a["owner"],
                            a["owner_worker_id"], _register=False)
            if self.owns(ref) or (
                    self.store is not None
                    and self.store.contains(ref.object_id())):
                local_idx.append(i)
            else:
                by_owner.setdefault(a["owner"], []).append((i, a))

        async def fetch_group(owner: str, items: list):
            chunk = 2048
            for c0 in range(0, len(items), chunk):
                part = items[c0:c0 + chunk]
                ref0 = ObjectRef(ObjectID(part[0][1]["ref"]), owner,
                                 part[0][1]["owner_worker_id"],
                                 _register=False)
                try:
                    client = await self._owner_client(owner)
                    reply = await client.call("get_objects_batch", {
                        "object_ids": [a["ref"] for _i, a in part],
                    }, timeout=None)
                except RpcError as e:
                    raise ObjectLostError(
                        ref0.hex(),
                        f"owner at {owner} unreachable: {e}") from e
                store_resident = []
                for (i, a), rep in zip(part, reply["objects"]):
                    if rep.get("error"):
                        raise ObjectLostError(
                            ObjectID(a["ref"]).hex(), rep["error"])
                    if rep.get("data") is not None:
                        results[i] = self._materialize(
                            rep["data"], rep["meta"], copy_buffers=True)
                    else:
                        # store-resident value: the single-ref path handles
                        # location reads + lineage reconstruction
                        store_resident.append((i, a))
                if store_resident:
                    vals = await asyncio.gather(
                        *[self.resolve_arg(a) for _i, a in store_resident])
                    for (i, _a), v in zip(store_resident, vals):
                        results[i] = v

        local_vals = await asyncio.gather(
            *[self.resolve_arg(wire_args[i]) for i in local_idx])
        for i, v in zip(local_idx, local_vals):
            results[i] = v
        await asyncio.gather(
            *[fetch_group(owner, items)
              for owner, items in by_owner.items()])
        return results

    async def rpc_wait_object(self, conn_id: int, payload: dict) -> dict:
        await self.memory_store.wait_future(payload["object_id"])
        return {"ok": True}

    async def rpc_add_borrow(self, conn_id: int, payload: dict) -> dict:
        self.ref_counter.add_borrower(payload["object_id"],
                                      payload.get("borrower", ""))
        return {"ok": True}

    async def rpc_remove_borrow(self, conn_id: int, payload: dict) -> dict:
        self.ref_counter.remove_borrower(payload["object_id"],
                                         payload.get("borrower", ""))
        return {"ok": True}

    # ------------------------------------------------------------------
    # streaming generators — owner side (reference: task_manager.h:88
    # ObjectRefStream + core_worker.proto ReportGeneratorItemReturns)
    # ------------------------------------------------------------------

    def _record_return_entry(self, ret: dict):
        oid = ret["object_id"]
        if ret.get("inline") is not None:
            self.memory_store.put(oid, ret["inline"], ret.get("meta", META_NORMAL))
        else:
            new = ret["location"]
            old = self.memory_store.locations.get(oid)
            if old is not None and old.get("daemon") != new.get("daemon"):
                # a retry/reconstruction relocated the object; free the
                # superseded copy so healthy nodes don't accumulate orphans
                spawn(self._free_store_copy(oid, old))
            self.memory_store.set_location(oid, new)

    async def _free_store_copy(self, oid: bytes, loc: dict):
        try:
            if loc.get("node_id") == self.node_id_hex and self.store is not None:
                self.store.delete(ObjectID(oid))
            else:
                client = await self._owner_client(loc["daemon"])
                await client.call("free_objects", {"object_ids": [oid]}, timeout=5)
        except Exception:  # noqa: BLE001 — the holder may be the dead node
            pass

    def _stream_end(self, tid: bytes, total: int):
        st = self._streams.get(tid)
        if st is None or st.end is not None:
            return
        st.produced = max(st.produced, total)
        st.end = st.produced
        st.wake_all()
        st.wake_consumers()

    async def rpc_report_stream_item(self, conn_id: int, payload: dict) -> dict:
        tid = payload["task_id"]
        st = self._streams.get(tid)
        if st is None or st.cancelled:
            return {"cancelled": True, "consumed": 0}
        self._record_return_entry(payload["ret"])
        st.produced = max(st.produced, payload["index"] + 1)
        st.wake_all()
        return {"cancelled": False, "consumed": st.consumed}

    async def rpc_stream_wait_consumed(self, conn_id: int, payload: dict) -> dict:
        """Executor-side backpressure: block until the consumer has taken
        `until` items (or the stream is cancelled/dropped)."""
        tid = payload["task_id"]
        st = self._streams.get(tid)
        if st is None or st.cancelled or st.consumed >= payload["until"]:
            return {"cancelled": st is None or st.cancelled, "consumed": 0 if st is None else st.consumed}
        fut = self.loop.create_future()
        st.consume_waiters.append((payload["until"], fut))
        await fut
        st2 = self._streams.get(tid)
        return {
            "cancelled": st2 is None or st2.cancelled,
            "consumed": 0 if st2 is None else st2.consumed,
        }

    async def stream_next(self, tid: bytes) -> Optional["ObjectRef"]:
        """Next item ref, or None when the stream is exhausted. The end is
        signalled by a sentinel (not an exception) because raising through
        run_coroutine_threadsafe chains tracebacks into a Task↔exception
        reference cycle that pins caller frames until a full GC."""
        st = self._streams.get(tid)
        if st is None:
            return None
        while True:
            if st.next_read < st.produced:
                idx = st.next_read
                st.next_read += 1
                st.consumed += 1
                st.wake_consumers()
                oid = ObjectID.for_task_return(TaskID(tid), idx)
                return ObjectRef(oid, self.address, self.worker_id.binary())
            if st.cancelled or (st.end is not None and st.next_read >= st.end):
                return None
            fut = self.loop.create_future()
            st.waiters.append(fut)
            await fut

    async def stream_drop(self, tid: bytes):
        """Generator GC'd: cancel the producer, release backpressure waiters,
        and free unconsumed item objects."""
        st = self._streams.pop(tid, None)
        if st is None:
            return
        st.cancelled = True
        st.wake_all()
        st.wake_consumers()
        try:
            await self.cancel_task_by_id(tid, force=False)
        except Exception:  # noqa: BLE001 — producer may have finished already
            pass
        for idx in range(st.next_read, st.produced):
            oid = ObjectID.for_task_return(TaskID(tid), idx)
            await self.free_owned_object(oid)

    # ------------------------------------------------------------------
    # task cancellation (reference: core_worker.proto CancelTask,
    # normal_task_submitter.cc CancelTask)
    # ------------------------------------------------------------------

    async def cancel_task(self, ref: "ObjectRef", force: bool = False,
                          recursive: bool = False) -> bool:
        tid = self._return_to_task.get(ref.binary())
        if tid is None:
            return False
        return await self.cancel_task_by_id(tid, force=force)

    async def cancel_task_by_id(self, tid: bytes, force: bool = False) -> bool:
        sub = self._submissions.get(tid)
        if sub is None:
            return False
        sub["cancelled"] = True
        spec: TaskSpec = sub["spec"]
        if spec.is_streaming:
            # Mark the owner-side stream cancelled and release both waiter
            # groups: a producer parked in stream_wait_consumed (or its next
            # report_stream_item) sees cancelled and aborts; the consumer
            # drains already-produced items and then stops.
            st = self._streams.get(tid)
            if st is not None:
                st.cancelled = True
                st.wake_all()
                st.wake_consumers(force=True)
        if sub["state"] == "running" and sub["worker"]:
            reply = {}
            try:
                client = await self._worker_client(sub["worker"])
                reply = await client.call(
                    "cancel_task", {"task_id": tid, "force": force}, timeout=10
                )
            except Exception:  # noqa: BLE001 — worker already gone
                pass
            if not force and reply.get("ok") and reply.get("running"):
                # The executor raises TaskCancelledError into the task's
                # thread, but async-exc delivery waits for a Python bytecode
                # boundary — a task blocked in C (time.sleep, IO) would pin
                # the caller's get() arbitrarily long. Resolve the returns
                # now; the eventual stale reply is dropped (reference:
                # CancelTask acks fail the task at the owner promptly).
                self._fail_task(spec, TaskCancelledError(
                    f"task {spec.name or spec.function_key} was cancelled"))
            # otherwise the push_task reply (an error for a cancelled task)
            # resolves the returns; force-kill resolves via the retry loop
            # seeing the cancelled flag
        elif spec.kind == pb.TASK_KIND_ACTOR_TASK:
            # queued actor task: do NOT hard-cancel the submit coroutine — it
            # must still deliver a tombstone for its sequence slot (see
            # _submit_actor_with_retries)
            pass
        elif sub["atask"] is not None:
            sub["atask"].cancel()
        else:
            # fast-lane queued entry: no coroutine exists; resolve the
            # returns now and let the feeder skip (and untrack) the entry
            self._fail_task(spec, TaskCancelledError(
                f"task {spec.name or spec.function_key} was cancelled"))
        return True

    # executor side: delegate to the task executor
    async def rpc_cancel_task(self, conn_id: int, payload: dict) -> dict:
        if self.executor is None:
            return {"ok": False}
        return self.executor.cancel(payload["task_id"], payload.get("force", False))

    async def notify_owner(self, owner_address: str, method: str, oid: bytes):
        if owner_address == self.address:
            return
        try:
            client = await self._owner_client(owner_address)
            await client.call(method, {
                "object_id": oid,
                # borrow bookkeeping is keyed by borrower identity so the
                # owner can reconcile borrows of DEAD borrowers (reference:
                # reference_counter.h borrower death cleanup)
                "borrower": self.address,
            }, timeout=10)
        except Exception:  # noqa: BLE001 — owner may be gone; borrow bookkeeping is moot
            pass

    async def _owner_client(self, address: str) -> RpcClient:
        client = self._owner_clients.get(address)
        if client is None:
            client = RpcClient(address, name="owner-client")
            await client.connect()
            self._owner_clients[address] = client
        return client

    async def _call_owner(self, ref: ObjectRef, method: str, payload: dict) -> dict:
        try:
            client = await self._owner_client(ref.owner_address)
            return await client.call(method, payload, timeout=None)
        except RpcError as e:
            raise ObjectLostError(
                ref.hex(), f"owner at {ref.owner_address} unreachable: {e}"
            ) from e

    async def free_owned_object(self, oid: ObjectID):
        key = oid.binary()
        loc = self.memory_store.locations.get(key)
        self.memory_store.delete(key)
        self.recovery.drop_lineage_for(key)
        if loc is not None:
            await self._free_store_copy(key, loc)

    # ------------------------------------------------------------------
    # task submission (reference: normal_task_submitter.h:87)
    # ------------------------------------------------------------------

    async def serialize_args(self, args: tuple, kwargs: dict) -> List[dict]:
        """Serialize positional + keyword args. Each wire entry is either a
        pass-by-reference {"ref", "owner", ...} or an {"inline"} value, with an
        optional "kw" name; refs (positional OR keyword) are resolved to their
        values on the executor, like the reference's plasma-arg resolution."""
        out = []
        for kw_name, value in [
            *((None, v) for v in args),
            *kwargs.items(),
        ]:
            if isinstance(value, ObjectRef):
                entry = {
                    "ref": value.binary(),
                    "owner": value.owner_address,
                    "owner_worker_id": value._owner_worker_id,
                    # pin the caller's ref until the task completes: if the
                    # caller drops it right after .remote(), the owner would
                    # free the object while the executor is still resolving
                    # it (reference: task args are pinned by the submitter)
                    "_pyref": value,  # stripped before wire
                }
            else:
                sobj = ser.serialize(value)
                if sobj.total_bytes > self._inline_max or sobj.contained_refs:
                    ref = await self.put_object(value)
                    entry = {
                        "ref": ref.binary(),
                        "owner": ref.owner_address,
                        "owner_worker_id": ref._owner_worker_id,
                        # keep the put alive until the task completes
                        "_pyref": ref,  # stripped before wire
                    }
                else:
                    entry = {"inline": sobj.to_bytes()}
            if kw_name is not None:
                entry["kw"] = kw_name
            out.append(entry)
        return out

    def serialize_args_sync(self, args: tuple, kwargs: dict):
        """Caller-thread arg serialization for the non-blocking submission
        path: serialization errors raise HERE, at the .remote() call site
        (matching the reference, where submit_task serializes synchronously
        in the Cython seam before the async C++ pipeline takes over).

        Returns (wire_args, pyrefs, pending_puts); pending_puts are
        (ObjectID, SerializedObject) pairs whose store writes the loop-side
        coroutine must complete before submitting — the ObjectRef/oid are
        allocated here so the wire entry is final."""
        out, pyrefs, pending = [], [], []
        for kw_name, value in [
            *((None, v) for v in args),
            *kwargs.items(),
        ]:
            if isinstance(value, ObjectRef):
                entry = {
                    "ref": value.binary(),
                    "owner": value.owner_address,
                    "owner_worker_id": value._owner_worker_id,
                }
                pyrefs.append(value)
            else:
                sobj = ser.serialize(value)
                if sobj.total_bytes > self._inline_max or sobj.contained_refs:
                    with self._lock:
                        self._put_index += 1
                        oid = ObjectID.for_put(
                            self.current_task_id, self._put_index)
                    ref = ObjectRef(oid, self.address, self.worker_id.binary())
                    pending.append((oid, sobj))
                    entry = {
                        "ref": ref.binary(),
                        "owner": ref.owner_address,
                        "owner_worker_id": ref._owner_worker_id,
                    }
                    pyrefs.append(ref)
                else:
                    entry = {"inline": sobj.to_bytes()}
            if kw_name is not None:
                entry["kw"] = kw_name
            out.append(entry)
        return out, pyrefs, pending

    async def _complete_put(self, oid: ObjectID, sobj: "ser.SerializedObject"):
        """Finish a caller-thread-allocated put (the write half of
        put_object): resolve the memory-store future / write shm so
        dependents and gets unblock."""
        if sobj.total_bytes <= self._inline_max:
            self.memory_store.put(oid.binary(), sobj.to_bytes(), META_NORMAL)
        elif self.store is None:
            await self._remote_put(oid, sobj)
            self.memory_store.set_location(
                oid.binary(),
                {"daemon": self.daemon_address, "node_id": self.node_id_hex},
            )
        else:
            view = await self._create_with_spill(oid, sobj.total_bytes)
            sobj.write_into(view)
            view.release()
            self.store.seal(oid)
            self.memory_store.set_location(
                oid.binary(),
                {"daemon": self.daemon_address, "node_id": self.node_id_hex,
                 "local": True},
            )

    def submit_task_fast(
        self,
        function_obj,
        function_key: str,
        args: tuple,
        kwargs: dict,
        num_returns: int = 1,
        resources: Optional[Dict[str, float]] = None,
        strategy: Optional[SchedulingStrategy] = None,
        max_retries: Optional[int] = None,
        name: str = "",
        runtime_env: Optional[dict] = None,
        stream_backpressure: int = -1,
        lease_key: Any = False,
    ):
        """Non-blocking submission callable from ANY thread — the driver's
        .remote() must never wait on a loop round trip (reference:
        normal_task_submitter.h — submission is pipelined; ray_perf's async
        suite measures exactly this). Serialization runs on the caller
        thread (errors raise at the call site); everything needing the loop
        (pending put writes, export, lease/push) continues asynchronously.

        `resources`/`strategy` may be prebuilt (shared, never-mutated)
        objects and `lease_key` their precomputed scheduling key — the
        RemoteFunction caches all three across calls."""
        task_id = self.next_task_id()
        wire_args, pyrefs, pending = self.serialize_args_sync(args, kwargs)
        spec = TaskSpec(
            trace_ctx=_trace_inject(),
            task_id=task_id,
            job_id=self.job_id,
            kind=pb.TASK_KIND_NORMAL,
            function_key=function_key,
            args=wire_args,
            num_returns=num_returns,
            resources=(
                resources if isinstance(resources, ResourceSet)
                else ResourceSet(resources or {"CPU": 1.0})
            ),
            strategy=strategy or SchedulingStrategy(),
            max_retries=(
                max_retries if max_retries is not None
                else GLOBAL_CONFIG.get("max_task_retries_default")
            ),
            owner_worker_id=self.worker_id.binary(),
            owner_address=self.address,
            name=name,
            runtime_env=runtime_env or {},
            stream_backpressure=stream_backpressure,
        )
        refs = [
            ObjectRef(oid, self.address, self.worker_id.binary())
            for oid in spec.return_ids()
        ]
        if spec.trace_ctx is not None:
            # per-hop decomposition stamps ride the spec OBJECT (owner-side
            # only — nothing extra crosses the wire on the submit side)
            spec._hop = {"sub_ns": time.monotonic_ns(), "wall0": time.time()}
        if spec.is_streaming:
            self._streams[task_id.binary()] = StreamState(task_id.binary())

        # FAST LANE: inline-only args, exported function, no env prep —
        # nothing to await before delivery, so skip the per-task coroutine
        # chain entirely; the push feeder handles replies AND retries from
        # the submission entry (reference: the C++ submitter is exactly this
        # shape — no per-task task, just queues and callbacks).
        fast = (
            not spec.is_streaming
            and not pending
            and not spec.runtime_env
            and function_key in self._exported
            and not any("ref" in a for a in wire_args)
        )
        if fast:
            key = lease_key if lease_key is not False else self._lease_key(spec)
            fast = key is not None
        if fast:
            # native engine first: encode the spec to wire bytes in C++ and
            # enqueue on the lock-free ring; falls through to the Python
            # queue when the shape has no template or the ring is full. On
            # the loop thread the encode runs inline; from a driver thread
            # it rides the batched cross-thread drain — a deep burst's
            # caller-side cost must stay at spec+refs+append (the encode is
            # cheap but the submission entry bookkeeping is not).
            # trace_ctx: the ROOT sentinel (DERIVE_CTX, identity-compared) is
            # per-task-invariant and bakes into the template — tracing ON
            # keeps the native engine engaged. Explicit per-task contexts
            # (nested submissions, serve requests) ride the Python queue.
            if self._fastpath is not None and (
                    spec.trace_ctx is None
                    or spec.trace_ctx is _tracing_DERIVE_CTX()):
                if self._loop_running_here():
                    if self._fp_submit(key, spec, pyrefs):
                        return refs
                else:
                    self._xthread_submits.append(("fp", key, (spec, pyrefs)))
                    if not self._xthread_scheduled:
                        self._xthread_scheduled = True
                        self.loop.call_soon_threadsafe(
                            self._drain_xthread_submits)
                    return refs
            item = (spec, None, pyrefs)
            if self._loop_running_here():
                self._enqueue_fast(key, item)
            else:
                self._xthread_submits.append(("fast", key, item))
                if not self._xthread_scheduled:
                    self._xthread_scheduled = True
                    self.loop.call_soon_threadsafe(self._drain_xthread_submits)
            return refs

        async def finish():
            from ray_tpu._private.runtime_env_mgr import prepare_runtime_env

            for oid, sobj in pending:
                await self._complete_put(oid, sobj)
            if spec.runtime_env:
                spec.runtime_env = await prepare_runtime_env(
                    spec.runtime_env, self) or {}
            await self.export_function(function_key, function_obj)
            await self._submit_with_retries(spec, pyrefs)

        if self._loop_running_here():
            atask = spawn(self._guard_submit(spec, finish()))
            self._track_submission(spec, atask)
        else:
            # batched handoff, FIFO with subsequent cancel/get calls through
            # the loop (their run_coroutine_threadsafe callbacks queue after
            # the drain callback already scheduled for this burst)
            self._xthread_submits.append(("coro", spec, finish()))
            if not self._xthread_scheduled:
                self._xthread_scheduled = True
                self.loop.call_soon_threadsafe(self._drain_xthread_submits)
        if spec.is_streaming:
            return ObjectRefGenerator(self, task_id.binary())
        return refs

    @staticmethod
    def _hop_enqueue_stamp(spec: TaskSpec):
        """Stamp the spec's queue-entry time. submit_encode is observed
        ONCE (first enqueue only): a RETRY re-entering the queue would
        otherwise fold the whole failed attempt — lease wait, RPC, backoff
        — into a microsecond-scale hop and corrupt the dominant-hop
        answer. The enqueue stamp itself always refreshes so ring_wait
        measures the CURRENT attempt's queue residency."""
        hop = getattr(spec, "_hop", None)
        if hop is None:
            return
        now = time.monotonic_ns()
        if "enq_ns" not in hop:
            hops.observe_ns("submit_encode", now - hop["sub_ns"])
        hop["enq_ns"] = now

    def _enqueue_fast(self, key: tuple, item: tuple):
        spec = item[0]
        if self._closed:
            self._fail_task(spec, RayTpuError("core worker closed"))
            return
        self._hop_enqueue_stamp(spec)
        tid = spec.task_id.binary()
        entry = {
            "state": "pending", "worker": "", "cancelled": False,
            "atask": None, "spec": spec, "attempts": 0,
            "keepalive": item[2],
        }
        self._submissions[tid] = entry
        for oid in spec.return_ids():
            self._return_to_task[oid.binary()] = tid
        q = self._push_queues.get(key)
        if q is None:
            q = self._push_queues[key] = collections.deque()
        q.append((spec, None))
        self._ensure_push_feeders(key, spec)

    def _drain_xthread_submits(self):
        # reset BEFORE popping: a producer that observes the flag still True
        # is guaranteed its append happens while this loop is still draining
        self._xthread_scheduled = False
        budget = 4096
        while self._xthread_submits:
            if budget <= 0:
                # a 100k-task burst must not monopolize the loop in one
                # callback: re-schedule the remainder so feeders and reply
                # handling interleave (the flag stays True across the gap —
                # producers piggyback instead of double-scheduling)
                self._xthread_scheduled = True
                self.loop.call_soon(self._drain_xthread_submits)
                return
            budget -= 1
            kind, a, b = self._xthread_submits.popleft()
            if kind == "fast":
                self._enqueue_fast(a, b)
            elif kind == "fp":
                spec, pyrefs = b
                if not self._fp_submit(a, spec, pyrefs):
                    # ring full / template miss: the Python queue takes it
                    self._enqueue_fast(a, (spec, None, pyrefs))
            else:
                self._spawn_tracked_submit(a, b)

    # ------------------------------------------------------------------
    # native fast path (reference: _raylet.pyx:3817 submit_task — the
    # compiled seam every .remote() crosses in the reference)
    # ------------------------------------------------------------------

    def _fp_ring_for(self, key: tuple) -> int:
        ring = self._fp_rings.get(key)
        if ring is None:
            with self._lock:
                ring = self._fp_rings.get(key)
                if ring is None:
                    # -1 latches "this key submits via Python" (ring table
                    # full — 256 distinct scheduling shapes is a lot)
                    ring = self._fastpath.ring_create()
                    self._fp_rings[key] = ring
        return ring

    def _fp_template_for(self, spec: TaskSpec, key: tuple) -> int:
        # trace marker in the key: a template encodes trace_ctx as a
        # constant fragment (None vs the DERIVE sentinel), so the same
        # shape templated with tracing off must not serve traced specs
        tkey = (spec.function_key, spec.num_returns, spec.max_retries,
                spec.name, spec.stream_backpressure,
                spec.trace_ctx is not None, key)
        tmpl = self._fp_templates.get(tkey)
        if tmpl is None:
            with self._lock:
                tmpl = self._fp_templates.get(tkey)
                if tmpl is None:
                    tmpl = _fp.build_template(self._fastpath, spec)
                    self._fp_templates[tkey] = tmpl
        return tmpl

    def _fp_pending(self, key: tuple) -> int:
        eng = self._fastpath
        if eng is None:
            return 0
        ring = self._fp_rings.get(key)
        if ring is None or ring < 0:
            return 0
        return eng.ring_len(ring)

    def _fp_submit(self, key: tuple, spec: TaskSpec, pyrefs: list) -> bool:
        """Encode + enqueue one fast-lane spec on the native ring. Runs on
        the LOOP thread (inline for loop-side submitters, via the batched
        xthread drain for driver threads — the caller thread's burst cost
        must stay at spec+refs+append). Returns False when the caller
        should fall back to the Python queue (no template for this shape,
        ring full, closed)."""
        if self._closed:
            return False
        eng = self._fastpath
        ring = self._fp_ring_for(key)
        if ring < 0:
            return False
        tmpl = self._fp_template_for(spec, key)
        if tmpl < 0:
            return False
        try:
            args_blob = _fp_pack_args(spec.args)
        except Exception:  # noqa: BLE001 — exotic arg entry: Python path
            return False
        tid = spec.task_id.binary()
        entry = {
            "state": "pending", "worker": "", "cancelled": False,
            "atask": None, "spec": spec, "attempts": 0,
            "keepalive": pyrefs, "fp": True,
        }
        self._submissions[tid] = entry
        for oid in spec.return_ids():
            self._return_to_task[oid.binary()] = tid
        if eng.encode(ring, tmpl, tid, args_blob) != 0:
            # ring full (or torn down): undo the tracking, use the deque
            self._submissions.pop(tid, None)
            for oid in spec.return_ids():
                self._return_to_task.pop(oid.binary(), None)
            return False
        hop = getattr(spec, "_hop", None)
        if hop is not None:
            # the C++ encode stamped the ring-enqueue time inside the entry
            # (pop returns the residency); this side closes submit_encode.
            # enq_ns doubles as the observed-once marker: a retry of this
            # spec re-entering via the Python queue must not re-fold the
            # failed attempt into submit_encode
            now = time.monotonic_ns()
            if "enq_ns" not in hop:
                hops.observe_ns("submit_encode", now - hop["sub_ns"])
            hop["enq_ns"] = now
        # always on the loop thread (inline fast lane or the xthread drain)
        self._ensure_push_feeders(key, spec)
        return True

    def _spawn_tracked_submit(self, spec: TaskSpec, coro):
        if self._closed:
            coro.close()
            self._fail_task(spec, RayTpuError("core worker closed"))
            return
        atask = spawn(self._guard_submit(spec, coro))
        self._track_submission(spec, atask)

    def submit_actor_task_nowait(self, actor_id: bytes, method_name: str,
                                 args: tuple, kwargs: dict,
                                 num_returns: int = 1,
                                 max_task_retries: int = 0,
                                 stream_backpressure: int = -1,
                                 concurrency_group: str = "",
                                 concurrent: bool = False):
        """NON-BLOCKING actor submission from ANY thread: args serialize
        on the calling thread (errors raise at the .remote() call site,
        before a sequence slot is taken), the sequence number is assigned
        under the lock (ordering is decided here), and delivery continues
        on the event loop. This is the `.remote()` hot path — a driver
        thread must not round-trip through the loop per call (that
        serializes "async" submission behind a thread hop and caps
        pipelined throughput at the hop rate; same design as
        submit_task_fast for plain tasks)."""
        wire_args, pyrefs, pending = self.serialize_args_sync(args, kwargs)
        st = self._actor_state(actor_id)
        if concurrent:
            st.concurrent = True
        with self._lock:
            seq = self._next_seq(st)
            # the task id must NOT derive from `seq`: sequence numbering
            # restarts at 1 for every actor incarnation, so a post-restart
            # task would reuse a pre-restart task's id — colliding in the
            # executor's duplicate-reply cache (a new call answered with a
            # stale cached reply) and in this owner's submission/return
            # tables. Mint from the caller-global task counter instead;
            # seq stays purely an ordering stamp. (Found by the chaos
            # harness: soak scenario 4, control-store stall during
            # failover.)
            self._task_index += 1
            task_index = self._task_index
        task_id = TaskID.for_actor_task(
            self.job_id, ActorID(actor_id), self.current_task_id, task_index
        )
        spec = TaskSpec(
            trace_ctx=_trace_inject(),
            task_id=task_id,
            job_id=self.job_id,
            kind=pb.TASK_KIND_ACTOR_TASK,
            method_name=method_name,
            args=wire_args,
            num_returns=num_returns,
            owner_worker_id=self.worker_id.binary(),
            owner_address=self.address,
            actor_id=ActorID(actor_id),
            seq_no=seq,
            incarnation=st.incarnation,
            name=method_name,
            stream_backpressure=stream_backpressure,
            concurrency_group=concurrency_group,
        )
        refs = [
            ObjectRef(oid, self.address, self.worker_id.binary())
            for oid in spec.return_ids()
        ]
        if spec.is_streaming:
            self._streams[task_id.binary()] = StreamState(task_id.binary())

        async def finish():
            for oid, sobj in pending:
                await self._complete_put(oid, sobj)
            await self._submit_actor_with_retries(st, spec, max_task_retries, pyrefs)

        guarded = self._guard_submit(spec, finish())
        if self._loop_running_here():
            atask = spawn(guarded)
        else:
            # foreign (driver) thread: hand off without waiting; the
            # concurrent.Future supports the same cancel/done-callback
            # surface _track_submission needs
            atask = asyncio.run_coroutine_threadsafe(guarded, self.loop)
        self._track_submission(spec, atask)
        if spec.is_streaming:
            return ObjectRefGenerator(self, task_id.binary())
        return refs

    async def _guard_submit(self, spec: TaskSpec, coro):
        """Serialization/export failures in a deferred submission must fail
        the returns, not vanish into the spawn error log."""
        try:
            await coro
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001
            if spec.kind == pb.TASK_KIND_ACTOR_TASK:
                # the sequence number was taken at submission but the spec
                # never reached the executor (e.g. unpicklable args): deliver
                # a cancelled tombstone so the slot is consumed — ordered
                # actors stall on sequence holes otherwise
                try:
                    spec.cancelled = True
                    spec.args = []
                    st = self._actor_state(spec.actor_id.binary())
                    await self._submit_actor_with_retries(st, spec, 0, [])
                except Exception:  # noqa: BLE001 — actor gone; no hole to fill
                    pass
            self._fail_task(spec, RayTpuError(f"submit failed: {e}"))

    def _track_submission(self, spec: TaskSpec, atask: asyncio.Task):
        tid = spec.task_id.binary()
        entry = {
            "state": "pending", "worker": "", "cancelled": False,
            "atask": atask, "spec": spec,
        }
        self._submissions[tid] = entry
        for oid in spec.return_ids():
            self._return_to_task[oid.binary()] = tid
        atask.add_done_callback(lambda _t: self._untrack_submission(spec))

    def _untrack_submission(self, spec: TaskSpec):
        self._submissions.pop(spec.task_id.binary(), None)
        for oid in spec.return_ids():
            self._return_to_task.pop(oid.binary(), None)

    def _fail_task(self, spec: TaskSpec, exc: Exception):
        """Resolve every return of a task (fixed or streaming) to an error."""
        for oid in spec.return_ids():
            self.memory_store.fail(oid.binary(), exc)
        if spec.is_streaming:
            self._stream_fail(spec.task_id.binary(), exc)

    def _stream_fail(self, tid: bytes, exc: Exception):
        """Terminate a stream with a trailing error item so iteration raises
        (at get of the final ref) instead of hanging."""
        st = self._streams.get(tid)
        if st is None or st.end is not None:
            return
        oid = ObjectID.for_task_return(TaskID(tid), st.produced)
        self.memory_store.fail(oid.binary(), exc)
        st.produced += 1
        st.end = st.produced
        st.wake_all()
        st.wake_consumers()

    async def _submit_with_retries(self, spec: TaskSpec, keepalive):
        from ray_tpu._private.retry import RetryPolicy

        retries = spec.max_retries
        attempt = 0
        sub = None
        backoff = RetryPolicy(
            GLOBAL_CONFIG.get("retry_base_s"),
            GLOBAL_CONFIG.get("retry_max_s"),
        ).backoff()
        while True:
            sub = self._submissions.get(spec.task_id.binary())
            if sub is not None and sub["cancelled"]:
                self._fail_task(spec, TaskCancelledError(
                    f"task {spec.name or spec.function_key} was cancelled"))
                return
            try:
                await self._submit_once(spec)
                self._record_lineage(spec, keepalive)
                return
            except asyncio.CancelledError:
                # ray_tpu.cancel() of a queued/leasing task cancels this
                # coroutine; resolve the returns so get() raises
                self._fail_task(spec, TaskCancelledError(
                    f"task {spec.name or spec.function_key} was cancelled"))
                raise
            except (WorkerCrashedError, RpcError, ConnectionError, asyncio.TimeoutError) as e:
                if sub is not None and sub["cancelled"]:
                    self._fail_task(spec, TaskCancelledError(
                        f"task {spec.name or spec.function_key} was cancelled"))
                    return
                attempt += 1
                if attempt > retries:
                    self._fail_task(
                        spec,
                        WorkerCrashedError(
                            f"task {spec.name or spec.function_key} failed after "
                            f"{retries} retries: {e}"
                        ),
                    )
                    return
                logger.info("retrying task %s (attempt %d): %s", spec.name, attempt, e)
                await backoff.sleep()
            except Exception as e:  # noqa: BLE001 — scheduling-level failure
                self._fail_task(spec, RayTpuError(f"submit failed: {e}"))
                return
        # `keepalive` pins arg refs for the life of this coroutine.

    async def _wait_args_ready(self, spec: TaskSpec):
        """Block until every by-reference arg is computed (reference:
        task_submission/dependency_resolver — the lease is requested only
        after dependencies resolve). Without this, a full complement of
        granted consumer tasks blocking on queued producer tasks deadlocks
        the worker pool."""

        async def one(a: dict):
            if self.owns_oid(a["owner_worker_id"]):
                await self.memory_store.wait_future(a["ref"])
            else:
                ref = ObjectRef(
                    ObjectID(a["ref"]), a["owner"], a["owner_worker_id"],
                    _register=False,
                )
                await self._call_owner(ref, "wait_object", {"object_id": a["ref"]})

        waits = [one(a) for a in spec.args if "ref" in a]
        if waits:
            await asyncio.gather(*waits)

    def owns_oid(self, owner_worker_id: bytes) -> bool:
        return owner_worker_id == self.worker_id.binary()

    def _lease_key(self, spec: TaskSpec) -> Optional[tuple]:
        return compute_lease_key(
            spec.resources, spec.strategy,
            (spec.runtime_env or {}).get("env_key", ""))

    def _pool_for(self, key: tuple) -> dict:
        pool = self._lease_pools.get(key)
        if pool is None:
            pool = self._lease_pools[key] = {
                "idle": [], "waiters": collections.deque(), "fetching": 0,
            }
        return pool

    async def _pool_lease(self, key: tuple, spec: TaskSpec) -> dict:
        """Take an idle cached lease, or register as a waiter while a
        detached fetcher requests a fresh one — a lease released by a
        finishing task is handed to the oldest waiter directly."""
        pool = self._pool_for(key)
        if pool["idle"]:
            return pool["idle"].pop()
        fut = self.loop.create_future()
        pool["waiters"].append(fut)
        # Bounded fetchers (reference: LeaseRequestRateLimiter): a burst of
        # N submissions must not flood the daemon with N lease requests —
        # recycled leases serve most waiters; fetchers only prime the pump.
        if pool["fetching"] < min(
            len(pool["waiters"]), GLOBAL_CONFIG.get("max_pending_lease_requests")
        ):
            pool["fetching"] += 1
            spawn(self._lease_fetch(key, spec))
        try:
            return await fut
        except asyncio.CancelledError:
            # Cancelled in the window after _lease_pool_put resolved this
            # future but before this coroutine resumed: the delivered lease
            # would otherwise be orphaned — never re-pooled, never returned —
            # permanently leaking that worker's capacity (advisor r2).
            if fut.done() and not fut.cancelled() and fut.exception() is None:
                self._lease_pool_put(key, fut.result())
            else:
                try:
                    pool["waiters"].remove(fut)
                except ValueError:
                    pass
            raise

    async def _lease_fetch(self, key: tuple, spec: TaskSpec):
        try:
            lease = await self._acquire_lease(spec)
        except Exception as e:  # noqa: BLE001 — deliver the failure
            pool = self._lease_pools.get(key)
            if pool:
                pool["fetching"] = max(0, pool["fetching"] - 1)
            delivered = False
            while pool and pool["waiters"] and not delivered:
                fut = pool["waiters"].popleft()
                if not fut.done():
                    fut.set_exception(e)
                    delivered = True
            # each failure fails exactly one waiter; keep priming so the
            # REST eventually get a lease or their own failure instead of
            # hanging with fetching==0 and nothing recycling
            if pool and pool["waiters"] and pool["fetching"] < min(
                len(pool["waiters"]),
                GLOBAL_CONFIG.get("max_pending_lease_requests"),
            ):
                pool["fetching"] += 1
                spawn(self._lease_fetch(key, spec))
            return
        pool = self._lease_pools.get(key)
        if pool:
            pool["fetching"] = max(0, pool["fetching"] - 1)
            # keep priming while demand outstrips supply
            if pool["waiters"] and pool["fetching"] < min(
                len(pool["waiters"]),
                GLOBAL_CONFIG.get("max_pending_lease_requests"),
            ):
                pool["fetching"] += 1
                spawn(self._lease_fetch(key, spec))
        lease["fresh"] = True  # straight from the daemon, never executed on
        self._lease_pool_put(key, lease)

    def _lease_pool_put(self, key: tuple, lease: dict):
        pool = self._pool_for(key)
        while pool["waiters"]:
            fut = pool["waiters"].popleft()
            if not fut.done():
                fut.set_result(lease)
                return
        if len(pool["idle"]) >= GLOBAL_CONFIG.get("lease_pool_max_idle"):
            self.schedule(self._return_lease_quiet(
                lease["daemon_address"], lease["lease_id"]))
            return
        lease["idle_since"] = time.monotonic()
        pool["idle"].append(lease)

    async def _submit_once(self, spec: TaskSpec):
        await self._wait_args_ready(spec)
        key = self._lease_key(spec)
        if key is not None and not spec.is_streaming:
            # pipelined path: queue for a batch feeder (reference:
            # normal_task_submitter.h:226 pipelined PushNormalTask) — many
            # same-shaped tasks share one RPC to a leased worker
            await self._submit_via_queue(key, spec)
            return
        while True:
            if key is None:
                lease = await self._acquire_lease(spec)
                lease["fresh"] = True
            else:
                lease = await self._pool_lease(key, spec)
            # a recycled lease (another task already ran on its worker) can
            # be stale; only those get the transparent-refresh retry below
            cached = not lease.pop("fresh", False)
            worker_addr = lease["worker_address"]
            sub = self._submissions.get(spec.task_id.binary())
            if sub is not None:
                sub["state"] = "running"
                sub["worker"] = worker_addr
            try:
                client = await self._worker_client(worker_addr)
                reply = await client.call(
                    "push_task", {"spec": spec.to_wire()}, timeout=None)
            except (RpcError, ConnectionError) as e:
                # never reuse a lease whose worker just failed
                self.schedule(self._return_lease_quiet(
                    lease["daemon_address"], lease["lease_id"]))
                if cached:
                    # a cached lease can be stale (worker reaped, node died
                    # between tasks): siblings from the same daemon are
                    # equally dead — drop them all, then retry with a fresh
                    # lease rather than burning a task failure retry
                    self._drop_pooled_leases_from(lease["daemon_address"])
                    continue
                raise WorkerCrashedError(
                    f"worker at {worker_addr} died mid-task: {e}") from e
            except BaseException:
                # cancellation (ray_tpu.cancel of this submit, close()) must
                # not strand the lease: the daemon would count the worker
                # leased forever (the pre-pool code's finally did this)
                self.schedule(self._return_lease_quiet(
                    lease["daemon_address"], lease["lease_id"]))
                raise
            # success: recycle the lease — next same-shaped task skips the
            # lease RPCs (reference: lease reuse + pipelining); the sweeper
            # returns it if nothing claims it in time
            if key is None:
                self.schedule(self._return_lease_quiet(
                    lease["daemon_address"], lease["lease_id"]))
            else:
                self._lease_pool_put(key, lease)
            self._record_task_reply(spec, reply)
            return

    async def _submit_via_queue(self, key: tuple, spec: TaskSpec):
        """Enqueue a ready spec for batched delivery; completes (or raises
        WorkerCrashedError into the caller's retry loop) when its batch's
        reply lands. One future per task — the feeder owns leases and RPCs."""
        q = self._push_queues.get(key)
        if q is None:
            q = self._push_queues[key] = collections.deque()
        self._hop_enqueue_stamp(spec)
        fut = self.loop.create_future()
        q.append((spec, fut))
        self._ensure_push_feeders(key, spec)
        try:
            await fut
        except asyncio.CancelledError:
            # the entry may still sit in the queue; feeders skip done futures
            if not fut.done():
                fut.cancel()
            raise

    def _ensure_push_feeders(self, key: tuple, spec: TaskSpec):
        q = self._push_queues.get(key)
        if not q and not self._fp_pending(key):
            return
        active = self._push_feeders.get(key, 0)
        # Every enqueue may add one feeder (up to the cap): existing feeders
        # are busy awaiting an in-flight batch, and a newly queued task must
        # be able to reach a DIFFERENT worker concurrently — otherwise one
        # slow task head-of-line-blocks tasks that have idle capacity
        # elsewhere. Surplus feeders exit as soon as the queue drains.
        if active < GLOBAL_CONFIG.get("push_feeders_per_key"):
            self._push_feeders[key] = active + 1
            spawn(self._push_feeder(key, spec))

    async def _push_feeder(self, key: tuple, template_spec: TaskSpec):
        """Drain the key's ready queue: take a lease, ship up to
        push_batch_max specs in ONE push_task_batch RPC, record replies,
        recycle the lease, repeat. Stale cached leases retry the whole batch
        transparently (not charged against task retries), exactly like the
        single-push path."""
        try:
            while True:
                q = self._push_queues.get(key)
                fp_n = self._fp_pending(key)
                if not q and not fp_n:
                    return
                try:
                    t_lease_ns = time.monotonic_ns()
                    lease = await self._pool_lease(key, template_spec)
                except Exception as e:  # noqa: BLE001 — lease unobtainable
                    # e.g. worker spawn failed (broken pip env): deliver the
                    # failure to ONE queued task (mirroring _lease_fetch's
                    # one-failure-one-waiter rule) instead of dying with the
                    # queue stranded
                    delivered = False
                    while q:
                        spec, fut = q.popleft()
                        if fut is None:
                            sub = self._submissions.get(spec.task_id.binary())
                            if sub is None:
                                continue
                            self._fail_task(spec, e)
                            self._untrack_submission(spec)
                            delivered = True
                            break
                        if not fut.done():
                            fut.set_exception(e)
                            delivered = True
                            break
                    if not delivered and fp_n and self._fastpath is not None:
                        # native-ring entries only: fail one of those instead
                        for handle, tid, _wait in self._fastpath.pop(
                                self._fp_rings[key], 1):
                            self._fastpath.entry_free(handle)
                            sub = self._submissions.get(tid)
                            if sub is not None:
                                self._fail_task(sub["spec"], e)
                                self._untrack_submission(sub["spec"])
                    continue
                cached = not lease.pop("fresh", False)
                grant_ns = lease.pop("grant_wait_ns", None)
                if not cached and hops.enabled():
                    # the grant hop: daemon-side queue-to-grant time when the
                    # reply carries it, else the owner-observed fetch wait
                    hops.observe_ns("grant", grant_ns if grant_ns is not None
                                    else time.monotonic_ns() - t_lease_ns)
                # fair share: don't let one feeder swallow the whole queue
                # into a single worker's (sequential) batch while sibling
                # feeders could drain it onto other workers in parallel
                qlen = (len(q) if q else 0) + fp_n
                maxb = max(1, min(
                    GLOBAL_CONFIG.get("push_batch_max"),
                    -(-qlen // max(1, self._push_feeders.get(key, 1))),
                ))
                if fp_n:
                    progressed = await self._push_fp_batch(
                        key, lease, cached, maxb, q)
                    if progressed:
                        continue
                    if not q:
                        self._lease_pool_put(key, lease)
                        continue
                batch = []
                while q and len(batch) < maxb:
                    spec, fut = q.popleft()
                    if fut is not None and fut.done():
                        continue  # cancelled while queued
                    sub = self._submissions.get(spec.task_id.binary())
                    if sub is not None and sub.get("cancelled"):
                        if fut is None:
                            # fast-lane entry: no coroutine resolves the
                            # returns — do it here
                            self._fail_task(spec, TaskCancelledError(
                                f"task {spec.name or spec.function_key} "
                                f"was cancelled"))
                            self._untrack_submission(spec)
                        else:
                            fut.cancel()
                        continue
                    batch.append((spec, fut))
                if not batch:
                    self._lease_pool_put(key, lease)
                    continue
                worker_addr = lease["worker_address"]
                traced = hops.enabled()
                if traced:
                    t_pop = time.monotonic_ns()
                    waits = [t_pop - s._hop["enq_ns"] for s, _ in batch
                             if getattr(s, "_hop", None)
                             and "enq_ns" in s._hop]
                    if waits:
                        hops.observe_many_ns("ring_wait", waits)
                for spec, fut in batch:
                    sub = self._submissions.get(spec.task_id.binary())
                    if sub is not None:
                        sub["state"] = "running"
                        sub["worker"] = worker_addr
                try:
                    client = await self._worker_client(worker_addr)
                    payload = {"specs": [s.to_wire() for s, _ in batch]}
                    if traced:
                        t_send = time.monotonic_ns()
                        hops.observe_ns("frame_build", t_send - t_pop)
                        t_send_wall = time.time()
                    reply = await client.call(
                        "push_task_batch", payload, timeout=None,
                    )
                    if traced:
                        t_reply = time.monotonic_ns()
                        if "srv_ns" in reply:
                            # srv_ns missing = the worker's tracing flag is
                            # off (runtime-enabled driver, pre-spawn
                            # worker): skip rather than fold the whole
                            # server-side execution into the wire hop
                            hops.observe_ns(
                                "wire_rtt",
                                t_reply - t_send - reply["srv_ns"])
                except (RpcError, ConnectionError) as e:
                    self.schedule(self._return_lease_quiet(
                        lease["daemon_address"], lease["lease_id"]))
                    if cached:
                        # stale cached lease (worker reaped between tasks):
                        # requeue at the front and retry with another lease
                        # rather than burning task retries
                        self._drop_pooled_leases_from(lease["daemon_address"])
                        for item in reversed(batch):
                            self._hop_enqueue_stamp(item[0])
                            q.appendleft(item)
                        continue
                    err = WorkerCrashedError(
                        f"worker at {worker_addr} died mid-task: {e}")
                    for spec, fut in batch:
                        if fut is None:
                            self._fast_lane_retry(key, q, spec, err)
                        elif not fut.done():
                            fut.set_exception(err)
                    continue
                except BaseException as e:
                    # close()/feeder cancellation mid-push: don't strand the
                    # lease or the waiting submissions
                    self.schedule(self._return_lease_quiet(
                        lease["daemon_address"], lease["lease_id"]))
                    err = WorkerCrashedError(f"submission aborted: {e}")
                    for spec, fut in batch:
                        if fut is None:
                            self._fail_task(spec, err)
                            self._untrack_submission(spec)
                        elif not fut.done():
                            fut.set_exception(err)
                    raise
                self._lease_pool_put(key, lease)
                for (spec, fut), r in zip(batch, reply["replies"]):
                    try:
                        self._record_task_reply(spec, r)
                    except Exception as e:  # noqa: BLE001 — per-task failure
                        if fut is None:
                            self._fail_task(spec, e)
                            self._untrack_submission(spec)
                        elif not fut.done():
                            fut.set_exception(e)
                        continue
                    if traced and getattr(spec, "_hop", None) is not None:
                        self._note_hop_spans(spec, r, t_send_wall)
                    if fut is None:
                        sub = self._submissions.get(spec.task_id.binary())
                        self._record_lineage(
                            spec, sub["keepalive"] if sub else [])
                        self._untrack_submission(spec)
                    elif not fut.done():
                        fut.set_result(None)
                if traced:
                    hops.observe_ns("completion",
                                    time.monotonic_ns() - t_reply)
        finally:
            n = self._push_feeders.get(key, 1) - 1
            if n <= 0:
                self._push_feeders.pop(key, None)
            else:
                self._push_feeders[key] = n
            # a task enqueued in the window after this feeder saw an empty
            # queue must not wait forever
            self._ensure_push_feeders(key, template_spec)

    async def _push_fp_batch(self, key: tuple, lease: dict, cached: bool,
                             maxb: int, q) -> bool:
        """Drain up to `maxb` native-ring entries into ONE preassembled
        push_task_batch frame shipped to the leased worker (the C++ engine
        concatenates the pre-encoded specs and the frame header into a
        single buffer — one write, no per-spec packing). Returns True when
        this iteration made progress (sent a batch or consumed cancelled
        entries); False when the ring turned out empty (a sibling feeder
        won the race) — the caller still owns the lease."""
        eng = self._fastpath
        ring = self._fp_rings[key]
        popped = eng.pop(ring, maxb)
        if not popped:
            return False
        traced = hops.enabled()
        if traced:
            # ring residency stamped by the C++ engine at encode time
            hops.observe_many_ns("ring_wait", [w for _h, _t, w in popped])
        handles, specs = [], []
        for handle, tid, _wait in popped:
            sub = self._submissions.get(tid)
            if sub is None or sub.get("cancelled"):
                eng.entry_free(handle)
                if sub is not None:
                    spec = sub["spec"]
                    self._fail_task(spec, TaskCancelledError(
                        f"task {spec.name or spec.function_key} "
                        f"was cancelled"))
                    self._untrack_submission(spec)
                continue
            handles.append(handle)
            specs.append(sub["spec"])
        if not handles:
            self._lease_pool_put(key, lease)
            return True
        worker_addr = lease["worker_address"]
        for spec in specs:
            sub = self._submissions.get(spec.task_id.binary())
            if sub is not None:
                sub["state"] = "running"
                sub["worker"] = worker_addr

        consumed = [False]  # build() owns the entries once entered
        t_sent = [0]

        def build(req_id: int) -> bytes:
            consumed[0] = True
            t0 = time.monotonic_ns() if traced else 0
            frame = eng.build_frame(handles, req_id)
            if frame is None:  # over the transport limit (absurd batch)
                for h in handles:
                    eng.entry_free(h)
                raise RpcError("fastpath batch frame exceeds transport limit")
            if traced:
                t_sent[0] = time.monotonic_ns()
                hops.observe_ns("frame_build", t_sent[0] - t0)
            return frame

        def free_unconsumed():
            # a failure BEFORE build() ran (dead worker at connect, client
            # closed, cancellation) leaves the popped entries ours to free
            if not consumed[0]:
                for h in handles:
                    eng.entry_free(h)

        try:
            client = await self._worker_client(worker_addr)
            reply = await client.call_frame(build, timeout=None)
            if traced:
                t_reply = time.monotonic_ns()
                if "srv_ns" in reply:
                    # see the Python-batch site: a tracing-off worker's
                    # reply carries no srv_ns — skip, don't absorb exec time
                    hops.observe_ns(
                        "wire_rtt", t_reply - t_sent[0] - reply["srv_ns"])
        except (RpcError, ConnectionError) as e:
            free_unconsumed()
            self.schedule(self._return_lease_quiet(
                lease["daemon_address"], lease["lease_id"]))
            if q is None:
                q = self._push_queues.setdefault(key, collections.deque())
            if cached:
                # stale cached lease (worker reaped between tasks): retry
                # transparently — the encoded entries are gone (freed or
                # consumed), so the retry rides the Python queue
                self._drop_pooled_leases_from(lease["daemon_address"])
                for spec in reversed(specs):
                    self._hop_enqueue_stamp(spec)
                    q.appendleft((spec, None))
            else:
                err = WorkerCrashedError(
                    f"worker at {worker_addr} died mid-task: {e}")
                for spec in specs:
                    self._fast_lane_retry(key, q, spec, err)
            return True
        except BaseException as e:
            # close()/feeder cancellation mid-push: don't strand the lease,
            # the native entries, or the waiting submissions
            free_unconsumed()
            self.schedule(self._return_lease_quiet(
                lease["daemon_address"], lease["lease_id"]))
            err = WorkerCrashedError(f"submission aborted: {e}")
            for spec in specs:
                self._fail_task(spec, err)
                self._untrack_submission(spec)
            raise
        self._lease_pool_put(key, lease)
        for spec, r in zip(specs, reply["replies"]):
            try:
                self._record_task_reply(spec, r)
            except Exception as e:  # noqa: BLE001 — per-task failure
                self._fail_task(spec, e)
                self._untrack_submission(spec)
                continue
            sub = self._submissions.get(spec.task_id.binary())
            self._record_lineage(spec, sub["keepalive"] if sub else [])
            self._untrack_submission(spec)
        if traced:
            hops.observe_ns("completion", time.monotonic_ns() - t_reply)
        return True

    def _fast_lane_retry(self, key: tuple, q: collections.deque,
                         spec: TaskSpec, err: Exception):
        """Feeder-side retry bookkeeping for fast-lane submissions (no
        per-task coroutine to re-run): requeue until the spec's retry budget
        is spent, then fail the returns."""
        sub = self._submissions.get(spec.task_id.binary())
        if sub is None:
            return
        if sub.get("cancelled"):
            self._fail_task(spec, TaskCancelledError(
                f"task {spec.name or spec.function_key} was cancelled"))
            self._untrack_submission(spec)
            return
        sub["attempts"] = sub.get("attempts", 0) + 1
        if sub["attempts"] > spec.max_retries:
            self._fail_task(spec, WorkerCrashedError(
                f"task {spec.name or spec.function_key} failed after "
                f"{spec.max_retries} retries: {err}"))
            self._untrack_submission(spec)
            return
        sub["state"] = "pending"
        sub["worker"] = ""
        self._hop_enqueue_stamp(spec)
        q.append((spec, None))

    def _drop_pooled_leases_from(self, daemon_address: str):
        """A worker from `daemon_address` just failed: every cached lease
        from that daemon is suspect (node death kills them all at once)."""
        for pool in self._lease_pools.values():
            suspect = [
                lease for lease in pool["idle"]
                if lease["daemon_address"] == daemon_address
            ]
            if suspect:
                pool["idle"] = [
                    lease for lease in pool["idle"] if lease not in suspect
                ]
                for lease in suspect:
                    self.schedule(self._return_lease_quiet(
                        daemon_address, lease["lease_id"]))

    async def _lease_pool_sweep(self):
        """Return leases idle past worker_lease_idle_s so cached capacity
        doesn't starve other drivers (reference: lease idle timeout)."""
        period = GLOBAL_CONFIG.get("worker_lease_idle_s")
        while not self._closed:
            await asyncio.sleep(period / 2)
            cutoff = time.monotonic() - period
            for key, pool in list(self._lease_pools.items()):
                keep = []
                for lease in pool["idle"]:
                    if lease["idle_since"] < cutoff:
                        spawn(self._return_lease_quiet(
                            lease["daemon_address"], lease["lease_id"]))
                    else:
                        keep.append(lease)
                pool["idle"] = keep
                if not keep and not pool["waiters"]:
                    self._lease_pools.pop(key, None)

    def _note_hop_spans(self, spec: TaskSpec, reply: dict,
                        t_send_wall: float):
        """Fold one EXPLICITLY-traced task's hop stamps into span records so
        timeline() shows the call split into its hops (root-sentinel tasks
        fold into the rt_task_hop_seconds histograms only — per-task span
        records at 100k/s would be their own overhead)."""
        ctx = spec.trace_ctx
        if not isinstance(ctx, dict) or not ctx.get("trace_id"):
            return
        hop = getattr(spec, "_hop", None)
        if hop is None or "enq_ns" not in hop:
            return
        from ray_tpu.util import tracing

        wall0 = hop["wall0"]
        enq_wall = wall0 + (hop["enq_ns"] - hop["sub_ns"]) / 1e9
        segments = [("hop:submit", wall0, enq_wall),
                    ("hop:queue", enq_wall, t_send_wall)]
        whops = reply.get("hops") or {}
        recv = whops.get("recv")
        end = whops.get("end")
        if recv:
            segments.append(("hop:flight", t_send_wall, recv))
            if whops.get("start"):
                segments.append(("hop:exec_wait", recv, whops["start"]))
        if end:
            segments.append(("hop:reply", end, time.time()))
        for name, start, stop in segments:
            tracing.record_span({
                "trace_id": ctx["trace_id"],
                "span_id": os.urandom(8).hex(),
                "parent_span_id": ctx.get("parent_span_id", ""),
                "name": name, "start": start, "end": max(start, stop),
            }, task_id=spec.task_id.binary())

    def _record_task_reply(self, spec: TaskSpec, reply: dict):
        sub = self._submissions.get(spec.task_id.binary())
        if (sub is not None and sub.get("cancelled") and all(
                oid.binary() in self.memory_store.objects
                for oid in spec.return_ids())):
            # cancelled with returns already resolved to TaskCancelledError:
            # drop the stale reply from the interrupted (or completed-late)
            # execution instead of overwriting the cancellation
            return
        if reply.get("error"):
            err = reply["error"]
            exc = TaskError(
                spec.name or spec.function_key, err.get("traceback", ""),
            )
            if err.get("pickled"):
                try:
                    exc = self._deserialize_error(err["pickled"])
                except Exception:  # noqa: BLE001
                    pass
            self._fail_task(spec, exc)
            return
        if spec.is_streaming:
            # items flowed via report_stream_item; the final reply closes the
            # stream (backup in case the last report raced the reply)
            self._stream_end(spec.task_id.binary(), reply.get("stream_end", 0))
            return
        for ret in reply["returns"]:
            self._record_return_entry(ret)

    # ------------------------------------------------------------------
    # lineage reconstruction — delegated to the recovery manager
    # (reference: object_recovery_manager.h; see _private.recovery for the
    # per-object state machine and the authoritative-death trigger)
    # ------------------------------------------------------------------

    def _record_lineage(self, spec: TaskSpec, keepalive):
        self.recovery.record_lineage(spec, keepalive)

    async def rpc_reconstruct_object(self, conn_id: int, payload: dict) -> dict:
        """A borrower observed the object's store node die; recover it."""
        ok = await self.recovery.recover(
            payload["object_id"], payload.get("failed_node")
        )
        return {"ok": ok} if ok else {"ok": False, "error": "no lineage for object"}

    async def _acquire_lease(self, spec: TaskSpec) -> dict:
        address = self.daemon_address
        hops = 0
        last_warn = 0.0
        # stable per-logical-request key: retries after a dropped/timed-out
        # call attach to the daemon's original (possibly still queued)
        # request instead of double-granting
        request_key = os.urandom(16)
        while True:
            try:
                client = await self._owner_client(address)
            except (RpcConnectionLost, ConnectionError, OSError):
                if address != self.daemon_address:
                    # spillback target died before gossip caught up: route
                    # back through the local daemon rather than failing the
                    # submit (it re-picks from the refreshed view)
                    address = self.daemon_address
                    hops = 0
                    await asyncio.sleep(0.2)
                    continue
                raise
            payload = {
                "resources": spec.resources.to_wire(),
                "strategy": spec.strategy.to_wire(),
                "job_id": self.job_id.binary(),
                "hops": hops,
                "request_key": request_key,
            }
            if (spec.runtime_env or {}).get("env_key"):
                # isolating env (pip venv / working_dir): the daemon must
                # grant a worker built for exactly this env
                payload["runtime_env"] = spec.runtime_env
            inner = spawn(self._lease_call_with_deadline(client, payload))
            try:
                reply = await asyncio.shield(inner)
            except asyncio.CancelledError:
                # ray_tpu.cancel() of a queued task: the daemon may still
                # grant this request later — return that orphan lease so its
                # resources don't leak
                inner.add_done_callback(
                    functools.partial(self._return_orphan_lease, address)
                )
                raise
            except (RpcConnectionLost, ConnectionError):
                # connection-level loss ONLY: a server-side error reply must
                # still propagate (rerouting it would loop forever against a
                # healthy-but-erroring daemon)
                if address != self.daemon_address:
                    # spillback daemon died mid-call: reroute via local.
                    # It may have granted just before the blip — request_key
                    # idempotency is per-daemon, so the rerouted request
                    # would double-grant and leak the first worker forever
                    # (advisor r2). Best-effort release of the possible
                    # orphan, and a fresh key so a future spillback back to
                    # this daemon can't attach to the released grant.
                    spawn(self._cancel_lease_request_quiet(
                        address, request_key))
                    request_key = os.urandom(16)
                    address = self.daemon_address
                    hops = 0
                    await asyncio.sleep(0.2)
                    continue
                raise
            if reply.get("granted"):
                reply["daemon_address"] = address
                return reply
            if reply.get("spillback"):
                address = reply["spillback"]
                hops += 1
                continue
            if reply.get("infeasible"):
                # The reference keeps infeasible work queued — a node with the
                # right resources may join (autoscaling, gossip lag). Warn
                # periodically and retry.
                now = time.monotonic()
                if now - last_warn > 30:
                    last_warn = now
                    logger.warning(
                        "task %s requires resources %s which no live node "
                        "currently provides; waiting",
                        spec.name or spec.function_key, spec.resources.to_dict(),
                    )
                await asyncio.sleep(0.5)
                address = self.daemon_address
                hops = 0
                continue
            if reply.get("retry"):
                await asyncio.sleep(0.2)
                address = self.daemon_address
                # fresh routing attempt: without this, spillback→retry cycles
                # accumulate hops to the cap and the local daemon then queues
                # the lease locally even when only a remote node can host it
                hops = 0
                continue
            if reply.get("infeasible_in_pg"):
                # permanent: the request exceeds the bundle's TOTAL
                # reservation and can never be granted — fail loudly
                raise RayTpuError(
                    f"task {spec.name or spec.function_key} can never be "
                    f"placed: {reply.get('error')}")
            raise RayTpuError(f"lease request failed: {reply}")

    async def _lease_call_with_deadline(self, client, payload: dict) -> dict:
        """request_lease with a per-attempt deadline, retried forever: the
        lease may legitimately stay queued on a busy daemon (the reference
        holds RequestWorkerLease open indefinitely), while a dropped call is
        recovered after one deadline because the request_key makes retries
        idempotent (daemon coalesces them onto the original request)."""
        deadline_s = GLOBAL_CONFIG.get("lease_request_timeout_s")
        while True:
            try:
                return await client.call("request_lease", payload,
                                         timeout=deadline_s)
            except asyncio.TimeoutError:
                await asyncio.sleep(0.05)
            except RpcError as e:
                # timeouts mean the lease is (still) queued — keep waiting.
                # Connection-level failures mean the daemon is gone and must
                # propagate so _submit_with_retries re-routes/fails the task.
                if isinstance(e.__cause__, asyncio.TimeoutError):
                    await asyncio.sleep(0.05)
                    continue
                raise

    async def _cancel_lease_request_quiet(
        self, daemon_address: str, request_key: bytes
    ):
        """Ask `daemon_address` to release whatever lease it may have granted
        under `request_key` (the connection died mid-request_lease and the
        caller rerouted, so a grant would never be claimed). Best-effort with
        brief retries — the daemon was reachable moments ago and connection
        blips heal; if it truly died, its leases die with it."""
        for _ in range(5):
            try:
                client = await self._owner_client(daemon_address)
                await client.call(
                    "cancel_lease_request",
                    {"request_key": request_key}, timeout=5.0)
                return
            except Exception:  # noqa: BLE001 — best-effort
                await asyncio.sleep(0.5)

    def _return_orphan_lease(self, daemon_address: str, t: asyncio.Task):
        if t.cancelled() or t.exception() is not None:
            return
        reply = t.result()
        if reply.get("granted"):
            self.schedule(self._return_lease_quiet(daemon_address, reply["lease_id"]))

    async def _return_lease_quiet(self, daemon_address: str, lease_id,
                                  deadline: Optional[float] = None):
        try:
            client = await self._owner_client(daemon_address)
            await client.call("return_lease", {"lease_id": lease_id},
                              timeout=5, deadline=deadline)
        except Exception:  # noqa: BLE001 — daemon may be gone
            pass

    async def _worker_client(self, address: str) -> RpcClient:
        client = self._worker_clients.get(address)
        if client is None:
            client = RpcClient(address, name="to-worker", retries=0)
            await client.connect()
            self._worker_clients[address] = client
        return client

    # ------------------------------------------------------------------
    # compiled-graph channel plane (reference: experimental/channel/
    # torch_tensor_accelerator_channel.py — cross-node channel endpoints)
    # ------------------------------------------------------------------

    def register_dag_channel(self, dag_id: str, edge: str, chan) -> None:
        """Expose a locally-created ring so cross-node writers can reach it
        through rpc_chan_write. Called from the reader's executor thread."""
        self._dag_channels[(dag_id, edge)] = chan

    def unregister_dag_channel(self, dag_id: str, edge: str) -> None:
        self._dag_channels.pop((dag_id, edge), None)
        self._dag_channel_locks.pop((dag_id, edge), None)
        self._dag_channel_seqs.pop((dag_id, edge), None)

    async def quiesce_dag_channel(self, dag_id: str, edge: str) -> None:
        """Teardown half of the rpc_chan_write race fix: unregister the
        edge AFTER draining its per-edge lock, so no in-flight write still
        holds the chan when the caller unpins the ring (the ring must be
        close()d first so a blocked writer fails fast instead of holding
        the lock until its timeout)."""
        key = (dag_id, edge)
        lock = self._dag_channel_locks.get(key)
        if lock is not None:
            async with lock:
                self.unregister_dag_channel(dag_id, edge)
        else:
            self.unregister_dag_channel(dag_id, edge)

    async def rpc_chan_write(self, conn_id: int, payload: dict) -> dict:
        """Write one slot into a ring this process reads (the cross-node
        half of a compiled-graph edge). Per-edge FIFO lock keeps slot order
        equal to RPC arrival order even though writes block in a thread.

        `seq` is the writer's per-edge slot counter and makes the write
        IDEMPOTENT: the RPC client retries on lost connections, and a
        retry of a write the ring already took must not land a second
        copy (a duplicate slot would shift every later execution's value
        on that edge). An edge has exactly one writer (SPSC), so a simple
        last-applied watermark suffices."""
        key = (payload["dag_id"], payload["edge"])
        deadline = time.monotonic() + float(payload.get("open_timeout", 15))
        while key not in self._dag_channels:
            # the reader registers at executor-loop start; a writer racing
            # ahead of it parks here rather than failing the edge
            if time.monotonic() >= deadline:
                return {"error": "no_such_channel"}
            await asyncio.sleep(0.02)
        lock = self._dag_channel_locks.get(key)
        if lock is None:
            lock = self._dag_channel_locks[key] = asyncio.Lock()
        chan = self._dag_channels[key]
        timeout = payload.get("timeout")
        seq = payload.get("seq")
        async with lock:
            # re-check under the lock: teardown may have unregistered the
            # edge between the lookup above and acquiring the lock — writing
            # into an unpinned ring is silent shm corruption (ADVICE r5 #3)
            if self._dag_channels.get(key) is not chan:
                return {"error": "no_such_channel"}
            if seq is not None and seq <= self._dag_channel_seqs.get(key, -1):
                return {"ok": True, "duplicate": True}
            try:
                await asyncio.to_thread(
                    chan.write_bytes, payload["payload"],
                    None if timeout is None else float(timeout))
            except TimeoutError:
                return {"error": "full"}
            except EOFError:
                # ring closed by the reader (teardown): fail fast
                return {"error": "closed"}
            except ValueError as exc:  # oversized payload
                return {"error": f"value:{exc}"}
            if seq is not None:
                self._dag_channel_seqs[key] = seq
        return {"ok": True}

    # ------------------------------------------------------------------
    # actors (reference: actor_task_submitter.h:69, gcs_actor_manager.h:94)
    # ------------------------------------------------------------------

    _ACTOR_STATE_RANK = {
        pb.ACTOR_PENDING: 0, pb.ACTOR_RESTARTING: 1,
        pb.ACTOR_ALIVE: 2, pb.ACTOR_DEAD: 3,
    }

    def _on_actor_update(self, message: dict):
        st = self._actor_states.get(message["actor_id"])
        if st is None:
            return
        # per-restart-cycle monotonic version: PENDING(0) < RESTARTING(n) <
        # ALIVE(n) < DEAD(n). Poll replies and pubsub pushes interleave
        # without ordering; applying a stale one must never regress state
        # (it would fabricate an incarnation and poison seq numbering).
        version = (message.get("num_restarts", 0),
                   self._ACTOR_STATE_RANK.get(message["state"], 0))
        if version < st.applied_version:
            return
        st.applied_version = version
        st.state = message["state"]
        st.death_cause = message.get("death_cause", "")
        if st.state == pb.ACTOR_ALIVE:
            if st.address != message["worker_address"]:
                if st.client is not None:
                    old = st.client
                    st.client = None
                    self.schedule(old.close())
                st.address = message["worker_address"]
                if st.ever_alive:
                    # replacement worker process = fresh incarnation: its
                    # executor expects seq to restart at 1
                    st.incarnation += 1
                    st.seq = 0
            st.ever_alive = True
        elif st.state in (pb.ACTOR_RESTARTING, pb.ACTOR_DEAD):
            st.address = ""
            if st.client is not None:
                old = st.client
                st.client = None
                self.schedule(old.close())
            if st.state == pb.ACTOR_DEAD:
                st.creation_keepalive = []
        if st.event is not None:
            st.event.set()

    def _actor_state(self, actor_id: bytes) -> ActorHandleState:
        st = self._actor_states.get(actor_id)
        if st is None:
            st = ActorHandleState(actor_id)
            st.event = asyncio.Event()
            self._actor_states[actor_id] = st
        return st

    async def create_actor(
        self,
        class_key: str,
        args: tuple,
        kwargs: dict,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
        is_async: bool = False,
        strategy: Optional[SchedulingStrategy] = None,
        name: str = "",
        namespace: str = "",
        detached: bool = False,
        runtime_env: Optional[dict] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
        method_meta: Optional[Dict[str, dict]] = None,
        drain_cooperative: bool = False,
    ) -> ActorID:
        with self._lock:
            self._actor_index += 1
            actor_id = ActorID.of(self.job_id, self.current_task_id, self._actor_index)
        await self._register_actor_with_id(
            actor_id, class_key, args, kwargs,
            resources=resources, max_restarts=max_restarts,
            max_task_retries=max_task_retries, max_concurrency=max_concurrency,
            is_async=is_async, strategy=strategy, name=name,
            namespace=namespace, detached=detached, runtime_env=runtime_env,
            concurrency_groups=concurrency_groups, method_meta=method_meta,
            drain_cooperative=drain_cooperative,
        )
        return actor_id

    def create_actor_nowait(self, class_obj, class_key: str, args: tuple,
                            kwargs: dict, **ctor_opts) -> ActorID:
        """Loop-thread-safe actor creation (from inside async actors):
        allocate the id synchronously, register in a spawned task. Callers
        interact through the handle; method submissions wait for ALIVE."""
        with self._lock:
            self._actor_index += 1
            actor_id = ActorID.of(self.job_id, self.current_task_id, self._actor_index)
        st = self._actor_state(actor_id.binary())

        async def finish():
            try:
                await self.export_function(class_key, class_obj)
                await self._register_actor_with_id(
                    actor_id, class_key, args, kwargs, **ctor_opts
                )
            except Exception as e:  # noqa: BLE001 — surface via actor state
                st.state = pb.ACTOR_DEAD
                st.death_cause = f"actor registration failed: {e}"
                if st.event is not None:
                    st.event.set()

        spawn(finish())
        return actor_id

    async def _register_actor_with_id(
        self,
        actor_id: ActorID,
        class_key: str,
        args: tuple,
        kwargs: dict,
        resources: Optional[Dict[str, float]] = None,
        max_restarts: int = 0,
        max_task_retries: int = 0,
        max_concurrency: int = 1,
        is_async: bool = False,
        strategy: Optional[SchedulingStrategy] = None,
        name: str = "",
        namespace: str = "",
        detached: bool = False,
        runtime_env: Optional[dict] = None,
        concurrency_groups: Optional[Dict[str, int]] = None,
        method_meta: Optional[Dict[str, dict]] = None,
        drain_cooperative: bool = False,
    ) -> None:
        from ray_tpu._private.runtime_env_mgr import prepare_runtime_env

        runtime_env = await prepare_runtime_env(runtime_env, self)
        wire_args = await self.serialize_args(args, kwargs)
        pyrefs = [a.pop("_pyref") for a in wire_args if "_pyref" in a]
        spec = TaskSpec(
            trace_ctx=_trace_inject(),
            task_id=TaskID.for_actor_creation(actor_id),
            job_id=self.job_id,
            kind=pb.TASK_KIND_ACTOR_CREATION,
            function_key=class_key,
            args=wire_args,
            resources=ResourceSet(resources if resources is not None else {"CPU": 1.0}),
            strategy=strategy or SchedulingStrategy(),
            owner_worker_id=self.worker_id.binary(),
            owner_address=self.address,
            actor_id=actor_id,
            max_restarts=max_restarts,
            max_task_retries=max_task_retries,
            max_concurrency=max_concurrency,
            is_async_actor=is_async,
            concurrency_groups=dict(concurrency_groups or {}),
            method_meta=dict(method_meta or {}),
            runtime_env={**(runtime_env or {}), "namespace": namespace,
                         "detached": detached},
            name=name,
            drain_cooperative=drain_cooperative,
        )
        self._actor_state(actor_id.binary()).creation_keepalive = pyrefs
        await self.control.call("register_actor", {"spec": spec.to_wire()})

    async def wait_actor_alive(self, actor_id: bytes,
                               timeout: Optional[float] = None):
        st = self._actor_state(actor_id)
        if timeout is None:
            # track the control store's creation budget (plus margin for its
            # retries) — a caller giving up before the scheduler does turns
            # recoverable delays into spurious ActorUnavailableErrors
            timeout = GLOBAL_CONFIG.get("actor_creation_timeout_s") + 30.0
        deadline = time.monotonic() + timeout
        while st.state != pb.ACTOR_ALIVE:
            if st.state == pb.ACTOR_DEAD:
                raise ActorDiedError(f"actor failed to start: {st.death_cause}")
            # poll as fallback for missed pubsub
            reply = await self.control.call("get_actor_info", {"actor_id": actor_id})
            if reply["actor"]:
                self._on_actor_update(reply["actor"])
            if st.state == pb.ACTOR_ALIVE:
                break
            if time.monotonic() > deadline:
                raise ActorUnavailableError("timed out waiting for actor to start")
            await asyncio.sleep(0.1)

    async def submit_actor_task(self, actor_id: bytes, method_name: str,
                                args: tuple, kwargs: dict, **opts):
        """Thin async shim over the one real submission path (the nowait
        one) — kept for API compatibility; a second seq-minting path would
        have to stay lock-consistent with it for nothing."""
        return self.submit_actor_task_nowait(
            actor_id, method_name, args, kwargs, **opts)

    def _next_seq(self, st: ActorHandleState) -> int:
        st.seq += 1
        return st.seq

    async def _submit_actor_with_retries(self, st: ActorHandleState, spec: TaskSpec,
                                         max_task_retries: int, keepalive):
        try:
            await self._submit_actor_with_retries_inner(
                st, spec, max_task_retries, keepalive)
        except asyncio.CancelledError:
            raise
        except Exception as e:  # noqa: BLE001 — e.g. ObjectLostError from args
            # exceptions outside the inner loop's handled set: the caller's
            # refs must resolve (not hang), and the executor's sequence slot
            # must be tombstoned or later seqs eat the ordering-gap timeout
            self._fail_task(spec, e if isinstance(e, RayTpuError)
                            else RayTpuError(f"actor submit failed: {e}"))
            spec.cancelled = True
            self.schedule(self._push_tombstone_quiet(st, spec))
        finally:
            # catch-all: a spec that terminally failed BEFORE its push (args
            # lost, cancellation, actor death) must still release its push
            # turn or every later sequence number blocks forever
            self._release_push_turn(st, spec)

    async def _push_tombstone_quiet(self, st: ActorHandleState, spec: TaskSpec):
        """Best-effort delivery of a cancelled tombstone so the executor's
        sequence window advances past a terminally-failed spec."""
        try:
            await self.wait_actor_alive(st.actor_id, timeout=30)
            if st.client is None:
                st.client = RpcClient(st.address, name="to-actor", retries=0)
                await st.client.connect()
            await self._actor_push(st, spec)
        except Exception:  # noqa: BLE001 — the gap timeout is the fallback
            pass

    async def _submit_actor_with_retries_inner(
            self, st: ActorHandleState, spec: TaskSpec,
            max_task_retries: int, keepalive):
        attempt = 0
        while True:
            sub = self._submissions.get(spec.task_id.binary())
            if sub is not None and sub["cancelled"]:
                # Push a tombstone instead of dropping the spec: its sequence
                # slot must advance on the executor or every later task from
                # this caller stalls on the hole (ordered actors never
                # reorder). The executor replies TaskCancelledError without
                # running the method.
                spec.cancelled = True
            try:
                # resolve dependencies before delivery: an actor slot blocked
                # on a queued producer would stall the whole ordered queue
                await self._wait_args_ready(spec)
                await self.wait_actor_alive(st.actor_id)
                if spec.incarnation != st.incarnation:
                    # the actor restarted since this spec was stamped: its
                    # fresh executor numbers from 1, so re-stamp into the
                    # current incarnation's sequence (order across a crash is
                    # best-effort, as in the reference's restart epoch).
                    # _next_seq under the lock: driver threads mint seqs
                    # concurrently via submit_actor_task_nowait
                    spec.incarnation = st.incarnation
                    with self._lock:
                        spec.seq_no = self._next_seq(st)
                if st.client is None:
                    st.client = RpcClient(st.address, name="to-actor", retries=0)
                    await st.client.connect()
                client = st.client
                if sub is not None:
                    if sub["cancelled"]:
                        spec.cancelled = True  # flag set while waiting above
                    sub["state"] = "running"
                    sub["worker"] = st.address
                reply = await self._actor_push(st, spec)
                self._record_task_reply(spec, reply)
                return
            except asyncio.CancelledError:
                self._fail_task(spec, TaskCancelledError(
                    f"actor task {spec.method_name} was cancelled"))
                raise
            except _ActorRestartedWhileQueued:
                # parked in the push queue across a restart: loop to restamp
                # into the new incarnation (never delivered — does not
                # consume a user retry; bounded by actual restarts)
                continue
            except (ActorDiedError, ActorUnavailableError) as e:
                self._fail_task(spec, e)
                return
            except (RpcError, ConnectionError, asyncio.TimeoutError) as e:
                if sub is not None and sub["cancelled"]:
                    self._fail_task(spec, TaskCancelledError(
                        f"actor task {spec.method_name} was cancelled"))
                    return
                attempt += 1
                if st.state == pb.ACTOR_ALIVE:
                    # connection died but no death report yet: nudge state
                    reply = await self.control.call(
                        "get_actor_info", {"actor_id": st.actor_id}
                    )
                    if reply["actor"]:
                        self._on_actor_update(reply["actor"])
                if attempt > max_task_retries:
                    self._fail_task(
                        spec,
                        ActorUnavailableError(
                            f"actor task {spec.method_name} failed: {e}"
                        ) if st.state != pb.ACTOR_DEAD else ActorDiedError(
                            f"actor died: {st.death_cause or e}"
                        ),
                    )
                    return
                await asyncio.sleep(min(0.2 * (2 ** attempt), 5.0))

    async def _await_push_turn(self, st: ActorHandleState, spec: TaskSpec):
        """Block until every lower sequence number of this incarnation has
        been pushed (or terminally failed). Retried pushes (seq <= push_next)
        pass straight through. A spec whose incarnation is now STALE (the
        actor restarted while it was parked here) must NOT be pushed — it
        would execute unordered on the fresh executor ahead of its restamped
        predecessors — so it is bounced back to the retry loop for
        restamping."""
        if spec.seq_no < 0:
            return
        while True:
            if spec.incarnation > st.push_incarnation:
                # actor restarted: fresh incarnation numbers from 1
                st.push_incarnation = spec.incarnation
                st.push_next = 1
                self._wake_push_waiters(st, wake_all=True)
            if spec.incarnation < st.push_incarnation:
                raise _ActorRestartedWhileQueued(
                    f"incarnation {spec.incarnation} superseded by "
                    f"{st.push_incarnation}")
            if spec.seq_no <= st.push_next:
                return
            fut = self.loop.create_future()
            st.push_waiters[spec.seq_no] = fut
            try:
                await fut
            finally:
                if st.push_waiters.get(spec.seq_no) is fut:
                    st.push_waiters.pop(spec.seq_no, None)

    def _release_push_turn(self, st: ActorHandleState, spec: TaskSpec):
        """Idempotent: the push went out (or the spec terminally failed) —
        let the next sequence number proceed. Handles an incarnation the
        await path never saw (a spec restamped then failed before pushing):
        dropping such a release would deadlock every later submission."""
        if spec.seq_no < 0:
            return
        if spec.incarnation > st.push_incarnation:
            st.push_incarnation = spec.incarnation
            st.push_next = 1
            self._wake_push_waiters(st, wake_all=True)
        if spec.incarnation != st.push_incarnation:
            return  # stale incarnation: its ordering domain is gone
        if spec.seq_no + 1 > st.push_next:
            st.push_next = spec.seq_no + 1
            self._wake_push_waiters(st)

    @staticmethod
    def _wake_push_waiters(st: ActorHandleState, wake_all: bool = False):
        """Wake exactly the waiters whose turn arrived (keyed by seq — a
        broadcast would cost O(n^2) wakeups over a deep backlog)."""
        if wake_all:
            waiters, st.push_waiters = st.push_waiters, {}
            for fut in waiters.values():
                if not fut.done():
                    fut.set_result(True)
            return
        ready = [s for s in st.push_waiters if s <= st.push_next]
        for s in ready:
            fut = st.push_waiters.pop(s)
            if not fut.done():
                fut.set_result(True)

    async def _actor_push(self, st: ActorHandleState, spec: TaskSpec) -> dict:
        """Coalesced actor-task delivery: enqueue and let one per-actor pump
        ship batches over the connection (reference: pipelined PushTask on
        the actor client). Pushes are RELEASED in sequence order (see
        _await_push_turn); the executor's reorder buffer then only covers
        in-flight wire/dispatch reordering.

        CONCURRENT actors (async/threaded/concurrency groups) bypass the
        pump entirely: their executions overlap on the worker, and a batched
        reply would couple a fast method's completion to the slowest task in
        its batch (head-of-line blocking across concurrency lanes)."""
        if st.concurrent:
            client = st.client
            if client is None:
                raise RpcConnectionLost("actor client not connected")
            return await client.call(
                "push_task", {"spec": spec.to_wire()}, timeout=None)
        await self._await_push_turn(st, spec)
        fut = self.loop.create_future()
        st.push_queue.append((spec, fut))
        self._release_push_turn(st, spec)
        if not st.pump_running:
            st.pump_running = True
            spawn(self._actor_push_pump(st))
        return await fut

    async def _actor_push_pump(self, st: ActorHandleState):
        """Drain the queue into batches and ship them WITHOUT awaiting
        replies between sends. An ordered actor may block one delivered
        batch in its reorder buffer until a lower seq (still queued here)
        arrives — a pump that awaited each reply before sending the next
        batch would deadlock on exactly that. Sorting each drain by
        (incarnation, seq) keeps lower seqs no later than higher ones."""
        try:
            while st.push_queue:
                maxb = GLOBAL_CONFIG.get("push_batch_max")
                drained = [
                    item for item in (
                        st.push_queue.popleft()
                        for _ in range(len(st.push_queue))
                    ) if not item[1].done()
                ]
                drained.sort(key=lambda it: (it[0].incarnation, it[0].seq_no))
                for i in range(0, len(drained), maxb):
                    spawn(self._actor_send_batch(st, drained[i:i + maxb]))
                if not st.push_queue:
                    return
        finally:
            st.pump_running = False
            if st.push_queue:
                # enqueued in the window after the loop saw empty
                st.pump_running = True
                spawn(self._actor_push_pump(st))

    async def _actor_send_batch(self, st: ActorHandleState, batch: list):
        client = st.client
        try:
            if client is None:
                raise RpcConnectionLost("actor client not connected")
            reply = await client.call(
                "push_task_batch",
                {"specs": [s.to_wire() for s, _ in batch]},
                timeout=None,
            )
        except BaseException as e:  # noqa: BLE001 — per-call retry loops decide
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(
                        e if isinstance(e, Exception)
                        else RpcConnectionLost(f"push aborted: {e}"))
            if not isinstance(e, Exception):
                raise
            return
        for (_, fut), r in zip(batch, reply["replies"]):
            if not fut.done():
                fut.set_result(r)

    async def kill_actor(self, actor_id: bytes, no_restart: bool = True):
        await self.control.call(
            "kill_actor", {"actor_id": actor_id, "no_restart": no_restart}
        )

    # Actor-handle GC (reference: actor handles participate in reference
    # counting, python/ray/actor.py — an unnamed, non-detached actor dies when
    # the creator's last handle goes out of scope).
    def add_actor_handle_ref(self, actor_id: bytes):
        with self._lock:
            self._owned_actor_handles[actor_id] = (
                self._owned_actor_handles.get(actor_id, 0) + 1
            )

    def remove_actor_handle_ref(self, actor_id: bytes):
        with self._lock:
            n = self._owned_actor_handles.get(actor_id, 0) - 1
            if n > 0:
                self._owned_actor_handles[actor_id] = n
                return
            self._owned_actor_handles.pop(actor_id, None)
        self.schedule(self._kill_on_gc(actor_id))

    async def _kill_on_gc(self, actor_id: bytes):
        try:
            await self.kill_actor(actor_id, no_restart=True)
        except Exception:  # noqa: BLE001 — shutdown race
            pass

    # ------------------------------------------------------------------
    # executor side (workers; reference: core_worker.cc:3672 HandlePushTask)
    # ------------------------------------------------------------------

    async def rpc_push_task(self, conn_id: int, payload: dict) -> dict:
        assert self.executor is not None, "push_task on a non-worker process"
        spec = TaskSpec.from_wire(payload["spec"])
        return await self.executor.execute(spec)

    async def rpc_push_task_batch(self, conn_id: int, payload: dict) -> dict:
        """Pipelined batch delivery (reference: back-to-back PushNormalTask
        on one granted lease): tasks run sequentially — the lease grants one
        worker — and the replies travel in one frame. The reply carries the
        server-side residency (`srv_ns`) so the owner's wire_rtt hop
        excludes execution time without any cross-host clock comparison."""
        assert self.executor is not None, "push_task_batch on a non-worker process"
        traced = hops.enabled()
        t_recv = time.monotonic_ns() if traced else 0
        specs = [TaskSpec.from_wire(w) for w in payload["specs"]]
        if traced:
            recv_wall = time.time()
            for spec in specs:
                spec._recv_ns = t_recv
                spec._recv_wall = recv_wall
        reply = {"replies": await self.executor.execute_batch(specs)}
        if traced:
            reply["srv_ns"] = time.monotonic_ns() - t_recv
        return reply

    async def resolve_arg(self, arg: dict) -> Any:
        if "inline" in arg:
            return ser.deserialize(arg["inline"], copy_buffers=True)
        ref = ObjectRef(
            ObjectID(arg["ref"]), arg["owner"], arg["owner_worker_id"], _register=False
        )
        if self.owns(ref):
            return await self._get_one(ref)
        # check local shm first (zero-copy fast path)
        if self.store is not None and self.store.contains(ref.object_id()):
            res = self.store.get(ref.object_id())
            if res is not None:
                view, meta = res
                if meta == META_ERROR:
                    try:
                        raise self._deserialize_error(bytes(view))
                    finally:
                        self.store.release(ref.object_id())
                return ser.deserialize(
                    view, copy_buffers=False,
                    release=functools.partial(self.store.release, ref.object_id()),
                )
        return await self._fetch_via_owner(ref, None, copy_buffers=True)

    async def _create_with_spill(self, oid: ObjectID, size: int,
                                 meta: int = META_NORMAL) -> memoryview:
        """create() with BACKPRESSURE: a full store asks the daemon to spill
        and then retries with backoff until capacity appears (spilling,
        eviction, or consumers releasing refs) or the grace period expires
        (reference: plasma create_request_queue.h — creates queue under
        memory pressure instead of failing immediately)."""
        try:
            return self.store.create(oid, size, meta)
        except ObjectStoreFullError:
            pass
        deadline = time.monotonic() + GLOBAL_CONFIG.get(
            "object_store_full_timeout_s")
        delay = GLOBAL_CONFIG.get("object_store_full_delay_s")
        last_exc: Optional[Exception] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            # a dead/hung daemon propagates (as before this backpressure
            # existed) rather than masquerading as a full store. The fixed
            # generous timeout lets a SLOW-but-working multi-GB spill finish
            # (overrunning the grace period by at most one call is better
            # than failing a create the spill was about to satisfy).
            await self.daemon.call(
                "spill_now", {"need_bytes": size}, timeout=120)
            try:
                return self.store.create(oid, size, meta)
            except ObjectStoreFullError as e:
                last_exc = e
            await asyncio.sleep(min(delay, max(0.0, deadline - time.monotonic())))
            delay = min(delay * 2, 1.0)
        raise ObjectStoreFullError(
            f"object store still full after "
            f"{GLOBAL_CONFIG.get('object_store_full_timeout_s')}s waiting "
            f"for capacity ({size} bytes needed): {last_exc}")

    async def store_return(self, oid: ObjectID, sobj: ser.SerializedObject,
                           meta: int = META_NORMAL) -> dict:
        """Store one return value; small→inline reply, large→local shm."""
        if sobj.total_bytes <= self._inline_max:
            return {"object_id": oid.binary(), "inline": sobj.to_bytes(), "meta": meta}
        try:
            view = await self._create_with_spill(oid, sobj.total_bytes, meta)
            sobj.write_into(view)
            view.release()
            self.store.seal(oid)
        except FileExistsError:
            pass
        return {
            "object_id": oid.binary(),
            "inline": None,
            "location": {"daemon": self.daemon_address, "node_id": self.node_id_hex},
        }
