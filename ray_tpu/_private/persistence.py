"""Control-store persistence: snapshot + write-ahead log.

Capability parity with the reference's GCS store clients (reference:
src/ray/gcs/store_client/redis_store_client.h, in_memory_store_client.h and
the RAY_external_storage_namespace recovery flow): the control store appends
every table mutation to a WAL and periodically compacts into a snapshot; a
restarted control store replays snapshot + WAL and resumes serving with
nodes, actors, placement groups, jobs, and KV intact. Running actors are
unaffected by the outage — their records (including worker addresses) come
back, and daemons re-register on their next heartbeat.

Files (in `<dir>/`): `snapshot.msgpack` (atomic, whole-state) and
`wal.msgpack` (appended records). msgpack handles bytes keys/values natively
and self-frames, so recovery is a plain Unpacker scan that tolerates a torn
final record (crash mid-append).
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import msgpack

logger = logging.getLogger(__name__)

SNAPSHOT = "snapshot.msgpack"
WAL = "wal.msgpack"
WAL_OLD = "wal.old.msgpack"


def _read_records(path: str) -> list:
    records = []
    if os.path.exists(path):
        with open(path, "rb") as f:
            unpacker = msgpack.Unpacker(f, raw=False, strict_map_key=False)
            try:
                for rec in unpacker:
                    records.append(rec)
            except Exception:  # noqa: BLE001 — torn tail record
                logger.warning(
                    "dropping torn WAL tail after %d records (%s)",
                    len(records), path,
                )
    return records


class WalStore:
    """Append-only log with snapshot compaction.

    Compaction is two-phase so the (potentially large) state pack + fsync can
    run on a worker thread without losing concurrent appends: `rotate()` (on
    the event loop, cheap — rename) freezes the current log as wal.old and
    starts a fresh one; `write_snapshot(state)` (threadable) atomically
    replaces the snapshot — which already reflects wal.old — and deletes
    wal.old. Recovery replays snapshot → wal.old (crash mid-compaction) →
    wal."""

    def __init__(self, directory: str, compact_every: int = 512):
        self.dir = directory
        self.compact_every = compact_every
        os.makedirs(directory, exist_ok=True)
        self._wal_path = os.path.join(directory, WAL)
        self._wal_old_path = os.path.join(directory, WAL_OLD)
        self._snap_path = os.path.join(directory, SNAPSHOT)
        self._wal_file = None
        self._appends_since_compact = 0

    # -- recovery -------------------------------------------------------

    def recover(self) -> tuple[Optional[dict], list]:
        """Return (snapshot_state_or_None, wal_records). A torn final WAL
        record (crash mid-write) is dropped."""
        snap = None
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    # raw=False: str↔str, bytes(bin)↔bytes — exact round-trip
                    # of the wire-dict convention; bytes map keys allowed.
                    snap = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
            except Exception:  # noqa: BLE001 — corrupt snapshot: start empty
                logger.exception("snapshot unreadable; recovering from WAL only")
        records = _read_records(self._wal_old_path) + _read_records(self._wal_path)
        return snap, records

    # -- writes ---------------------------------------------------------

    def _wal(self):
        if self._wal_file is None:
            self._wal_file = open(self._wal_path, "ab")
        return self._wal_file

    def append(self, record: dict) -> bool:
        """Append one record; True when a compaction is due (caller copies
        state, calls rotate(), then write_snapshot() — possibly on a
        thread)."""
        f = self._wal()
        f.write(msgpack.packb(record))
        f.flush()
        self._appends_since_compact += 1
        return self._appends_since_compact >= self.compact_every

    def rotate(self):
        """Freeze the current WAL as wal.old (cheap rename; event-loop
        safe). New appends go to a fresh WAL. If a previous compaction
        failed, its un-folded wal.old is still live state — merge instead of
        clobbering it."""
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
        if os.path.exists(self._wal_path):
            if os.path.exists(self._wal_old_path):
                with open(self._wal_old_path, "ab") as dst, \
                        open(self._wal_path, "rb") as src:
                    dst.write(src.read())
                os.unlink(self._wal_path)
            else:
                os.replace(self._wal_path, self._wal_old_path)
        self._appends_since_compact = 0

    def write_snapshot(self, state: dict):
        """Pack + fsync + atomically install the snapshot, then drop wal.old
        (its records are folded in). Safe to run on a worker thread."""
        tmp = self._snap_path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(state))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        try:
            os.unlink(self._wal_old_path)
        except OSError:
            pass

    def snapshot(self, state: dict):
        """Synchronous rotate + write (small states / tests)."""
        self.rotate()
        self.write_snapshot(state)

    def close(self):
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
