"""Control-store persistence: pluggable snapshot + write-ahead-log backends.

Capability parity with the reference's GCS store clients (reference:
src/ray/gcs/store_client/redis_store_client.h, in_memory_store_client.h and
the RAY_external_storage_namespace recovery flow): the control store appends
every table mutation to a WAL and periodically compacts into a snapshot; a
restarted (or warm-standby) control store replays snapshot + WAL and resumes
serving with nodes, actors, placement groups, jobs, and KV intact.

The storage layer is a pluggable backend selected by the
`control_store_backend` flag:

  file    (default) `snapshot.msgpack` (atomic whole-state) + `wal.msgpack`
          (appended msgpack records) in `<dir>/` — the original format.
  sqlite  one `store.sqlite3` holding a `wal` table (seq-keyed records), a
          `snap` table, and a `meta` table carrying the fence epoch — the
          rocksdb-style embedded-KV shape of the reference's store clients.

Every record is stamped with a monotonic sequence number `i` (resumed across
restarts/failovers) and every snapshot carries `_wal_seq`, the seq of the
last folded record. Those stamps are what make two HA mechanisms exact:

  * warm-standby tailing (`open_tailer`): a standby replays the WAL as it
    grows — duplicates from compaction rotations dedup by seq, and a seq
    GAP (records compacted away before the tailer saw them) tells the
    standby to re-seed from the snapshot.
  * epoch fencing (`FencedError`): each leader opens the store with a
    fencing epoch from the leadership lease. A zombie primary that lost
    leadership cannot apply a late mutation — the sqlite backend refuses
    appends from a stale epoch in the INSERT itself; the file backend's
    appends check the EPOCH stamp a new leader writes before reading the
    WAL, and the takeover compaction unlinks the zombie's WAL inode so
    even a stamp-racing append lands in a file nobody will read.
"""

from __future__ import annotations

import logging
import os
import sqlite3
from typing import Optional

import msgpack

logger = logging.getLogger(__name__)

SNAPSHOT = "snapshot.msgpack"
WAL = "wal.msgpack"
WAL_OLD = "wal.old.msgpack"
SQLITE_DB = "store.sqlite3"
EPOCH_FILE = "EPOCH"

# snapshot key carrying the seq of the last record folded into it
SNAP_SEQ_KEY = "_wal_seq"
# record key carrying the monotonic sequence stamp
REC_SEQ_KEY = "i"


class FencedError(RuntimeError):
    """This writer's fencing epoch was superseded: another control store
    took over leadership. The only safe reaction is to stop serving — a
    fenced primary must not apply (or ack) another mutation."""


def _valid_record(rec) -> bool:
    # a torn/corrupt tail can decode to SOME msgpack value (e.g. a stray
    # int); only a dict shaped like a WAL record counts — anything else
    # ends the valid log
    return isinstance(rec, dict) and "op" in rec and "d" in rec


def _read_records(path: str) -> list:
    records = []
    if os.path.exists(path):
        with open(path, "rb") as f:
            unpacker = msgpack.Unpacker(f, raw=False, strict_map_key=False)
            while True:
                try:
                    rec = next(unpacker)
                except StopIteration:
                    break
                except Exception:  # noqa: BLE001 — torn tail record
                    logger.warning(
                        "dropping torn WAL tail after %d records (%s)",
                        len(records), path,
                    )
                    break
                if not _valid_record(rec):
                    logger.warning(
                        "dropping malformed WAL tail after %d records (%s)",
                        len(records), path,
                    )
                    break
                records.append(rec)
    return records


def read_epoch(directory: str) -> int:
    """Highest fencing epoch that ever opened this persist dir (0 = none)."""
    try:
        with open(os.path.join(directory, EPOCH_FILE)) as f:
            return int(f.read().strip() or 0)
    except (OSError, ValueError):
        return 0


def _write_epoch(directory: str, epoch: int) -> None:
    tmp = os.path.join(directory, f".epoch.tmp{os.getpid()}")
    with open(tmp, "w") as f:
        f.write(str(epoch))
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(directory, EPOCH_FILE))


# ---------------------------------------------------------------------------
# file backend (the original format)
# ---------------------------------------------------------------------------


class FileBackend:
    """msgpack files: `snapshot.msgpack` + `wal.msgpack` (+ `wal.old` during
    two-phase compaction). Fencing: a new leader stamps the EPOCH file at
    open (before reading the WAL) — a zombie's appends check it and raise
    FencedError; the takeover compaction additionally folds and unlinks the
    zombie's WAL inode, so even an append that races the stamp lands in a
    file the new leader will never read."""

    name = "file"

    def __init__(self, directory: str, epoch: int = 0):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self._wal_path = os.path.join(directory, WAL)
        self._wal_old_path = os.path.join(directory, WAL_OLD)
        self._snap_path = os.path.join(directory, SNAPSHOT)
        self._wal_file = None
        self.epoch = epoch
        if epoch:
            recorded = read_epoch(directory)
            if recorded > epoch:
                raise FencedError(
                    f"persist dir {directory} already fenced at epoch "
                    f"{recorded} > {epoch}")
            if recorded < epoch:
                _write_epoch(directory, epoch)

    def recover(self) -> tuple:
        snap = None
        if os.path.exists(self._snap_path):
            try:
                with open(self._snap_path, "rb") as f:
                    # raw=False: str↔str, bytes(bin)↔bytes — exact round-trip
                    # of the wire-dict convention; bytes map keys allowed.
                    snap = msgpack.unpackb(
                        f.read(), raw=False, strict_map_key=False)
            except Exception:  # noqa: BLE001 — corrupt snapshot: start empty
                logger.exception("snapshot unreadable; recovering from WAL only")
        records = _read_records(self._wal_old_path) + _read_records(self._wal_path)
        return snap, records

    def _wal(self):
        if self._wal_file is None:
            self._wal_file = open(self._wal_path, "ab")
        return self._wal_file

    def append(self, record: dict) -> None:
        f = self._wal()
        if self.epoch:
            # two fencing probes, both BEFORE the write is acked. (1) The
            # inode at the WAL path vs our open handle: the active leader
            # always writes to the path's inode (rotate closes the handle;
            # the next append reopens) — a mismatch or missing path means
            # a takeover rotated our file away. (2) The EPOCH stamp a new
            # leader writes before it READS the WAL — this closes the
            # pre-rotate window exactly: an append that passed the check
            # before the stamp landed is included in the new leader's
            # recovery (so its ack is honest), and one after it is
            # refused, never acked. The small-file read is noise next to
            # the pack+write+flush it gates, and persisted mutations are
            # orders of magnitude rarer than heartbeats.
            try:
                if os.stat(self._wal_path).st_ino \
                        != os.fstat(f.fileno()).st_ino:
                    raise FencedError(
                        f"WAL rotated away by a newer leader (epoch "
                        f"{self.epoch} superseded); append refused")
            except FileNotFoundError:
                raise FencedError(
                    f"WAL unlinked by a newer leader (epoch {self.epoch} "
                    f"superseded); append refused") from None
            recorded = read_epoch(self.dir)
            if recorded > self.epoch:
                raise FencedError(
                    f"epoch {self.epoch} superseded by {recorded}; "
                    f"append refused")
        f.write(msgpack.packb(record))
        f.flush()
        if self.epoch and read_epoch(self.dir) > self.epoch:
            # the stamp landed BETWEEN our probe and the flush: the new
            # leader's recovery may or may not have read this record, so
            # the only honest answer is an error — the caller's retry
            # lands on the new incumbent, whose mutations are idempotent
            raise FencedError(
                f"epoch {self.epoch} superseded mid-append; ack refused")

    def rotate(self) -> None:
        """Freeze the current WAL as wal.old (cheap rename; event-loop
        safe). New appends go to a fresh WAL. If a previous compaction
        failed, its un-folded wal.old is still live state — merge instead of
        clobbering it."""
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None
        if os.path.exists(self._wal_path):
            if os.path.exists(self._wal_old_path):
                with open(self._wal_old_path, "ab") as dst, \
                        open(self._wal_path, "rb") as src:
                    dst.write(src.read())
                os.unlink(self._wal_path)
            else:
                os.replace(self._wal_path, self._wal_old_path)

    def write_snapshot(self, state: dict) -> None:
        """Pack + fsync + atomically install the snapshot, then drop wal.old
        (its records are folded in). Safe to run on a worker thread."""
        if self.epoch and read_epoch(self.dir) > self.epoch:
            raise FencedError(
                f"epoch {self.epoch} superseded; refusing snapshot")
        tmp = self._snap_path + f".tmp{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(msgpack.packb(state))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._snap_path)
        try:
            os.unlink(self._wal_old_path)
        except OSError:
            pass

    def close(self) -> None:
        if self._wal_file is not None:
            self._wal_file.close()
            self._wal_file = None


class FileTailer:
    """Warm-standby tail of a FileBackend dir: poll() yields records as the
    leader appends them. Holds file handles across compaction rotations
    (renames keep the inode; merge-rotations only copy already-seen bytes,
    deduped by seq upstream), so no record is ever missed."""

    def __init__(self, directory: str):
        self.dir = directory
        self._wal_path = os.path.join(directory, WAL)
        self._wal_old_path = os.path.join(directory, WAL_OLD)
        # creation-ordered open inodes: [(inode, fh, unpacker)]
        self._streams: list = []
        self._known_inodes: set = set()

    def read_snapshot(self) -> Optional[dict]:
        path = os.path.join(self.dir, SNAPSHOT)
        if not os.path.exists(path):
            return None
        try:
            with open(path, "rb") as f:
                return msgpack.unpackb(f.read(), raw=False,
                                       strict_map_key=False)
        except Exception:  # noqa: BLE001 — mid-replace read; next poll
            return None

    def _open_new(self):
        # wal.old first (older records), then wal
        for path in (self._wal_old_path, self._wal_path):
            try:
                st = os.stat(path)
            except OSError:
                continue
            if st.st_ino in self._known_inodes:
                continue
            try:
                fh = open(path, "rb")
            except OSError:
                continue
            if os.fstat(fh.fileno()).st_ino != st.st_ino:
                # path re-pointed between stat and open; retry next poll
                fh.close()
                continue
            self._known_inodes.add(st.st_ino)
            self._streams.append((
                st.st_ino, fh,
                msgpack.Unpacker(raw=False, strict_map_key=False),
            ))

    def poll(self) -> list:
        """All complete records appended since the last poll, oldest first.
        A torn tail (leader mid-write, or killed mid-write) stays buffered
        until its remaining bytes arrive — or forever, which recovery-time
        torn-tail dropping handles."""
        self._open_new()
        out = []
        dead = []
        for entry in self._streams:
            ino, fh, unpacker = entry
            try:
                data = fh.read()
            except OSError:
                data = b""
            if data:
                unpacker.feed(data)
                while True:
                    try:
                        rec = next(unpacker)
                    except StopIteration:
                        break
                    except Exception:  # noqa: BLE001 — corrupt bytes: stop
                        dead.append(entry)
                        break
                    if _valid_record(rec):
                        out.append(rec)
            elif os.fstat(fh.fileno()).st_nlink == 0:
                # unlinked by compaction and fully drained: retire
                dead.append(entry)
        for entry in dead:
            self._streams.remove(entry)
            entry[1].close()
            # forget the retired inode number: the filesystem can reuse it
            # for a future wal.msgpack, which _open_new must then OPEN, not
            # skip (a skipped reused inode would silently end the tail)
            self._known_inodes.discard(entry[0])
        out.sort(key=lambda r: r.get(REC_SEQ_KEY, 0))
        return out

    def close(self) -> None:
        for _, fh, _ in self._streams:
            fh.close()
        self._streams.clear()


# ---------------------------------------------------------------------------
# sqlite backend (the rocksdb-style embedded alternative)
# ---------------------------------------------------------------------------


_SQLITE_SCHEMA = """
CREATE TABLE IF NOT EXISTS wal (seq INTEGER PRIMARY KEY, rec BLOB NOT NULL);
CREATE TABLE IF NOT EXISTS snap (
    id INTEGER PRIMARY KEY CHECK (id = 0),
    state BLOB NOT NULL, wal_seq INTEGER NOT NULL);
CREATE TABLE IF NOT EXISTS meta (key TEXT PRIMARY KEY, value INTEGER);
"""


class SqliteBackend:
    """One sqlite file; WAL-journal mode so the standby's read connection
    tails while the leader writes. Fencing is transactional: the epoch
    lives in the `meta` table and every append is an INSERT guarded by
    `epoch <= mine` — a zombie's mutation fails atomically, with no
    window."""

    name = "sqlite"

    def __init__(self, directory: str, epoch: int = 0):
        self.dir = directory
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, SQLITE_DB)
        self.epoch = epoch
        self._db = sqlite3.connect(self.path, timeout=10.0)
        self._db.executescript(_SQLITE_SCHEMA)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute("PRAGMA synchronous=NORMAL")
        self._db.execute(
            "INSERT OR IGNORE INTO meta(key, value) VALUES ('epoch', 0)")
        if epoch:
            cur = self._db.execute(
                "UPDATE meta SET value = ? WHERE key = 'epoch' AND value < ?",
                (epoch, epoch))
            if cur.rowcount == 0:
                row = self._db.execute(
                    "SELECT value FROM meta WHERE key = 'epoch'").fetchone()
                if row and row[0] > epoch:
                    self._db.commit()
                    self._db.close()
                    raise FencedError(
                        f"sqlite store already fenced at epoch {row[0]} "
                        f"> {epoch}")
            _write_epoch(directory, max(epoch, read_epoch(directory)))
        self._db.commit()
        self._frozen_seq = 0

    def recover(self) -> tuple:
        snap = None
        row = self._db.execute(
            "SELECT state FROM snap WHERE id = 0").fetchone()
        if row is not None:
            try:
                snap = msgpack.unpackb(row[0], raw=False,
                                       strict_map_key=False)
            except Exception:  # noqa: BLE001 — corrupt snapshot row
                logger.exception("sqlite snapshot unreadable; WAL only")
        records = []
        for (blob,) in self._db.execute(
                "SELECT rec FROM wal ORDER BY seq"):
            try:
                rec = msgpack.unpackb(blob, raw=False, strict_map_key=False)
            except Exception:  # noqa: BLE001 — corrupt row: stop at it
                logger.warning("dropping corrupt sqlite WAL record")
                break
            if not _valid_record(rec):
                logger.warning("dropping malformed sqlite WAL record")
                break
            records.append(rec)
        return snap, records

    def append(self, record: dict) -> None:
        seq = record.get(REC_SEQ_KEY, 0)
        cur = self._db.execute(
            "INSERT INTO wal(seq, rec) SELECT ?, ? WHERE "
            "(SELECT value FROM meta WHERE key = 'epoch') <= ?",
            (seq, msgpack.packb(record), self.epoch or 0))
        if cur.rowcount == 0:
            self._db.rollback()
            raise FencedError(
                f"epoch {self.epoch} superseded; append refused")
        self._db.commit()

    def rotate(self) -> None:
        row = self._db.execute("SELECT MAX(seq) FROM wal").fetchone()
        self._frozen_seq = row[0] or 0

    def write_snapshot(self, state: dict) -> None:
        frozen = self._frozen_seq
        # a FRESH connection per snapshot: this runs on a worker thread
        # during live compaction (sqlite3 connections are bound to their
        # creating thread), and compactions are rare enough that the
        # connect cost is noise
        db = sqlite3.connect(self.path, timeout=10.0)
        try:
            with db:  # one transaction: fold + trim atomically
                cur = db.execute(
                    "SELECT value FROM meta WHERE key = 'epoch'").fetchone()
                if self.epoch and cur and cur[0] > self.epoch:
                    raise FencedError(
                        f"epoch {self.epoch} superseded; refusing snapshot")
                db.execute(
                    "INSERT OR REPLACE INTO snap(id, state, wal_seq) "
                    "VALUES (0, ?, ?)",
                    (msgpack.packb(state), state.get(SNAP_SEQ_KEY, frozen)))
                db.execute("DELETE FROM wal WHERE seq <= ?", (frozen,))
        except sqlite3.Error as e:
            raise RuntimeError(f"sqlite snapshot failed: {e}") from e
        finally:
            db.close()

    def close(self) -> None:
        try:
            self._db.commit()
            self._db.close()
        except sqlite3.Error:
            pass


class SqliteTailer:
    """Warm-standby tail of a SqliteBackend: records with seq > cursor.
    Compaction can delete rows the standby never saw (it fell behind a
    whole compaction window); the seq gap is detected by the WalStore-level
    tail driver, which re-seeds from the snapshot."""

    def __init__(self, directory: str):
        self.dir = directory
        self.path = os.path.join(directory, SQLITE_DB)
        self._db: Optional[sqlite3.Connection] = None
        self._cursor = 0

    def _conn(self) -> Optional[sqlite3.Connection]:
        if self._db is None:
            if not os.path.exists(self.path):
                return None
            self._db = sqlite3.connect(self.path, timeout=10.0)
        return self._db

    def read_snapshot(self) -> Optional[dict]:
        db = self._conn()
        if db is None:
            return None
        try:
            row = db.execute("SELECT state FROM snap WHERE id = 0").fetchone()
        except sqlite3.Error:
            return None
        if row is None:
            return None
        try:
            return msgpack.unpackb(row[0], raw=False, strict_map_key=False)
        except Exception:  # noqa: BLE001
            return None

    def poll(self) -> list:
        db = self._conn()
        if db is None:
            return []
        out = []
        try:
            rows = db.execute(
                "SELECT seq, rec FROM wal WHERE seq > ? ORDER BY seq",
                (self._cursor,)).fetchall()
        except sqlite3.Error:
            return []
        for seq, blob in rows:
            try:
                rec = msgpack.unpackb(blob, raw=False, strict_map_key=False)
            except Exception:  # noqa: BLE001
                break
            if not _valid_record(rec):
                break
            self._cursor = max(self._cursor, seq)
            out.append(rec)
        return out

    def close(self) -> None:
        if self._db is not None:
            self._db.close()
            self._db = None


# ---------------------------------------------------------------------------
# facade
# ---------------------------------------------------------------------------


_BACKENDS = {"file": FileBackend, "sqlite": SqliteBackend}
_TAILERS = {"file": FileTailer, "sqlite": SqliteTailer}


def _backend_name(backend: Optional[str]) -> str:
    if backend is None:
        from ray_tpu._private.config import GLOBAL_CONFIG

        backend = GLOBAL_CONFIG.get("control_store_backend")
    if backend not in _BACKENDS:
        raise ValueError(
            f"unknown control_store_backend {backend!r} "
            f"(choices: {sorted(_BACKENDS)})")
    return backend


class WalStore:
    """Append-only log with snapshot compaction over a pluggable backend.

    Compaction is two-phase so the (potentially large) state pack + fsync can
    run on a worker thread without losing concurrent appends: `rotate()` (on
    the event loop, cheap) freezes the current log; `write_snapshot(state)`
    (threadable) atomically replaces the snapshot — which already reflects
    the frozen log — and drops the folded records. Recovery replays
    snapshot → frozen log → live log.

    Every record is stamped with a monotonic seq (`i`, resumed across
    restarts) and the snapshot carries `_wal_seq` — see the module
    docstring for why."""

    def __init__(self, directory: str, compact_every: int = 512,
                 backend: Optional[str] = None, epoch: int = 0):
        self.dir = directory
        self.compact_every = compact_every
        self.epoch = epoch
        self.backend = _BACKENDS[_backend_name(backend)](directory, epoch)
        self._appends_since_compact = 0
        self._seq = 0
        self._frozen_seq = 0

    # -- recovery -------------------------------------------------------

    def recover(self) -> tuple:
        """Return (snapshot_state_or_None, wal_records). A torn/corrupt
        final WAL record (crash mid-write) is dropped; the append seq
        resumes after the highest recovered stamp."""
        snap, records = self.backend.recover()
        if snap is not None:
            self._seq = max(self._seq, int(snap.pop(SNAP_SEQ_KEY, 0) or 0))
        for rec in records:
            self._seq = max(self._seq, int(rec.get(REC_SEQ_KEY, 0) or 0))
        return snap, records

    def adopt_seq(self, seq: int) -> None:
        """Resume the append counter after `seq` (warm-standby takeover:
        the tailer, not recover(), saw the existing records)."""
        self._seq = max(self._seq, int(seq))

    @property
    def seq(self) -> int:
        return self._seq

    # -- writes ---------------------------------------------------------

    def append(self, record: dict) -> bool:
        """Append one record; True when a compaction is due (caller copies
        state, calls rotate(), then write_snapshot() — possibly on a
        thread). Raises FencedError if a newer leader owns the store."""
        self._seq += 1
        record[REC_SEQ_KEY] = self._seq
        self.backend.append(record)
        self._appends_since_compact += 1
        return self._appends_since_compact >= self.compact_every

    def rotate(self) -> None:
        self._frozen_seq = self._seq
        self.backend.rotate()
        self._appends_since_compact = 0

    def write_snapshot(self, state: dict) -> None:
        state = {**state, SNAP_SEQ_KEY: self._frozen_seq}
        self.backend.write_snapshot(state)

    def snapshot(self, state: dict) -> None:
        """Synchronous rotate + write (small states / takeover fold)."""
        self.rotate()
        self.write_snapshot(state)

    def close(self) -> None:
        self.backend.close()


class WalTail:
    """The warm-standby driver over a backend tailer: dedups compaction-
    rotation duplicates by seq, detects seq gaps (records folded away
    before we saw them) and re-seeds from the snapshot.

    poll() returns a list of ("snapshot", state) / ("record", rec) items to
    apply IN ORDER: a snapshot item means reset tables and re-seed."""

    def __init__(self, directory: str, backend: Optional[str] = None):
        self.dir = directory
        self.tailer = _TAILERS[_backend_name(backend)](directory)
        self.last_seq = 0
        self._seeded = False
        # records held back because a seq gap wasn't covered by a snapshot
        # re-seed yet (transient snapshot-read failure / replace race):
        # consuming them would silently lose the missed window forever
        self._held: list = []

    @property
    def drained(self) -> bool:
        """True when nothing is held back waiting on a snapshot re-seed."""
        return not self._held

    def _seed(self) -> list:
        state = self.tailer.read_snapshot()
        if state is None:
            return []
        self.last_seq = max(self.last_seq,
                            int(state.pop(SNAP_SEQ_KEY, 0) or 0))
        return [("snapshot", state)]

    def poll(self) -> list:
        out = []
        if not self._seeded:
            # seed AFTER the tailer opened its handles: records folded by a
            # compaction racing us are covered by the snapshot's _wal_seq
            out.extend(self._seed())
            self._seeded = True
        records = self._held + self.tailer.poll()
        self._held = []
        for idx, rec in enumerate(records):
            seq = int(rec.get(REC_SEQ_KEY, 0) or 0)
            if seq and seq <= self.last_seq:
                continue  # rotation-merge duplicate
            if seq > self.last_seq + 1:
                # gap: a compaction folded records we never saw (sqlite
                # trim, or a whole rotate+fold between polls) — the
                # snapshot is the only copy now
                reseed = self._seed()
                if reseed:
                    out.extend(reseed)
                if seq and seq <= self.last_seq:
                    continue  # snapshot covered this record too
                if seq > self.last_seq + 1:
                    # the re-seed did NOT cover the gap (snapshot read
                    # transiently failed, or an old snapshot is still
                    # installed): hold everything from here and retry next
                    # poll — advancing past the gap would lose the missed
                    # records permanently. The compaction that created the
                    # gap commits its covering snapshot atomically, so a
                    # later seed must cover it.
                    self._held = records[idx:]
                    break
            self.last_seq = seq or self.last_seq
            out.append(("record", rec))
        return out

    def close(self) -> None:
        self.tailer.close()


def open_tailer(directory: str, backend: Optional[str] = None) -> WalTail:
    return WalTail(directory, backend)
