"""Per-process task-event buffer, flushed to the control store.

Reference: src/ray/core_worker/profile_event.h:33 + task_event_buffer.h
(workers buffer ProfileEvents, flush to GcsTaskManager,
src/ray/gcs/gcs_task_manager.h) — `ray_tpu.timeline()` renders the history
as Chrome-trace JSON the way `ray timeline` does
(python/ray/_private/state.py:1017).

Loss is ACCOUNTED: events trimmed past `task_event_buffer_max` increment
`rt_task_events_dropped_total` and the drop count rides every `drain()`
so the telemetry loop reports it to the control store (surfaced on the
dashboard scrape) — a silent gap in the task history is itself a bug
signal worth observing.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from ray_tpu._private.config import GLOBAL_CONFIG


class TaskEventBuffer:
    def __init__(self):
        self._events: List[dict] = []
        self._lock = threading.Lock()
        self._dropped_pending = 0   # since the last drain()
        self.dropped_total = 0
        self._drop_counter = None
        self._drop_counter_gen = None
        # eager zero-registration: the dropped-total series exists on the
        # scrape (and the Grafana loss panel) before the first drop
        self._count_drops(0)

    def _count_drops(self, n: int):
        """Called under self._lock. The counter handle is re-resolved when
        the metric registry was reset (test isolation)."""
        self._dropped_pending += n
        self.dropped_total += n
        try:
            from ray_tpu.util import metrics

            gen = metrics.registry_generation()
            if self._drop_counter is None or self._drop_counter_gen != gen:
                self._drop_counter = metrics.get_or_create_counter(
                    "rt_task_events_dropped_total",
                    "Task events trimmed from a full per-process buffer "
                    "before they could flush to the control store")
                self._drop_counter_gen = gen
            self._drop_counter.inc(n)
        except Exception:  # noqa: BLE001 — accounting must not fail record()
            pass

    def record(self, *, task_id: bytes, name: str, kind: str, event: str,
               worker_id: bytes, node_id: str, ts: Optional[float] = None,
               duration_s: Optional[float] = None,
               extra: Optional[Dict] = None):
        ev = {
            "task_id": task_id,
            "name": name,
            "kind": kind,            # NORMAL / ACTOR_CREATION / ACTOR_TASK
            "event": event,          # RUNNING / FINISHED / FAILED / SPAN
            "worker_id": worker_id,
            "node_id": node_id,
            "ts": ts if ts is not None else time.time(),
        }
        if duration_s is not None:
            ev["duration_s"] = duration_s
        if extra:
            ev.update(extra)
        cap = GLOBAL_CONFIG.get("task_event_buffer_max")
        with self._lock:
            self._events.append(ev)
            if len(self._events) > cap:
                n = len(self._events) - cap
                del self._events[:n]
                self._count_drops(n)

    def drain(self) -> Tuple[List[dict], int]:
        """Take the buffered events plus the number of events DROPPED since
        the previous drain — the flush reports both so the control store's
        history carries its own loss accounting."""
        with self._lock:
            out, self._events = self._events, []
            dropped, self._dropped_pending = self._dropped_pending, 0
            return out, dropped

    def requeue(self, events: List[dict], dropped: int = 0):
        """Put a drained-but-unflushed batch back (flush RPC failed) so a
        control-store blip doesn't lose the interval's events."""
        cap = GLOBAL_CONFIG.get("task_event_buffer_max")
        with self._lock:
            merged = events + self._events
            if len(merged) > cap:
                self._count_drops(len(merged) - cap)
            self._events = merged[-cap:]
            self._dropped_pending += dropped


_KIND_NAMES = {0: "normal", 1: "actor_creation", 2: "actor_task"}


def to_chrome_trace(events: List[dict]) -> List[dict]:
    """Chrome trace 'X' (complete) events. FINISHED/FAILED task records
    render as before (pid = node, tid = worker — matching `ray timeline`'s
    layout); SPAN records (execution spans, per-hop sub-spans, serve/data
    spans) render on the same worker rows so one traced sync call visibly
    splits into its hops and a serve request shows
    ingress→replica→batch→stream stitched by trace id."""
    trace = []
    for ev in events:
        event = ev.get("event")
        if event == "SPAN" and ev.get("trace_id"):
            dur = ev.get("duration_s") or 0.0
            trace.append({
                "name": ev["name"],
                "cat": "span",
                "ph": "X",
                "ts": ev["ts"] * 1e6,
                "dur": dur * 1e6,
                "pid": f"node:{ev.get('node_id', '')[:12]}",
                "tid": f"worker:{ev['worker_id'].hex()[:12]}",
                "args": {
                    "trace_id": ev["trace_id"],
                    "span_id": ev.get("span_id", ""),
                    "parent_span_id": ev.get("parent_span_id", ""),
                    "task_id": ev["task_id"].hex(),
                },
            })
            continue
        if event not in ("FINISHED", "FAILED"):
            continue
        dur = ev.get("duration_s", 0.0)
        trace.append({
            "name": ev["name"],
            "cat": _KIND_NAMES.get(ev["kind"], str(ev["kind"])),
            "ph": "X",
            "ts": (ev["ts"] - dur) * 1e6,
            "dur": dur * 1e6,
            "pid": f"node:{ev['node_id'][:12]}",
            "tid": f"worker:{ev['worker_id'].hex()[:12]}",
            "args": {
                "task_id": ev["task_id"].hex(),
                "status": ev["event"],
            },
        })
    return trace
