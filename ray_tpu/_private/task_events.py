"""Per-process task-event buffer, flushed to the control store.

Reference: src/ray/core_worker/profile_event.h:33 + task_event_buffer.h
(workers buffer ProfileEvents, flush to GcsTaskManager,
src/ray/gcs/gcs_task_manager.h) — `ray_tpu.timeline()` renders the history
as Chrome-trace JSON the way `ray timeline` does
(python/ray/_private/state.py:1017).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

from ray_tpu._private.config import GLOBAL_CONFIG


class TaskEventBuffer:
    def __init__(self):
        self._events: List[dict] = []
        self._lock = threading.Lock()

    def record(self, *, task_id: bytes, name: str, kind: str, event: str,
               worker_id: bytes, node_id: str, ts: Optional[float] = None,
               duration_s: Optional[float] = None,
               extra: Optional[Dict] = None):
        ev = {
            "task_id": task_id,
            "name": name,
            "kind": kind,            # NORMAL / ACTOR_CREATION / ACTOR_TASK
            "event": event,          # RUNNING / FINISHED / FAILED
            "worker_id": worker_id,
            "node_id": node_id,
            "ts": ts if ts is not None else time.time(),
        }
        if duration_s is not None:
            ev["duration_s"] = duration_s
        if extra:
            ev.update(extra)
        cap = GLOBAL_CONFIG.get("task_event_buffer_max")
        with self._lock:
            self._events.append(ev)
            if len(self._events) > cap:
                del self._events[: len(self._events) - cap]

    def drain(self) -> List[dict]:
        with self._lock:
            out, self._events = self._events, []
            return out

    def requeue(self, events: List[dict]):
        """Put a drained-but-unflushed batch back (flush RPC failed) so a
        control-store blip doesn't lose the interval's events."""
        cap = GLOBAL_CONFIG.get("task_event_buffer_max")
        with self._lock:
            self._events = (events + self._events)[-cap:]


_KIND_NAMES = {0: "normal", 1: "actor_creation", 2: "actor_task"}


def to_chrome_trace(events: List[dict]) -> List[dict]:
    """Chrome trace 'X' (complete) events from FINISHED/FAILED records.
    pid = node, tid = worker — matching `ray timeline`'s layout."""
    trace = []
    for ev in events:
        if ev["event"] not in ("FINISHED", "FAILED"):
            continue
        dur = ev.get("duration_s", 0.0)
        trace.append({
            "name": ev["name"],
            "cat": _KIND_NAMES.get(ev["kind"], str(ev["kind"])),
            "ph": "X",
            "ts": (ev["ts"] - dur) * 1e6,
            "dur": dur * 1e6,
            "pid": f"node:{ev['node_id'][:12]}",
            "tid": f"worker:{ev['worker_id'].hex()[:12]}",
            "args": {
                "task_id": ev["task_id"].hex(),
                "status": ev["event"],
            },
        })
    return trace
