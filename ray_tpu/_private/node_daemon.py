"""Node daemon — the per-node agent (raylet equivalent).

Capability parity with the reference's raylet (reference: src/ray/raylet/
node_manager.h:144, worker_pool.h:284, scheduling/cluster_lease_manager.h:41,
scheduling/local_lease_manager.h:62, object_manager/object_manager.h:137):

- owns the node's shared-memory object store (native, ray_tpu/native/shm_store.cc);
- spawns and pools worker processes (keyed by job, cached idle, monitored for
  death — reference: worker_pool.h:284);
- serves worker leases with a two-level scheduler: a cluster policy choosing a
  node from the gossiped resource view (hybrid pack/spread, reference:
  hybrid_scheduling_policy.h:50) with spillback replies, and a local grant path
  that queues until resources free up (reference: cluster_lease_manager.cc:195);
- reserves/commits placement-group bundles 2-phase (reference:
  node_manager.proto:515-525, placement_group_resource_manager.h);
- transfers objects node-to-node in chunks pulled into the local store
  (reference: object_manager/pull_manager.h:52, push_manager.h:28).
"""

from __future__ import annotations

import asyncio
from ray_tpu._private.aio import spawn
import json
import logging
import os
import signal
import subprocess
import sys
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Set, Tuple

from ray_tpu._private import chaos
from ray_tpu._private import flight_recorder
from ray_tpu._private import protocol as pb
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.errors import ObjectStoreFullError
from ray_tpu._private.ids import NodeID, ObjectID, WorkerID
from ray_tpu._private.protocol import NodeInfo, ResourceSet, TaskSpec
from ray_tpu.runtime.object_store import ShmObjectStore
from ray_tpu.runtime.rpc import RpcClient, RpcServer

logger = logging.getLogger(__name__)


def _read_file_range(path: str, offset: int, limit: int) -> bytes:
    """Bounded positional read, run in a worker thread by the async log/
    profile paths so the daemon's event loop never blocks on disk."""
    with open(path, "rb") as f:
        f.seek(offset)
        return f.read(limit)


W_STARTING = "STARTING"
W_IDLE = "IDLE"
W_LEASED = "LEASED"
W_ACTOR = "ACTOR"
W_DEAD = "DEAD"


class WorkerHandle:
    __slots__ = (
        "worker_id", "proc", "state", "address", "pid", "job_id",
        "client", "lease_id", "actor_id", "ready_event", "idle_since",
        "actor_resources", "actor_pg", "tpu_chips", "reserved", "env_key",
        "spawn_ts", "drain_coop",
    )

    def __init__(self, worker_id: WorkerID, proc: subprocess.Popen, job_id: bytes):
        self.worker_id = worker_id
        self.proc = proc
        self.state = W_STARTING
        self.address = ""
        self.pid = proc.pid
        self.job_id = job_id
        self.spawn_ts = time.monotonic()  # OOM policy kills newest first
        # runtime-env isolation key this worker was spawned for ("" = plain
        # pooled worker; reference: worker_pool.h keys by runtime_env_hash)
        self.env_key = ""
        self.client: Optional[RpcClient] = None
        self.lease_id: Optional[bytes] = None
        self.actor_id: Optional[bytes] = None
        self.ready_event = asyncio.Event()
        self.idle_since = time.monotonic()
        self.actor_resources: Optional[ResourceSet] = None
        # (pg_id, bundle_index) when the actor consumes a PG bundle
        self.actor_pg: Optional[Tuple[bytes, int]] = None
        # actor whose owner coordinates planned removal (elastic gangs):
        # a terminal drain holds the node open while it lives
        self.drain_coop = False
        # chip ids this worker's TPU_VISIBLE_CHIPS was baked with at spawn
        # (visibility is per-process: it cannot change after libtpu init)
        self.tpu_chips: Optional[Tuple[int, ...]] = None
        # spawned for a specific waiting grantee: worker_ready must NOT
        # publish it to the idle pool (a concurrent _get_idle_worker could
        # lease it out from under the spawner)
        self.reserved = False


class PendingLease:
    __slots__ = ("spec_resources", "strategy", "job_id", "future", "hops",
                 "runtime_env", "t0_ns")

    def __init__(self, spec_resources: ResourceSet, strategy: pb.SchedulingStrategy,
                 job_id: bytes, hops: int,
                 runtime_env: Optional[dict] = None):
        self.spec_resources = spec_resources
        self.strategy = strategy
        self.job_id = job_id
        self.future: asyncio.Future = asyncio.get_running_loop().create_future()
        self.hops = hops
        # request-arrival stamp: the grant reply carries queue-to-grant time
        # (the per-hop decomposition's `grant` hop, daemon-side truth)
        self.t0_ns = time.monotonic_ns()
        # wire runtime env when it needs a dedicated worker (pip venv,
        # working_dir); None for plain leases
        self.runtime_env = runtime_env


class NodeDaemon:
    def __init__(
        self,
        control_address: str,
        resources: Optional[Dict[str, float]] = None,
        labels: Optional[Dict[str, str]] = None,
        session_dir: str = "/tmp/ray_tpu_sessions",
        host: str = "127.0.0.1",
        store_name: Optional[str] = None,
    ):
        self.node_id = NodeID.from_random()
        self.control_address = control_address
        self.host = host
        self.session_dir = session_dir
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        # cgroup-v2 isolation (opt-in; reference: cgroup_manager.h) — the
        # daemon itself is a "system" process, workers are confined
        from ray_tpu._private.cgroup import manager_from_config

        self.cgroups = manager_from_config(os.path.basename(session_dir))
        if self.cgroups is not None and self.cgroups.setup(
                system_pids=[os.getpid()]):
            logger.info("cgroup2 worker isolation active under %s",
                        self.cgroups.base)
        else:
            self.cgroups = None
        res = dict(resources or {})
        if "CPU" not in res:
            res["CPU"] = float(os.cpu_count() or 1)
        self.labels = dict(labels or {})
        # spot/preemptible marker normalization: a node advertising the
        # "spot" custom resource IS spot capacity — mirror it into the
        # label plane so anti-affinity selectors (label_selector=
        # {"spot": "!true"}) can keep coordination actors off it
        if res.get("spot"):
            self.labels.setdefault("spot", "true")
        if "TPU" not in res and os.environ.get("RT_TPU_AUTODETECT"):
            # env-only detection: the daemon must not touch libtpu (that
            # would claim the chips workers need). Opt-in: on shared-sandbox
            # hosts several fake daemons coexist with one real chip.
            from ray_tpu.tpu.accelerator import TpuAcceleratorManager

            info = TpuAcceleratorManager.detect(allow_jax_probe=False)
            if info is not None:
                tpu_res, tpu_labels = (
                    TpuAcceleratorManager.node_resources_and_labels(info)
                )
                res.update(tpu_res)
                self.labels.update(tpu_labels)
        self.total_resources = ResourceSet(res)
        self.available = ResourceSet(res)
        # Free TPU chip ids (reference: tpu.py:42-55 visibility semantics —
        # each granted lease/actor with {"TPU": n} takes n specific chips and
        # the worker is spawned with TPU_VISIBLE_CHIPS restricted to them).
        self._tpu_free_chips: List[int] = list(range(int(res.get("TPU", 0))))
        self._tpu_chips_per_host = len(self._tpu_free_chips)
        self.store_name = store_name or f"rt_{self.node_id.hex()[:12]}"
        self.store: Optional[ShmObjectStore] = None
        self.server = RpcServer(name=f"daemon-{self.node_id.hex()[:6]}")
        self.control: Optional[RpcClient] = None
        # worker pool
        self.workers: Dict[bytes, WorkerHandle] = {}
        # idle pool keyed by (job_id, env_key) — workers built for a
        # pip/working_dir env serve only that env (worker_pool.h hash)
        self.idle_by_job: Dict[Tuple[bytes, str], List[bytes]] = {}
        # leases
        self.leases: Dict[bytes, Tuple[bytes, ResourceSet, Optional[bytes]]] = {}
        #   lease_id -> (worker_id, resources, pg_id, bundle_index)
        self.pending: List[PendingLease] = []
        # recently-rejected infeasible lease shapes (deduped): reported in
        # heartbeats so the autoscaler can provision nodes for demand no
        # current node can host (clients retry infeasible leases every
        # ~0.5s, refreshing these entries until capacity appears)
        self._infeasible_seen: Dict[tuple, float] = {}
        # idempotency for retried RPCs (dropped/timed-out calls re-sent by
        # clients must not double-grant/double-create)
        self._lease_requests: Dict[bytes, asyncio.Task] = {}
        self._lease_key_by_id: Dict[bytes, bytes] = {}
        # request_keys cancelled before their request_lease arrived (the
        # dead connection's frame or a resend can land after the cancel):
        # a tombstoned key is refused instead of queued-and-leaked
        self._cancelled_lease_keys: "OrderedDict[bytes, float]" = OrderedDict()
        self._creating_actors: Dict[bytes, asyncio.Task] = {}
        # cluster view: node_id hex -> available ResourceSet
        self.cluster_view: Dict[str, ResourceSet] = {}
        # per-origin gossip versions (reference: ray_syncer versioned
        # snapshots); my own availability publishes under _my_view_seq
        self._view_seq: Dict[str, int] = {}
        self._my_view_seq = 0
        self.peer_nodes: Dict[str, NodeInfo] = {}
        self._peer_clients: Dict[str, RpcClient] = {}
        # placement groups: pg_id -> {"bundles": {idx: ResourceSet}, "state", "free": {idx: ResourceSet}}
        self.pg_prepared: Dict[bytes, dict] = {}
        self._tasks: List[asyncio.Task] = []
        self._stopped = False
        self._draining = False
        # monotonic stamp of the last authoritative drain-state sync; an
        # in-flight heartbeat reply issued BEFORE a pubsub drain update must
        # not roll the state back (reply snapshots are unordered vs pubsub)
        self._drain_sync_ts = 0.0
        # terminal-drain orchestration (one per daemon lifetime): set when a
        # deadline-carrying drain notice lands; run_daemon wires _exit_cb so
        # the process exits cleanly once the drain completes
        self._drain_task: Optional[asyncio.Task] = None
        self._exit_cb = None
        # preemption watcher (real metadata polling or the chaos stand-in);
        # kept for introspection/stop and so tests can assert publish counts
        self._preempt_watcher = None
        # subscriber-side pubsub gap detection: last publish seq seen on the
        # "nodes" channel (control_store stamps every notice with _seq)
        self._nodes_seq: Optional[int] = None
        # node-table version cursor (scale plane): the max `_v` applied from
        # notices/deltas — reconciles pull get_nodes_delta(cursor) instead
        # of the full table, and an IN-STREAM seq jump (bounded-backlog shed
        # at the store) triggers the same cheap reconcile
        self._node_table_version = -1
        self._view_cursor = -1  # availability-view version (heartbeat delta)
        self._nodes_reconcile_task: Optional[asyncio.Task] = None
        # pre-gap cursor pinned at gap-detection time (the reconcile task
        # runs deferred; by then the gap-revealing notice's _v has advanced
        # the cursor past the shed window and a pull would replay nothing);
        # also re-armed by gaps landing while a reconcile is in flight
        self._nodes_reconcile_from: Optional[int] = None
        self._pulls_inflight: Dict[bytes, asyncio.Future] = {}
        # per-node metric pre-aggregation (reference: the per-node metrics
        # agent): workers ship DELTAS here; this daemon merges them into one
        # per-node series set under a cardinality cap and forwards the
        # merged deltas to the control store on the telemetry cadence
        self._metrics_pending: Dict[tuple, dict] = {}
        self._metrics_keys: Set[tuple] = set()
        self._metrics_dropped = 0
        # (reporter -> last applied seq): report_metrics is retried
        # verbatim by workers until acked, so ingestion dedups by sequence
        # — an applied-but-unacked flush must not double-count
        self._metrics_last_seq: "OrderedDict[bytes, int]" = OrderedDict()
        # daemon addresses declared dead by the control store: pulls from
        # them fail fast instead of retrying into a void (authoritative
        # death beats connect timeouts)
        self._dead_peer_addrs: Set[str] = set()
        # in-progress remote-client puts: oid -> (writable view, last-touch
        # ts). Swept by the reap loop — a client dying mid-put must not pin
        # store capacity forever (unsealed entries are not evictable).
        self._inbound_creates: Dict[bytes, Tuple[memoryview, float]] = {}
        # spilled objects: oid bytes -> (path, metadata, size). Reference:
        # raylet local_object_manager.h:45 spill/restore of primary copies.
        self.spilled: Dict[bytes, Tuple[str, int, int]] = {}
        self.spill_dir = os.path.join(
            session_dir, "spill", self.node_id.hex()[:12]
        )
        self._spill_lock: Optional[asyncio.Lock] = None
        # spawn-ordered suffix for worker chaos roles (deterministic fault
        # schedules — see _private.chaos)
        self._worker_role_counter = 0

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    async def start(self, port: int = 0) -> str:
        self.store = ShmObjectStore(
            self.store_name,
            create=True,
            size=GLOBAL_CONFIG.get("object_store_memory_bytes"),
        )
        self.server.register_service(self)
        addr = await self.server.start(self.host, port)
        self.address = addr
        self.control = RpcClient(self.control_address, name="daemon->cs")
        await self.control.connect()
        info = NodeInfo(
            node_id=self.node_id,
            address=addr,
            object_store_name=self.store_name,
            resources=self.total_resources,
            labels=self.labels,
        )
        self._node_info = info
        # Event-driven peer discovery: node registrations/deaths push over
        # the "nodes" channel, so the scheduler's cluster view is populated
        # at member-change time instead of waiting for heartbeat gossip
        # (reference: GcsNodeManager node add/removed pubsub).
        self.control.subscribe_channel("nodes", self._on_node_update)
        await self._subscribe_nodes()
        self.control.on_reconnect(
            lambda: self._subscribe_nodes(resync=True)
        )
        reg = await self.control.call("register_node", {"node": info.to_wire()})
        if reg.get("version") is not None:
            # the seed reply reflects the table at this version: start the
            # delta cursor here so the first reconcile is incremental
            self._node_table_version = reg["version"]
        for nw in reg.get("nodes", []):
            self._on_node_update(nw)
        self._tasks.append(spawn(self._heartbeat_loop()))
        self._tasks.append(spawn(self._reap_loop()))
        self._tasks.append(spawn(self._metrics_ship_loop()))
        if GLOBAL_CONFIG.get("log_to_driver"):
            self._tasks.append(spawn(self._log_forward_loop()))
        if GLOBAL_CONFIG.get("object_spill_enabled"):
            os.makedirs(self.spill_dir, exist_ok=True)
            self._tasks.append(spawn(self._spill_loop()))
        prestart = GLOBAL_CONFIG.get("worker_pool_prestart")
        if prestart < 0:
            prestart = min(
                16, int(self.total_resources.to_dict().get("CPU", 0)))
        for _ in range(prestart):
            spawn(self._spawn_worker(job_id=b"", reserve=False))
        self._oom_kills = 0
        self._tasks.append(spawn(self._memory_monitor_loop()))
        self._tasks.append(spawn(self._resource_gossip_loop()))
        # preemption plane: a real (GCE maintenance-event metadata/SIGTERM)
        # or synthetic (seeded chaos) preemption notice triggers a terminal
        # drain — the 30-90s of warning spot TPU VMs give must not be
        # thrown away (reference: autoscaler preemption handling)
        notice = chaos.preempt_notice()
        if notice is not None:
            delay_s, deadline_s = notice
            self._tasks.append(spawn(self._chaos_preempt(delay_s, deadline_s)))
        # correlated spot-reclaim wave (testing_preempt_wave): a seeded draw
        # preempts a fraction of the SPOT fleet inside one window — only
        # nodes advertising spot/preemptible capacity are eligible victims
        wave = chaos.preempt_wave(
            self.labels.get("spot") == "true"
            or self.labels.get("preemptible") == "true")
        if wave is not None:
            offset_s, deadline_s = wave
            self._tasks.append(spawn(self._chaos_preempt(offset_s, deadline_s)))
        if GLOBAL_CONFIG.get("preemption_watcher_enabled"):
            self._preempt_watcher = self._make_preempt_watcher()
            self._tasks.append(spawn(self._preempt_watcher.run()))
        logger.info(
            "daemon %s up at %s store=%s resources=%s",
            self.node_id.hex()[:8], addr, self.store_name, self.total_resources.to_dict(),
        )
        return addr

    async def stop(self):
        self._stopped = True
        if self._preempt_watcher is not None:
            self._preempt_watcher.stop()
        for t in self._tasks:
            t.cancel()
        for w in list(self.workers.values()):
            self._kill_worker_proc(w, "daemon shutdown")
        if self.control:
            await self.control.close()
        for c in self._peer_clients.values():
            await c.close()
        await self.server.stop()
        if self.store:
            self.store.destroy()
        if self.cgroups is not None:
            self.cgroups.cleanup()

    def _sync_drain_state(self, info: NodeInfo):
        """Mirror the control store's view of this node into the local
        lease gate (reference: DrainRaylet; undrain re-opens local grants).
        A drain carrying a deadline is TERMINAL (preemption / planned
        removal): beyond gating leases, it starts the full orchestration —
        finish running work, replicate primary copies, exit with an
        expected-termination record."""
        self._drain_sync_ts = time.monotonic()
        draining = info.state == pb.NODE_DRAINING
        if self._drain_task is not None and not draining:
            # terminal drain is one-way: once the exit orchestration is in
            # flight (e.g. a local preemption notice the store never heard
            # about), an ALIVE snapshot must not reopen the lease gate on a
            # node that is about to die — new tasks would be routed onto it
            # only to be killed at the deadline
            return
        if draining != self._draining:
            self._draining = draining
            logger.info("node %s drain -> %s (%s)", self.node_id.hex()[:8],
                        draining, info.drain_reason or "-")
            if not draining:
                self._try_schedule()
        if draining and info.drain_deadline and self._drain_task is None:
            # wall-clock deadline from the control store -> local monotonic
            deadline = time.monotonic() + max(
                0.0, info.drain_deadline - time.time())
            self._drain_task = spawn(
                self._drain_and_exit(info.drain_reason, deadline))

    async def _subscribe_nodes(self, resync: bool = False):
        """Subscribe to the "nodes" channel, detecting publish gaps: the
        subscribe reply carries the channel's current seq — a reconnect
        whose reply seq doesn't match the last notice we saw means deaths/
        drains were published while we were away (control-store failover
        window), so reconcile against the full node table instead of
        trusting the stream."""
        # capture the cursor BEFORE the subscribe lands: once the new
        # subscription exists, stream notices can max-advance the cursor
        # past the missed window and blind both the version comparison
        # and the reconcile's from-cursor pull
        pre_cursor = self._node_table_version
        reply = await self.control.call("subscribe", {"channel": "nodes"})
        server_seq = reply.get("seq")
        last_seen = self._nodes_seq
        # seq mismatch OR version-cursor mismatch: the ephemeral seq alone
        # can COINCIDE across a failover (new incumbent published exactly
        # as many notices as we had seen); the persisted version cursor
        # breaks the tie
        gap = resync and (
            (server_seq is not None and server_seq != last_seen)
            or (reply.get("version") is not None
                and reply["version"] != pre_cursor))
        if gap and (self._nodes_reconcile_from is None
                    or pre_cursor < self._nodes_reconcile_from):
            self._nodes_reconcile_from = pre_cursor
        if resync:
            # failover telemetry: outage as this daemon saw it + whether
            # the reconnect landed on a new store incarnation
            from ray_tpu._private import store_ha

            outage = None
            if self.control.last_disconnect_ts is not None:
                outage = time.monotonic() - self.control.last_disconnect_ts
            store_ha.record_store_reconnect("daemon", outage,
                                            new_incarnation=gap)
        if gap:
            logger.info("nodes-channel gap detected (last seen %s, server "
                        "at %s); reconciling node table", last_seen, server_seq)
            if not await self._reconcile_nodes():
                # keep the old last-seen seq so the next reconnect
                # re-detects this gap instead of marking it seen
                return
        if server_seq is not None:
            # RESET the baseline to the server's seq (don't max): a store
            # restart resets its counters, and a sticky high-water mark
            # would re-detect a phantom gap — and re-run the full table
            # reconcile — on every reconnect until the new counter caught up
            self._nodes_seq = server_seq

    def _spawn_nodes_reconcile(self) -> None:
        """One reconcile in flight at a time — a burst of gap signals
        (every shed notice of a churn wave) coalesces into one pull."""
        if (self._nodes_reconcile_task is None
                or self._nodes_reconcile_task.done()):
            self._nodes_reconcile_task = spawn(self._reconcile_nodes())

    async def _reconcile_nodes(self) -> bool:
        """Replay node-table mutations missed on the pubsub stream. With
        delta sync on this pulls get_nodes_delta(cursor) — O(missed
        changes); the wires are the exact notices the stream would have
        delivered (same `_v`/replica payloads), applied through the same
        handler. Falls back to the full table otherwise. Loops while
        fresh gap signals land mid-flight — a reply generated before a
        second shed cannot contain it."""
        while True:
            floor = self._nodes_reconcile_from
            self._nodes_reconcile_from = None
            pre = self._node_table_version
            try:
                full = True
                if GLOBAL_CONFIG.get("node_table_delta_sync"):
                    reply = await self.control.call(
                        "get_nodes_delta",
                        {"cursor": floor if floor is not None else pre})
                    full = bool(reply.get("full"))
                    nodes = reply.get("updates") or reply.get("nodes") or []
                    version = reply.get("version")
                else:
                    version = None
                    nodes = (await self.control.call(
                        "get_all_nodes", {})).get("nodes", [])
                for nw in nodes:
                    self._apply_node_update(nw)
                if full:
                    # a full snapshot is authoritative membership: peers
                    # absent from it (dead + already pruned from the
                    # store's retention window) must not linger in the
                    # scheduling view
                    present = {NodeInfo.from_wire(nw).node_id.hex()
                               for nw in nodes}
                    for hexid in list(self.peer_nodes):
                        if hexid not in present:
                            self.peer_nodes.pop(hexid, None)
                            self.cluster_view.pop(hexid, None)
                            self._view_seq.pop(hexid, None)
                if version is not None:
                    # authoritative assignment AFTER the apply: brings the
                    # cursor back DOWN after a store restart's counter
                    # reset (the stream path's monotonic guard never would)
                    self._node_table_version = version
            except Exception:  # noqa: BLE001 — store still mid-failover:
                # re-arm the pre-gap floor (stream notices advance the
                # live cursor past the missed window; a later from-cursor
                # pull would replay nothing) for the next gap signal /
                # reconnect / heartbeat-version retry
                used = floor if floor is not None else pre
                if (self._nodes_reconcile_from is None
                        or used < self._nodes_reconcile_from):
                    self._nodes_reconcile_from = used
                logger.warning("node-table reconcile failed", exc_info=True)
                return False
            if self._nodes_reconcile_from is None:
                return True

    def _on_node_update(self, message: dict):
        seq = message.get("_seq")
        if seq is not None:
            if self._nodes_seq is not None and seq > self._nodes_seq + 1:
                # in-stream publish gap: the store shed notices to us (its
                # bounded per-subscriber backlog) — reconcile from the
                # PRE-gap cursor, pinned NOW: this very message's _v will
                # advance the cursor past the shed window before the
                # deferred reconcile task runs
                logger.info("nodes-channel in-stream gap (%d -> %d); "
                            "reconciling", self._nodes_seq, seq)
                if (self._nodes_reconcile_from is None
                        or self._node_table_version
                        < self._nodes_reconcile_from):
                    self._nodes_reconcile_from = self._node_table_version
                self._spawn_nodes_reconcile()
            self._nodes_seq = max(self._nodes_seq or 0, seq)
        ver = message.get("_v")
        if ver is not None:
            if ver <= self._node_table_version:
                # stale replay: the store's coalescing window can deliver
                # a notice AFTER the reconcile reply that already covered
                # it — applying would resurrect superseded state (e.g. a
                # DEAD peer back to DRAINING). A restarted store's lower
                # counter is reset by _reconcile_nodes' authoritative
                # post-apply assignment, so skipping here can't wedge.
                return
            self._node_table_version = ver
        self._apply_node_update(message)

    def _apply_node_update(self, message: dict):
        info = NodeInfo.from_wire(message)
        hexid = info.node_id.hex()
        if hexid == self.node_id.hex():
            self._sync_drain_state(info)
            return
        if info.state == pb.NODE_ALIVE:
            self.peer_nodes[hexid] = info
            # an address can be reused by a re-registered node: it is no
            # longer an authoritatively-dead pull source
            self._dead_peer_addrs.discard(info.address)
            # seed with total resources; the next gossip beat corrects it
            self.cluster_view.setdefault(hexid, info.resources)
            self._try_schedule()
        else:
            self.peer_nodes.pop(hexid, None)
            self.cluster_view.pop(hexid, None)
            self._view_seq.pop(hexid, None)
            if info.state == pb.NODE_DEAD:
                # DEAD only — a DRAINING node still serves its objects.
                # Retire the pooled transfer client too: a later pull aimed
                # at the dead peer must fail fast, not burn retries through
                # a half-open cached transport
                self._dead_peer_addrs.add(info.address)
                dead = self._peer_clients.pop(info.address, None)
                if dead is not None:
                    spawn(dead.close())

    # ------------------------------------------------------------------
    # peer resource-view gossip (reference: src/ray/ray_syncer/
    # ray_syncer.h:91 — versioned resource-view snapshots exchanged
    # directly between raylets, decoupling scheduling freshness from the
    # control store's heartbeat cadence and surviving its brief outages)
    # ------------------------------------------------------------------

    def _gossip_entries(self) -> dict:
        """Everything this node knows, keyed by origin: own availability at
        its own (monotonic) version, plus relayed peer entries."""
        self._my_view_seq += 1
        entries = {
            self.node_id.hex(): [self._my_view_seq, self.available.to_wire()]
        }
        for hexid, avail in self.cluster_view.items():
            if hexid == self.node_id.hex():
                continue
            seq = self._view_seq.get(hexid)
            if seq is not None:
                entries[hexid] = [seq, avail.to_wire()]
        return entries

    def _merge_gossip(self, entries: dict) -> bool:
        """Adopt entries with a newer per-origin version; returns whether
        anything changed (→ re-run the scheduler)."""
        changed = False
        for hexid, (seq, wire) in entries.items():
            if hexid == self.node_id.hex():
                continue
            if hexid not in self.peer_nodes:
                continue  # unknown/dead origin: membership comes via pubsub
            if seq > self._view_seq.get(hexid, -1):
                self._view_seq[hexid] = seq
                self.cluster_view[hexid] = ResourceSet.from_wire(wire)
                changed = True
        return changed

    async def rpc_get_view(self, conn_id: int, payload: dict) -> dict:
        """This daemon's current cluster resource view + gossip versions
        (observability/debugging; reference: ray_syncer state dumps)."""
        return {
            "self": self.node_id.hex(),
            "available": self.available.to_wire(),
            "view": {h: a.to_wire() for h, a in self.cluster_view.items()},
            "versions": dict(self._view_seq),
        }

    async def rpc_sync_view(self, conn_id: int, payload: dict) -> dict:
        """Anti-entropy exchange: merge the sender's entries, reply with
        ours (reference: RaySyncer bidi snapshot exchange)."""
        if self._merge_gossip(payload.get("entries", {})):
            self._try_schedule()
        return {"entries": self._gossip_entries()}

    async def _resource_gossip_loop(self):
        period = GLOBAL_CONFIG.get("resource_gossip_period_s")
        if period <= 0:
            return
        import random as _random

        while not self._stopped:
            await asyncio.sleep(period)
            peers = [
                info for hexid, info in self.peer_nodes.items()
                if info.state == pb.NODE_ALIVE
                and hexid != self.node_id.hex()
            ]
            if not peers:
                continue
            fanout = min(len(peers),
                         GLOBAL_CONFIG.get("resource_gossip_fanout"))
            for info in _random.sample(peers, fanout):
                try:
                    client = self._peer_clients.get(info.address)
                    if client is None:
                        client = RpcClient(info.address, name="daemon->peer")
                        await client.connect()
                        self._peer_clients[info.address] = client
                    reply = await client.call(
                        "sync_view", {"entries": self._gossip_entries()},
                        timeout=period * 4)
                    if self._merge_gossip(reply.get("entries", {})):
                        self._try_schedule()
                except Exception:  # noqa: BLE001 — peer down; heartbeat prunes
                    continue

    async def _heartbeat_loop(self):
        import random as _random

        period = (GLOBAL_CONFIG.get("heartbeat_period_s")
                  or GLOBAL_CONFIG.get("health_check_period_s"))
        jitter = GLOBAL_CONFIG.get("heartbeat_jitter")
        delta_sync = GLOBAL_CONFIG.get("node_table_delta_sync")
        # demand-shape budget per beat: leases get the full cap, infeasible
        # shapes a quarter (they only need to be sampled, not enumerated,
        # for the autoscaler to see the node type that's missing)
        shape_cap = GLOBAL_CONFIG.get("heartbeat_pending_shapes_max")
        while not self._stopped:
            try:
                pending_leases = [
                    p for p in self.pending if not p.future.done()
                ]
                now = time.monotonic()
                self._infeasible_seen = {
                    k: t for k, t in self._infeasible_seen.items()
                    if now - t < 5.0
                }
                beat_started = time.monotonic()
                payload = {
                    "node_id": self.node_id.binary(),
                    "available": self.available.to_wire(),
                    # per-node physical stats for the dashboard/state API
                    # (reference: the per-node dashboard agent's psutil
                    # reporter, dashboard/modules/reporter/)
                    "stats": self._node_stats(),
                    # scheduling load → autoscaler demand (reference:
                    # raylet resource-view sync carries load). Infeasible
                    # shapes count too: no live node can host them, but
                    # the autoscaler may be able to provision one.
                    "pending": len(pending_leases) + len(self._infeasible_seen),
                    "pending_resources": [
                        p.spec_resources.to_wire()
                        for p in pending_leases[:shape_cap]
                    ] + [dict(k) for k in
                         list(self._infeasible_seen)[:max(1, shape_cap // 4)]],
                }
                if delta_sync:
                    # scale mode: present the availability cursor — the
                    # reply carries only CHANGES, not the O(nodes) view
                    payload["view_cursor"] = self._view_cursor
                reply = await self.control.call(
                    "heartbeat", payload,
                    # short deadline: a dropped beat must not silence this
                    # node long enough to trip health_check_timeout_s
                    timeout=period * 2,
                )
                if reply.get("unknown"):
                    # the control store restarted without (or before) our
                    # record: re-register so the cluster view includes us
                    await self.control.call(
                        "register_node", {"node": self._node_info.to_wire()}
                    )
                    continue
                if "view_version" in reply:
                    self._apply_view_reply(reply)
                else:
                    self.cluster_view = {
                        nid: ResourceSet.from_wire(w)
                        for nid, w in reply.get("view", {}).items()
                    }
                for nw in reply.get("nodes", []):
                    info = NodeInfo.from_wire(nw)
                    self.peer_nodes[info.node_id.hex()] = info
                    if (info.node_id.hex() == self.node_id.hex()
                            and beat_started > self._drain_sync_ts):
                        # stale-reply guard: a reply snapshotted before the
                        # last pubsub drain/undrain push must not revert it
                        self._sync_drain_state(info)
                self._try_schedule()
            except Exception as e:  # noqa: BLE001
                logger.warning("heartbeat failed: %s", e)
            # jittered sleep: a register storm phase-aligns every daemon's
            # beat; de-phasing keeps 1000 heartbeats from landing on the
            # same control-store event-loop tick
            await asyncio.sleep(
                period * (1.0 + jitter * _random.uniform(-1.0, 1.0)))

    def _apply_view_reply(self, reply: dict) -> None:
        """Fold a cursor heartbeat reply into the scheduling view: changed
        availabilities replace, removed nodes drop, a full snapshot (cursor
        behind the store's change log) rebuilds."""
        full = reply.get("view_full")
        if full is not None:
            self.cluster_view = {
                nid: ResourceSet.from_wire(w) for nid, w in full.items()
            }
        else:
            for nid, w in (reply.get("view_delta") or {}).items():
                self.cluster_view[nid] = ResourceSet.from_wire(w)
            for nid in reply.get("view_removed") or ():
                self.cluster_view.pop(nid, None)
        self._view_cursor = reply["view_version"]
        nodes_version = reply.get("nodes_version")
        if (nodes_version is not None
                and nodes_version != self._node_table_version) \
                or self._nodes_reconcile_from is not None:
            # membership moved while our pubsub stream was quiet (or shed,
            # or the store restarted and reset its counter), OR a pinned
            # pre-gap floor is waiting for a retry (its reconcile failed
            # mid-failover; the live cursor may have caught the server
            # version since, so the version check alone would go blind):
            # pull the missed mutations from the cursor/floor
            self._spawn_nodes_reconcile()

    async def _reap_loop(self):
        """Poll worker processes for death; reap idle surplus."""
        while not self._stopped:
            await asyncio.sleep(0.1)
            self._sweep_stale_inbound_creates()
            for w in list(self.workers.values()):
                if w.state != W_DEAD and w.proc.poll() is not None:
                    await self._on_worker_death(w)
            # reap surplus idle workers (only genuinely idle ones — the list
            # may hold stale ids for workers that have since been leased)
            max_idle = GLOBAL_CONFIG.get("worker_pool_max_idle")
            for job_id, idle in self.idle_by_job.items():
                idle[:] = [
                    wid for wid in idle
                    if self.workers.get(wid) is not None
                    and self.workers[wid].state == W_IDLE
                ]
                while len(idle) > max_idle:
                    wid = idle.pop(0)
                    w = self.workers.get(wid)
                    if w is not None and w.state == W_IDLE:
                        self._kill_worker_proc(w, "idle reaping")

    async def _log_forward_loop(self):
        """Tail workers' stdout/stderr files and push fresh lines to the
        control store's per-job log channel (reference: log_monitor.py
        tailing + GCS pubsub; drivers print them via print_worker_logs)."""
        offsets: Dict[Tuple[bytes, str], int] = {}
        while not self._stopped:
            await asyncio.sleep(0.5)
            for w in list(self.workers.values()):
                short = w.worker_id.hex()[:12]
                for suffix in (".out", ".err"):
                    path = os.path.join(
                        self.session_dir, "logs", f"worker-{short}{suffix}")
                    key = (w.worker_id.binary(), suffix)
                    try:
                        size = os.path.getsize(path)
                    except OSError:
                        continue
                    off = offsets.get(key, 0)
                    if size <= off:
                        continue
                    try:
                        # off-loop: one tail read per worker per tick adds up
                        # on a busy node, and log files can sit on slow disks
                        chunk = await asyncio.to_thread(
                            _read_file_range, path, off,
                            min(size - off, 256 * 1024))
                    except OSError:
                        continue
                    offsets[key] = off + len(chunk)
                    lines = chunk.decode("utf-8", "replace").splitlines()
                    if not lines:
                        continue
                    try:
                        await self.control.call("publish_logs", {
                            "job_id": w.job_id,
                            "worker_id": w.worker_id.binary(),
                            "node_id": self.node_id.hex(),
                            "stream": suffix[1:],
                            "lines": lines[:200],
                        }, timeout=5)
                    except Exception:  # noqa: BLE001 — control blip; retry next tick
                        offsets[key] = off  # re-read the chunk next round
            # drop offsets of forgotten workers
            live = {w.worker_id.binary() for w in self.workers.values()}
            for key in [k for k in offsets if k[0] not in live]:
                offsets.pop(key, None)

    # ------------------------------------------------------------------
    # worker pool (reference: worker_pool.h:284)
    # ------------------------------------------------------------------

    async def _spawn_worker(self, job_id: bytes,
                            tpu_chips: Optional[List[int]] = None,
                            reserve: bool = True,
                            env_key: str = "",
                            runtime_env: Optional[dict] = None) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        log_base = os.path.join(
            self.session_dir, "logs", f"worker-{worker_id.hex()[:12]}"
        )
        env = dict(os.environ)
        env.update(
            RT_CONTROL_ADDR=self.control_address,
            RT_DAEMON_ADDR=self.address,
            RT_NODE_ID=self.node_id.hex(),
            RT_WORKER_ID=worker_id.hex(),
            RT_STORE_NAME=self.store_name,
            RT_JOB_ID=job_id.hex(),
            RT_SESSION_DIR=self.session_dir,
            RT_CONFIG_JSON=GLOBAL_CONFIG.serialize_overrides(),
            RT_ENV_KEY=env_key,
            # spawn-ordered chaos role (see _private.chaos: the seeded PRNG
            # mixes in this label, making worker fault schedules replayable)
            RT_CHAOS_ROLE=f"{chaos.role()}.w{self._worker_role_counter}",
        )
        self._worker_role_counter += 1
        # the framework itself must resolve from the env worker's (possibly
        # venv) interpreter regardless of cwd
        repo_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
        python_exe = sys.executable
        cwd = None
        if env_key and runtime_env:
            python_exe, cwd = await self._build_worker_env(runtime_env)
        if tpu_chips:
            from ray_tpu.tpu.accelerator import TpuAcceleratorManager

            TpuAcceleratorManager.set_visible_chips_env(
                env, list(tpu_chips), self._tpu_chips_per_host
            )
        try:
            # rtlint: disable=R001 paired with the Popen below: worker spawn is a ms-scale cold path, not per-task
            out = open(log_base + ".out", "ab")
            err = open(log_base + ".err", "ab")  # rtlint: disable=R001 see line above
            proc = subprocess.Popen(
                [python_exe, "-m", "ray_tpu._private.default_worker"],
                env=env, stdout=out, stderr=err, start_new_session=True,
                cwd=cwd,
            )
            out.close()
            err.close()
        except Exception:
            if tpu_chips:
                self._return_chips(tpu_chips)
            raise
        if self.cgroups is not None:
            self.cgroups.add_worker(proc.pid)
        handle = WorkerHandle(worker_id, proc, job_id)
        handle.env_key = env_key
        handle.reserved = reserve
        if tpu_chips:
            # from here on the chips travel with the handle; _forget_worker
            # returns them to the pool exactly once
            handle.tpu_chips = tuple(tpu_chips)
        self.workers[worker_id.binary()] = handle
        try:
            await asyncio.wait_for(
                handle.ready_event.wait(),
                GLOBAL_CONFIG.get("worker_register_timeout_s"),
            )
        except asyncio.TimeoutError:
            self._kill_worker_proc(handle, "register timeout")
            raise RuntimeError(
                f"worker {worker_id.hex()[:8]} failed to register "
                f"(see {log_base}.err)"
            )
        return handle

    async def _build_worker_env(self, runtime_env: dict):
        """Materialize an isolating runtime env for a fresh worker: the
        content-addressed venv (pip) and/or extracted working_dir. Returns
        (python_exe, cwd). Runs BEFORE the register timeout starts."""
        from ray_tpu._private.runtime_env_mgr import _fetch_extract, ensure_venv

        cache_root = os.path.join(self.session_dir, "runtime_env_cache")
        os.makedirs(cache_root, exist_ok=True)
        python_exe = sys.executable
        pip = runtime_env.get("pip")
        uv = runtime_env.get("uv")
        if pip:
            python_exe = await asyncio.to_thread(
                ensure_venv, list(pip), cache_root)
        elif uv:
            python_exe = await asyncio.to_thread(
                ensure_venv, list(uv), cache_root, "uv")
        cwd = None
        wd_uri = runtime_env.get("working_dir_uri")
        if wd_uri:
            # duck-typed `cw`: _fetch_extract only uses .control.call
            cwd = await _fetch_extract(wd_uri, self, cache_root)
        return python_exe, cwd

    async def rpc_worker_ready(self, conn_id: int, payload: dict) -> dict:
        w = self.workers.get(payload["worker_id"])
        if w is None:
            return {"ok": False, "error": "unknown worker"}
        w.address = payload["address"]
        w.state = W_IDLE
        if not w.reserved:
            self.idle_by_job.setdefault(
                (w.job_id, w.env_key), []).append(w.worker_id.binary())
        w.ready_event.set()
        return {"ok": True}

    def _kill_worker_proc(self, w: WorkerHandle, reason: str):
        if w.state == W_DEAD:
            return
        w.state = W_DEAD
        try:
            os.killpg(os.getpgid(w.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        self._forget_worker(w)
        # intentional kills must reach the death records too: owners' borrow
        # reapers free this worker's borrows only on an authoritative notice
        spawn(self._report_worker_death_quiet(w, reason=reason))
        logger.info("killed worker %s: %s", w.worker_id.hex()[:8], reason)

    async def _report_worker_death_quiet(self, w: WorkerHandle,
                                         reason: str = "",
                                         exit_code: Optional[int] = None):
        try:
            await self.control.call(
                "report_worker_death",
                {"worker_id": w.worker_id.binary(), "reason": reason,
                 "exit_code": exit_code}, timeout=10)
        except Exception:  # noqa: BLE001 — control store may be restarting
            logger.debug("report_worker_death failed", exc_info=True)

    def _forget_worker(self, w: WorkerHandle):
        self.workers.pop(w.worker_id.binary(), None)
        idle = self.idle_by_job.get((w.job_id, w.env_key), [])
        if w.worker_id.binary() in idle:
            idle.remove(w.worker_id.binary())
        if w.actor_id is not None:
            # drop the idempotent-create cache entry, or the daemon leaks one
            # completed task per actor ever created on this node
            self._creating_actors.pop(w.actor_id, None)
        if w.tpu_chips:
            self._return_chips(w.tpu_chips)
            w.tpu_chips = None

    def _alloc_chips(self, n: int) -> List[int]:
        if len(self._tpu_free_chips) < n:
            raise RuntimeError(
                f"TPU chip accounting out of sync: need {n}, "
                f"free {self._tpu_free_chips}"
            )
        chips, self._tpu_free_chips = (
            self._tpu_free_chips[:n], self._tpu_free_chips[n:]
        )
        return chips

    def _return_chips(self, chips) -> None:
        self._tpu_free_chips.extend(chips)
        self._tpu_free_chips.sort()

    async def _on_worker_death(self, w: WorkerHandle,
                               reason: Optional[str] = None):
        prev_state = w.state
        w.state = W_DEAD
        self._forget_worker(w)
        exit_code = w.proc.poll()
        if exit_code is None:
            # freshly signalled: reap briefly so the death record carries
            # the real exit code instead of None
            try:
                exit_code = await asyncio.to_thread(w.proc.wait, 1.0)
            except subprocess.TimeoutExpired:
                pass
        if reason is None:
            # classify the unexpected exit so downstream errors say WHY
            # (reference: WorkerExitType): SIGKILL with the daemon healthy is
            # almost always the kernel OOM killer or an operator kill
            if exit_code == -signal.SIGKILL:
                reason = "worker killed (SIGKILL: OOM killer or external kill)"
            elif exit_code == 137:
                reason = "worker crashed (exit 137: killed/chaos process_kill)"
            else:
                reason = f"worker process exited ({exit_code})"
        logger.warning(
            "worker %s died (state=%s, code=%s): %s",
            w.worker_id.hex()[:8], prev_state, exit_code, reason,
        )
        flight_recorder.record(
            "worker", "death", worker=w.worker_id.hex()[:8],
            state=prev_state, exit_code=exit_code, reason=reason)
        if w.lease_id is not None:
            self._release_lease(w.lease_id)
        self._release_actor_resources(w)
        # authoritative death record: owners' borrow reapers free this
        # worker's borrows only once the exit is recorded here
        await self._report_worker_death_quiet(w, reason=reason,
                                              exit_code=exit_code)
        if w.actor_id is not None:
            try:
                await self.control.call(
                    "report_actor_death",
                    {"actor_id": w.actor_id, "reason": reason},
                    timeout=10,
                )
            except Exception:  # noqa: BLE001
                logger.exception("failed to report actor death")

    async def _get_idle_worker(
            self, job_id: bytes, env_key: str = "",
            runtime_env: Optional[dict] = None) -> WorkerHandle:
        idle = self.idle_by_job.setdefault((job_id, env_key), [])
        while idle:
            wid = idle.pop()
            w = self.workers.get(wid)
            if w is not None and w.state == W_IDLE and w.proc.poll() is None:
                return w
        # adopt a prestarted generic worker (spawned before any job existed)
        # — only for env-less leases: an env-keyed lease needs a worker
        # built for that env (venv interpreter, working dir)
        generic = self.idle_by_job.get((b"", ""), [])
        while job_id != b"" and env_key == "" and generic:
            wid = generic.pop()
            w = self.workers.get(wid)
            if w is not None and w.state == W_IDLE and w.proc.poll() is None:
                w.job_id = job_id
                return w
        return await self._spawn_worker(job_id, env_key=env_key,
                                        runtime_env=runtime_env)

    def _drop_from_idle(self, w: WorkerHandle):
        idle = self.idle_by_job.get((w.job_id, w.env_key), [])
        if w.worker_id.binary() in idle:
            idle.remove(w.worker_id.binary())

    # ------------------------------------------------------------------
    # lease scheduling (reference: cluster_lease_manager.cc:195)
    # ------------------------------------------------------------------

    async def rpc_request_lease(self, conn_id: int, payload: dict) -> dict:
        # Idempotent by caller-supplied request_key: a client retrying after
        # a timed-out/dropped call must attach to the original request, not
        # queue (and eventually be granted) a second lease (reference:
        # RequestWorkerLease is retried by the retryable grpc client; chaos
        # tests drop it on purpose).
        key = payload.get("request_key")
        if key is None:
            return await self._request_lease_inner(payload)
        if key in self._cancelled_lease_keys:
            # cancelled before this (late/resent) frame arrived: refuse
            # rather than queue a lease nobody will claim
            return {"cancelled": True, "error": "lease request cancelled"}
        task = self._lease_requests.get(key)
        if task is None:
            task = spawn(self._request_lease_inner(payload))
            self._lease_requests[key] = task

            def _settle(t, key=key):
                reply = None if t.cancelled() or t.exception() else t.result()
                if reply is not None and reply.get("granted"):
                    # cache until the lease is released, so late retries see
                    # the same grant instead of double-granting
                    self._lease_key_by_id[reply["lease_id"]] = key
                else:
                    self._lease_requests.pop(key, None)

            task.add_done_callback(_settle)
        return await asyncio.shield(task)

    def _note_infeasible(self, res: ResourceSet):
        """Stamp a lease shape no live node can host (or a draining node
        turned away) for the heartbeat demand signal; entries expire after
        5s unless the retrying client refreshes them."""
        self._infeasible_seen[
            tuple(sorted(res.to_wire().items()))
        ] = time.monotonic()

    async def _request_lease_inner(self, payload: dict) -> dict:
        spec_res = ResourceSet.from_wire(payload["resources"])
        strategy = pb.SchedulingStrategy.from_wire(payload.get("strategy"))
        job_id = payload["job_id"]
        hops = payload.get("hops", 0)
        runtime_env = payload.get("runtime_env") or None
        logger.debug("request_lease res=%s hops=%s", spec_res.to_dict(), hops)

        if strategy.kind == pb.STRATEGY_PLACEMENT_GROUP:
            if self._draining:
                # DrainRaylet rejects all new leases; the caller retries until
                # the node dies and the control store reschedules the PG.
                # Record the shape as demand — the autoscaler must see work
                # a draining node turned away, or it can never undrain us.
                self._note_infeasible(spec_res)
                return {"retry": True, "draining": True}
            return await self._grant_pg_lease(spec_res, strategy, job_id,
                                              runtime_env)

        # Cluster policy: pick the best node; spill if it isn't us.
        if not self._draining:
            choice = self._choose_node(spec_res, strategy)
        else:
            choice = self._choose_node(spec_res, strategy, exclude_self=True)
        my_hex = self.node_id.hex()
        if choice is not None and choice != my_hex:
            if hops < GLOBAL_CONFIG.get("lease_spillback_max_hops"):
                peer = self.peer_nodes.get(choice)
                if peer is not None:
                    return {"spillback": peer.address, "node_id": choice}
            # Hard node affinity to a node we can't reach (unknown peer, dead,
            # or hop cap) must fail, not silently run on the wrong node
            # (reference: node_affinity_scheduling_policy.h — hard affinity to
            # an unavailable node is infeasible).
            if strategy.kind == pb.STRATEGY_NODE_AFFINITY and not strategy.soft:
                return {"infeasible": True,
                        "error": f"node {choice} not available for hard affinity"}
        if choice is None and not self._feasible_anywhere(spec_res, strategy):
            self._note_infeasible(spec_res)
            return {"infeasible": True}
        if self._draining:
            # Never grant locally while draining; the caller retries until the
            # drain finishes or another node has capacity (reference:
            # DrainRaylet rejects new leases during drain). The rejected shape
            # still counts as demand: without it, work only this (draining)
            # node can host is invisible to the autoscaler and the undrain
            # that would unblock it never happens — a livelock.
            self._note_infeasible(spec_res)
            return {"retry": True, "draining": True}
        # Local grant path: queue until available.
        pending = PendingLease(spec_res, strategy, job_id, hops, runtime_env)
        self.pending.append(pending)
        self._try_schedule()
        return await pending.future

    @staticmethod
    def _labels_match(labels: Optional[Dict[str, str]],
                      selector: Optional[Dict[str, str]]) -> bool:
        """One definition of label-selector matching for every scheduling
        decision (choose/grant/spill/feasibility) — shared with the
        control store via pb.labels_match; supports "!value" anti-affinity
        (reference: node_label_scheduling_policy.h)."""
        return pb.labels_match(labels, selector)

    def _choose_node(self, res: ResourceSet, strategy: pb.SchedulingStrategy,
                     exclude_self: bool = False) -> Optional[str]:
        """Hybrid pack/spread over the gossiped view (hybrid_scheduling_policy.h:50)."""
        my_hex = self.node_id.hex()
        if strategy.kind == pb.STRATEGY_NODE_AFFINITY and strategy.node_id:
            return strategy.node_id
        candidates: List[Tuple[float, str]] = []
        view = dict(self.cluster_view)
        view[my_hex] = self.available
        for nid, avail in view.items():
            if exclude_self and nid == my_hex:
                continue
            info = self.peer_nodes.get(nid)
            if info is not None and pb.is_sim_node(info.labels):
                continue  # scale-harness nodes never take real work
            if strategy.label_selector:
                # reference: node_label_scheduling_policy.h:25 — plain
                # tasks select nodes by label. SELF is checked against
                # self.labels (it has no peer_nodes entry); peers with no
                # info yet are skipped rather than matched blindly.
                labels = (self.labels if nid == my_hex
                          else info.labels if info is not None else None)
                if not self._labels_match(labels, strategy.label_selector):
                    continue
            if res.is_subset_of(avail):
                total = info.resources if info else self.total_resources
                denom = max(1, sum(total.to_wire().values()))
                util = 1.0 - sum(avail.to_wire().values()) / denom
                candidates.append((util, nid))
        if not candidates:
            return None
        threshold = GLOBAL_CONFIG.get("scheduler_spread_threshold")
        if strategy.kind == pb.STRATEGY_SPREAD:
            candidates.sort(key=lambda c: c[0])
        else:
            below = [c for c in candidates if c[0] < threshold]
            if below:
                # pack: most utilized under threshold; prefer self on ties
                below.sort(key=lambda c: (-c[0], c[1] != my_hex))
                return below[0][1]
            candidates.sort(key=lambda c: c[0])
        # prefer self on equal footing to avoid pointless spills
        best_util = candidates[0][0]
        for util, nid in candidates:
            if nid == my_hex and util <= best_util + 1e-9:
                return my_hex
        return candidates[0][1]

    def _feasible_anywhere(self, res: ResourceSet,
                           strategy: Optional[pb.SchedulingStrategy] = None
                           ) -> bool:
        selector = strategy.label_selector if strategy is not None else None
        if (self._labels_match(self.labels, selector)
                and res.is_subset_of(self.total_resources)):
            return True
        for nid, info in self.peer_nodes.items():
            if (info.state == pb.NODE_ALIVE
                    and not pb.is_sim_node(info.labels)
                    and self._labels_match(info.labels, selector)
                    and res.is_subset_of(info.resources)):
                return True
        return False

    def _try_schedule(self):
        if not self.pending:
            return
        still: List[PendingLease] = []
        # optimistic view of PEER capacity for spillback of queued leases:
        # deducted as we spill so a burst doesn't all target one peer
        peer_view = {
            nid: avail for nid, avail in self.cluster_view.items()
            if nid != self.node_id.hex()
        }
        hop_cap = GLOBAL_CONFIG.get("lease_spillback_max_hops")
        for p in self.pending:
            if p.future.done():
                continue
            local_ok = self._labels_match(
                self.labels, p.strategy.label_selector)
            if local_ok and p.spec_resources.is_subset_of(self.available):
                self.available = self.available - p.spec_resources
                spawn(self._grant(p, pg_id=None, bundle_index=-1))
                continue
            # locally stuck: a peer (possibly one that just joined — the
            # autoscaler's whole point) may have room now. Re-evaluating
            # queued leases on every schedule tick is what moves demand onto
            # scaled-up nodes (reference: cluster lease manager spillback).
            # Node-affinity leases stay: they queued HERE on purpose.
            if (p.hops < hop_cap
                    and p.strategy.kind in (pb.STRATEGY_DEFAULT,
                                            pb.STRATEGY_SPREAD)):
                target = None
                for nid, avail in peer_view.items():
                    info = self.peer_nodes.get(nid)
                    if info is None or info.state != pb.NODE_ALIVE:
                        continue
                    if pb.is_sim_node(info.labels):
                        continue  # scripted grants must not take real work
                    if not self._labels_match(
                            info.labels, p.strategy.label_selector):
                        continue
                    if p.spec_resources.is_subset_of(avail):
                        target = nid
                        break
                if target is not None:
                    peer_view[target] = peer_view[target] - p.spec_resources
                    p.future.set_result({
                        "spillback": self.peer_nodes[target].address,
                        "node_id": target,
                    })
                    continue
            still.append(p)
        self.pending = still

    async def _grant(self, p: PendingLease, pg_id: Optional[bytes],
                     bundle_index: int = -1):
        n_tpu = int(p.spec_resources.get("TPU"))
        try:
            if n_tpu > 0:
                # TPU visibility is baked into the worker env at spawn, so a
                # chip-holding lease always gets a fresh worker bound to its
                # granted chip ids (reference: tpu.py:42-55; workers holding
                # devices are gang-bound, not pooled)
                from ray_tpu._private.runtime_env_mgr import env_isolation_key

                w = await self._spawn_worker(
                    p.job_id, tpu_chips=self._alloc_chips(n_tpu),
                    env_key=env_isolation_key(p.runtime_env),
                    runtime_env=p.runtime_env,
                )
            else:
                renv = p.runtime_env
                ekey = (renv or {}).get("env_key", "")
                w = await self._get_idle_worker(p.job_id, ekey, renv)
        except Exception as e:  # noqa: BLE001
            if pg_id is None:
                self.available = self.available + p.spec_resources
            if not p.future.done():
                p.future.set_result({"error": f"worker spawn failed: {e}"})
            return
        lease_id = os.urandom(16)
        w.state = W_LEASED
        w.lease_id = lease_id
        self.leases[lease_id] = (
            w.worker_id.binary(), p.spec_resources, pg_id, bundle_index
        )
        if not p.future.done():
            flight_recorder.record(
                "lease", "grant", worker=w.worker_id.hex()[:8],
                job=p.job_id.hex()[:8])
            p.future.set_result({
                "granted": True,
                "lease_id": lease_id,
                "worker_id": w.worker_id.binary(),
                "worker_address": w.address,
                "node_id": self.node_id.hex(),
                "grant_wait_ns": time.monotonic_ns() - p.t0_ns,
            })
        else:  # caller gave up (timeout) — reclaim
            self._release_lease(lease_id)

    @staticmethod
    def _pg_request_feasible(res: ResourceSet, pg: dict,
                             indices: List[int]) -> bool:
        """True when *res* fits inside the TOTAL reservation of at least
        one candidate bundle — False means the request can NEVER be
        granted from this group (permanent infeasibility, not a
        currently-occupied bundle)."""
        return any(
            i in pg["bundles"] and res.is_subset_of(pg["bundles"][i])
            for i in indices
        )

    async def _grant_pg_lease(self, res: ResourceSet, strategy: pb.SchedulingStrategy,
                              job_id: bytes,
                              runtime_env: Optional[dict] = None) -> dict:
        pg_id = bytes.fromhex(strategy.placement_group_id)
        pg = self.pg_prepared.get(pg_id)
        if pg is None or pg["state"] != "committed":
            return {"error": "placement group not committed on this node", "retry": True}
        free: Dict[int, ResourceSet] = pg["free"]
        idx = strategy.bundle_index
        indices = [idx] if idx >= 0 else sorted(free.keys())
        for i in indices:
            if i in free and res.is_subset_of(free[i]):
                free[i] = free[i] - res
                p = PendingLease(res, strategy, job_id, 0, runtime_env)
                await self._grant(p, pg_id=pg_id, bundle_index=i)
                reply = await p.future
                if reply.get("granted"):
                    reply["bundle_index"] = i
                else:
                    free[i] = free[i] + res
                return reply
        if not self._pg_request_feasible(res, pg, indices):
            # the request exceeds the bundle's TOTAL reservation: it can
            # never be granted here — surface a permanent infeasibility
            # instead of letting the caller retry forever
            return {"infeasible_in_pg": True,
                    "error": (f"resources {res.to_dict()} exceed the "
                              f"placement group bundle reservation")}
        return {"error": "insufficient placement group resources", "retry": True}

    def _release_lease(self, lease_id: bytes):
        lease = self.leases.pop(lease_id, None)
        if lease is None:
            return
        key = self._lease_key_by_id.pop(lease_id, None)
        if key is not None:
            self._lease_requests.pop(key, None)
        worker_id, res, pg_id, bundle_index = lease
        if pg_id is not None:
            pg = self.pg_prepared.get(pg_id)
            if pg is not None and bundle_index in pg["free"]:
                pg["free"][bundle_index] = pg["free"][bundle_index] + res
        else:
            self.available = self.available + res
        w = self.workers.get(worker_id)
        if w is not None and w.state == W_LEASED:
            if w.tpu_chips:
                # visibility can't be re-narrowed in a live process; retire the
                # worker and return its chips to the pool
                w.lease_id = None
                self._kill_worker_proc(w, "TPU lease returned")
            else:
                w.state = W_IDLE
                w.lease_id = None
                w.reserved = False
                w.idle_since = time.monotonic()
                self.idle_by_job.setdefault(
                    (w.job_id, w.env_key), []).append(worker_id)
        self._try_schedule()

    async def rpc_return_lease(self, conn_id: int, payload: dict) -> dict:
        self._release_lease(payload["lease_id"])
        return {"ok": True}

    async def rpc_cancel_lease_request(self, conn_id: int, payload: dict) -> dict:
        """Release whatever grant `request_key` produced (or will produce):
        the caller lost its connection mid-request_lease and rerouted, so a
        grant under this key is unclaimable — without this it leaks the
        worker forever (reference: NormalTaskSubmitter cancels pending lease
        requests it abandons). Idempotent; unknown keys are a no-op."""
        key = payload.get("request_key")
        if key is not None:
            # tombstone first: a late/resent request_lease frame for this key
            # must be refused even if it has not arrived yet
            self._cancelled_lease_keys[key] = time.monotonic()
            while len(self._cancelled_lease_keys) > 4096:
                self._cancelled_lease_keys.popitem(last=False)
        task = self._lease_requests.get(key) if key is not None else None
        if task is None:
            return {"ok": True}

        def _release(t, key=key):
            reply = None if t.cancelled() or t.exception() else t.result()
            # pop the key directly: in the done-task race window _settle may
            # not have cached the lease_id↔key mapping yet, and relying on
            # _release_lease's map-based pop would leak both entries
            self._lease_requests.pop(key, None)
            if reply is not None and reply.get("granted"):
                self._lease_key_by_id.pop(reply["lease_id"], None)
                self._release_lease(reply["lease_id"])

        # Always via add_done_callback — even for a done task it schedules
        # through call_soon, which queues AFTER any pending _settle callback
        # from rpc_request_lease; running _release first would let _settle
        # re-cache a stale lease_id↔key entry for the released lease.
        task.add_done_callback(_release)
        return {"ok": True}

    async def rpc_kill_worker(self, conn_id: int, payload: dict) -> dict:
        w = self.workers.get(payload["worker_id"])
        if w is None:
            return {"ok": False}
        actor_id = w.actor_id
        w.actor_id = None  # killed on purpose: no death report
        if actor_id is not None:
            self._creating_actors.pop(actor_id, None)
        self._kill_worker_proc(w, payload.get("reason", "kill_worker"))
        if w.lease_id is not None:
            self._release_lease(w.lease_id)
        self._release_actor_resources(w)
        return {"ok": True, "actor_id": actor_id}

    def _release_actor_resources(self, w: WorkerHandle):
        if w.actor_resources is not None:
            if w.actor_pg is not None:
                pg_id, idx = w.actor_pg
                pg = self.pg_prepared.get(pg_id)
                if pg is not None and idx in pg["free"]:
                    pg["free"][idx] = pg["free"][idx] + w.actor_resources
                w.actor_pg = None
            else:
                self.available = self.available + w.actor_resources
            w.actor_resources = None
            self._try_schedule()

    # ------------------------------------------------------------------
    # actor creation (reference: gcs_actor_scheduler.cc:235-387 — here the
    # control store delegates the lease+push to the owning daemon)
    # ------------------------------------------------------------------

    async def rpc_create_actor(self, conn_id: int, payload: dict) -> dict:
        """Idempotent by actor id: the control store retries a timed-out
        create, and the retry must attach to (or observe) the original
        attempt rather than spawn a second worker for the same actor."""
        spec = TaskSpec.from_wire(payload["spec"])
        aid = spec.actor_id.binary()
        task = self._creating_actors.get(aid)
        if task is not None and task.done() and not task.cancelled() \
                and task.exception() is None:
            reply = task.result()
            if reply.get("ok"):
                w = self.workers.get(reply["worker_id"])
                if w is not None and w.state == W_ACTOR and w.proc.poll() is None:
                    return reply  # original create succeeded; worker alive
            task = None  # failed or worker gone: this is a fresh incarnation
        elif task is not None and (task.cancelled() or (
                task.done() and task.exception() is not None)):
            task = None
        if task is None:
            task = spawn(self._create_actor_inner(spec))
            self._creating_actors[aid] = task
        return await asyncio.shield(task)

    async def _create_actor_inner(self, spec: TaskSpec) -> dict:
        # PG-scheduled actors consume their bundle's reservation, not the
        # node's general pool (reference: bundle resource accounting in
        # placement_group_resource_manager.h — same rule as PG leases)
        actor_pg = None
        if spec.strategy.kind == pb.STRATEGY_PLACEMENT_GROUP:
            pg_id = bytes.fromhex(spec.strategy.placement_group_id)
            pg = self.pg_prepared.get(pg_id)
            if pg is None or pg["state"] != "committed":
                return {"ok": False,
                        "error": "placement group not committed on this node"}
            free = pg["free"]
            idx = spec.strategy.bundle_index
            indices = [idx] if idx >= 0 else sorted(free.keys())
            got = None
            for i in indices:
                if i in free and spec.resources.is_subset_of(free[i]):
                    free[i] = free[i] - spec.resources
                    got = i
                    break
            if got is None:
                # transient (bundle currently occupied) vs PERMANENT (the
                # request exceeds the bundle's total reservation — e.g. it
                # asks for a resource the bundle never held): a permanent
                # mismatch must fail the creation loudly, not retry forever
                if not self._pg_request_feasible(
                        spec.resources, pg, indices):
                    return {"ok": False, "permanent": True,
                            "error": (
                                f"resources {spec.resources.to_dict()} exceed "
                                f"the placement group bundle reservation")}
                return {"ok": False,
                        "error": "insufficient resources in placement group bundle"}
            actor_pg = (pg_id, got)
        else:
            if not spec.resources.is_subset_of(self.available):
                return {"ok": False, "error": "insufficient resources"}
            self.available = self.available - spec.resources

        def refund():
            if actor_pg is not None:
                rpg_id, ridx = actor_pg
                rpg = self.pg_prepared.get(rpg_id)
                if rpg is not None and ridx in rpg["free"]:
                    rpg["free"][ridx] = rpg["free"][ridx] + spec.resources
            else:
                self.available = self.available + spec.resources

        n_tpu = int(spec.resources.get("TPU"))
        from ray_tpu._private.runtime_env_mgr import env_isolation_key

        renv = spec.runtime_env or None
        try:
            w = await self._spawn_worker(
                spec.job_id.binary(),
                tpu_chips=self._alloc_chips(n_tpu) if n_tpu > 0 else None,
                env_key=env_isolation_key(renv),
                runtime_env=renv,
            )
        except Exception as e:  # noqa: BLE001
            refund()
            return {"ok": False, "error": f"worker spawn failed: {e}"}
        # dedicate this worker to the actor
        idle = self.idle_by_job.get((w.job_id, w.env_key), [])
        if w.worker_id.binary() in idle:
            idle.remove(w.worker_id.binary())
        w.state = W_ACTOR
        w.actor_id = spec.actor_id.binary()
        w.drain_coop = bool(spec.drain_cooperative)
        # Mark PG membership BEFORE the init push: a concurrent
        # rpc_return_bundles must see (and kill) this in-flight actor, or the
        # bundle's resources get credited back while the actor keeps running.
        # actor_resources stays None until success so the reap path doesn't
        # double-credit with refund() on an init crash.
        w.actor_pg = actor_pg
        if actor_pg is not None and self.pg_prepared.get(actor_pg[0]) is None:
            # the PG was returned while the worker was spawning
            self._kill_worker_proc(w, "placement group returned during spawn")
            return {"ok": False, "error": "placement group returned"}
        client = RpcClient(w.address, name="daemon->worker")
        try:
            await client.connect()
            reply = await client.call(
                "push_task", {"spec": spec.to_wire()},
                timeout=GLOBAL_CONFIG.get("actor_creation_timeout_s"),
            )
        except Exception as e:  # noqa: BLE001
            self._kill_worker_proc(w, "actor init push failed")
            refund()
            return {"ok": False, "error": f"actor init failed: {e}"}
        finally:
            await client.close()
        if reply.get("error"):
            self._kill_worker_proc(w, "actor __init__ raised")
            refund()
            return {"ok": False, "error": reply["error"].get("traceback", "init failed")}
        if w.state == W_DEAD or (
            actor_pg is not None and self.pg_prepared.get(actor_pg[0]) is None
        ):
            # killed (e.g. the PG was returned) between init and registration
            self._kill_worker_proc(w, "killed during actor init")
            return {"ok": False, "error": "worker killed during actor init"}
        w.actor_resources = spec.resources
        return {
            "ok": True,
            "worker_id": w.worker_id.binary(),
            "worker_address": w.address,
        }

    # ------------------------------------------------------------------
    # placement group bundles (reference: node_manager.proto:515-525)
    # ------------------------------------------------------------------

    async def rpc_prepare_bundles(self, conn_id: int, payload: dict) -> dict:
        pg_id = payload["pg_id"]
        if pg_id in self.pg_prepared:
            # retried prepare (dropped response): already reserved — a second
            # deduction would leak the bundle's resources permanently
            return {"ok": True}
        bundles = [pb.Bundle.from_wire(b) for b in payload["bundles"]]
        need = ResourceSet()
        for b in bundles:
            need = need + b.resources
        if not need.is_subset_of(self.available):
            return {"ok": False}
        self.available = self.available - need
        self.pg_prepared[pg_id] = {
            "state": "prepared",
            "bundles": {b.index: b.resources for b in bundles},
            "free": {b.index: b.resources for b in bundles},
        }
        return {"ok": True}

    async def rpc_commit_bundles(self, conn_id: int, payload: dict) -> dict:
        pg = self.pg_prepared.get(payload["pg_id"])
        if pg is None:
            return {"ok": False}
        pg["state"] = "committed"
        return {"ok": True}

    async def rpc_cancel_bundles(self, conn_id: int, payload: dict) -> dict:
        return await self.rpc_return_bundles(conn_id, payload)

    async def rpc_return_bundles(self, conn_id: int, payload: dict) -> dict:
        pg = self.pg_prepared.pop(payload["pg_id"], None)
        if pg is not None:
            # Workers still leased from these bundles run in resources that
            # are being handed back — kill them before crediting, or the node
            # oversubscribes (their _release_lease path credits nothing once
            # the pg entry is popped).
            for lease_id, (wid, _res, l_pg, _b) in list(self.leases.items()):
                if l_pg == payload["pg_id"]:
                    self.leases.pop(lease_id, None)
                    w = self.workers.get(wid)
                    if w is not None:
                        self._kill_worker_proc(w, "placement group returned")
            # actors living in returned bundles go down with them
            for w in list(self.workers.values()):
                if w.actor_pg is not None and w.actor_pg[0] == payload["pg_id"]:
                    w.actor_pg = None
                    w.actor_resources = None
                    self._kill_worker_proc(w, "placement group returned")
            freed = ResourceSet()
            for res in pg["bundles"].values():
                freed = freed + res
            self.available = self.available + freed
            self._try_schedule()
        return {"ok": True}

    # ------------------------------------------------------------------
    # object spilling (reference: raylet local_object_manager.h:45 —
    # SpillObjects under memory pressure, restore on demand)
    # ------------------------------------------------------------------

    async def _spill_loop(self):
        """Spill cold sealed objects to disk when the store passes the
        high-water mark, down to the low-water mark, so in-store eviction
        (which destroys data) rarely has to fire."""
        period = GLOBAL_CONFIG.get("object_spill_check_period_s")
        high = GLOBAL_CONFIG.get("object_spill_high_water")
        low = GLOBAL_CONFIG.get("object_spill_low_water")
        while not self._stopped:
            await asyncio.sleep(period)
            try:
                st = self.store.stats()
                if st["heap_size"] and st["bytes_in_use"] / st["heap_size"] > high:
                    target = int(st["heap_size"] * low)
                    await self._spill_down_to(target)
            except Exception:  # noqa: BLE001 — keep the loop alive
                logger.exception("spill loop iteration failed")

    async def _spill_down_to(self, target_bytes: int):
        if self._spill_lock is None:
            self._spill_lock = asyncio.Lock()
        async with self._spill_lock:
            spilled_bytes = 0
            for oid, size in self.store.list_evictable(max_n=512):
                st = self.store.stats()
                if st["bytes_in_use"] <= target_bytes:
                    break
                if await self._spill_one(oid):
                    spilled_bytes += size
            if spilled_bytes:
                logger.info(
                    "spilled %.1f MiB to %s (%d objects on disk)",
                    spilled_bytes / 2**20, self.spill_dir, len(self.spilled),
                )

    # ------------------------------------------------------------------
    # memory-pressure worker killing (reference:
    # src/ray/raylet/worker_killing_policy_group_by_owner.h — group tasks
    # by owner, kill the newest member of the largest group so retried
    # work loses the least progress and no single owner is starved)
    # ------------------------------------------------------------------

    def _memory_usage_fraction(self, psutil) -> float:
        limit = GLOBAL_CONFIG.get("memory_limit_bytes")
        if limit <= 0:
            return psutil.virtual_memory().percent / 100.0
        total = 0
        for w in self.workers.values():
            if w.state == W_DEAD:
                continue
            try:
                proc = psutil.Process(w.pid)
                procs = [proc, *proc.children(recursive=True)]
                for p in procs:
                    mi = p.memory_info()
                    # exclude shared pages: every worker maps the same shm
                    # object store, and counting those pages once PER worker
                    # would OOM-kill healthy readers of one big object
                    total += max(0, mi.rss - getattr(mi, "shared", 0))
            except psutil.Error:
                continue
        return total / limit

    def _pick_oom_victim(self) -> Optional[WorkerHandle]:
        """Group-by-owner, newest-first (reference policy): leased task
        workers grouped by job; the largest group loses its newest member —
        running tasks are where the memory is, so reaping them first is the
        only selection that actually relieves pressure (idle workers hold
        ~nothing and would shield a hog forever). Idle workers go only when
        no task runs; actors are never OOM-killed (restart churn)."""
        leased = [w for w in self.workers.values() if w.state == W_LEASED]
        if leased:
            groups: Dict[bytes, List[WorkerHandle]] = {}
            for w in leased:
                groups.setdefault(w.job_id, []).append(w)
            biggest = max(groups.values(), key=len)
            return max(biggest, key=lambda w: w.spawn_ts)
        idle = [w for w in self.workers.values() if w.state == W_IDLE]
        if idle:
            return max(idle, key=lambda w: w.spawn_ts)
        return None

    async def _memory_monitor_loop(self):
        period = GLOBAL_CONFIG.get("memory_monitor_interval_s")
        if period <= 0:
            return
        try:
            import psutil
        except ImportError:
            logger.warning("psutil unavailable; OOM monitor disabled")
            return
        while not self._stopped:
            await asyncio.sleep(period)
            try:
                frac = self._memory_usage_fraction(psutil)
                if frac < GLOBAL_CONFIG.get("memory_usage_threshold"):
                    continue
                victim = self._pick_oom_victim()
                if victim is None:
                    continue
                self._oom_kills += 1
                logger.warning(
                    "memory pressure %.0f%% >= threshold: OOM-killing "
                    "worker %s (state=%s job=%s, newest of largest owner "
                    "group; kill #%d)",
                    frac * 100, victim.worker_id.hex()[:8], victim.state,
                    victim.job_id.hex()[:8], self._oom_kills,
                )
                flight_recorder.record(
                    "oom", "kill_worker", worker=victim.worker_id.hex()[:8],
                    usage_frac=round(frac, 3), kill_no=self._oom_kills)
                lease_id = victim.lease_id
                self._kill_worker_proc(victim, "OOM: node memory pressure")
                if lease_id is not None:
                    # _forget_worker removed it from the reap loop's sight:
                    # credit the lease's resources back ourselves or the
                    # node's capacity shrinks with every OOM kill
                    self._release_lease(lease_id)
            except Exception:  # noqa: BLE001 — monitor must survive
                logger.exception("memory monitor iteration failed")

    def _node_stats(self) -> dict:
        """psutil snapshot shipped with every heartbeat (reference: the
        dashboard agent's reporter module samples cpu/mem/gpu per node)."""
        out: dict = {
            "workers": sum(1 for w in self.workers.values()
                           if w.state != W_DEAD),
            "workers_idle": sum(1 for w in self.workers.values()
                                if w.state == W_IDLE),
            "oom_kills": getattr(self, "_oom_kills", 0),
        }
        if self.store is not None:
            st = self.store.stats()
            out["store_bytes_in_use"] = st["bytes_in_use"]
            out["store_heap_size"] = st["heap_size"]
            out["store_num_objects"] = st["num_objects"]
        try:
            import psutil

            out["cpu_percent"] = psutil.cpu_percent(interval=None)
            vm = psutil.virtual_memory()
            out["mem_percent"] = vm.percent
            out["mem_total"] = vm.total
            rss = 0
            for w in self.workers.values():
                if w.state == W_DEAD:
                    continue
                try:
                    rss += psutil.Process(w.pid).memory_info().rss
                except psutil.Error:
                    continue
            out["workers_rss"] = rss
        except ImportError:
            pass
        return out

    async def rpc_list_workers(self, conn_id: int, payload: dict) -> dict:
        """Live workers on this node (the dashboard's per-node worker table;
        reference: dashboard reporter's worker listing)."""
        return {"workers": [
            {
                "worker_id": w.worker_id.hex(),
                "pid": w.pid,
                "state": w.state,
                "job_id": w.job_id.hex(),
                "env_key": w.env_key,
                "actor_id": w.actor_id.hex() if w.actor_id else "",
            }
            for w in self.workers.values() if w.state != W_DEAD
        ]}

    async def rpc_profile_worker(self, conn_id: int, payload: dict) -> dict:
        """On-demand stack sample of a live worker (reference: the
        dashboard's py-spy/memray profiling,
        dashboard/modules/reporter/profile_manager.py:60-102): SIGUSR1
        dumps all thread stacks, SIGUSR2 dumps asyncio task await-chains —
        both land in the worker's .err log, whose tail is returned."""
        wid = payload["worker_id"]
        if isinstance(wid, str):
            wid = bytes.fromhex(wid)
        w = self.workers.get(wid)
        if w is None or w.state == W_DEAD or w.proc.poll() is not None:
            return {"ok": False, "error": "worker not found or dead"}
        kind = payload.get("kind", "threads")
        sig = signal.SIGUSR2 if kind == "tasks" else signal.SIGUSR1
        log_path = os.path.join(
            self.session_dir, "logs",
            f"worker-{w.worker_id.hex()[:12]}.err")
        try:
            before = os.path.getsize(log_path)
        except OSError:
            before = 0
        try:
            os.kill(w.pid, sig)
        except ProcessLookupError:
            return {"ok": False, "error": "worker died"}
        await asyncio.sleep(0.4)  # dump is async-signal-driven
        try:
            raw = await asyncio.to_thread(
                _read_file_range, log_path, before, 256 * 1024)
            dump = raw.decode("utf-8", "replace")
        except OSError as e:
            return {"ok": False, "error": f"log unreadable: {e}"}
        return {"ok": True, "worker_id": w.worker_id.hex(), "pid": w.pid,
                "kind": kind, "dump": dump}

    async def rpc_spill_now(self, conn_id: int, payload: dict) -> dict:
        """Synchronous spill request from a worker whose create() hit
        ObjectStoreFullError (reference: raylet triggers spilling when a
        plasma allocation stalls)."""
        if not GLOBAL_CONFIG.get("object_spill_enabled"):
            # spilling disabled: the creator's backpressure loop waits for
            # consumers to free refs instead (no spill_dir even exists)
            return {"ok": False, "disabled": True}
        need = payload.get("need_bytes", 0)
        st = self.store.stats()
        low = GLOBAL_CONFIG.get("object_spill_low_water")
        target = min(
            int(st["heap_size"] * low),
            max(0, st["bytes_in_use"] - need),
        )
        await self._spill_down_to(target)
        return {"ok": True}

    @staticmethod
    def _write_file(path: str, view: memoryview):
        with open(path, "wb") as f:
            f.write(view)

    async def _spill_one(self, oid: ObjectID) -> bool:
        res = self.store.get(oid)  # pins
        if res is None:
            return False
        view, meta = res
        path = os.path.join(self.spill_dir, oid.hex())
        try:
            size = len(view)
            # thread: a multi-GiB write must not stall heartbeats/leases
            # (the pin keeps the view valid across the await)
            await asyncio.to_thread(self._write_file, path, view)
        finally:
            view.release()
            self.store.release(oid)
        if not self.store.delete(oid):
            # someone pinned it between our release and delete; keep it in
            # store, drop the file
            try:
                os.unlink(path)
            except OSError:
                pass
            return False
        self.spilled[oid.binary()] = (path, meta, size)
        return True

    async def _create_making_room(self, oid: ObjectID, size: int, meta: int):
        """store.create with one retry after spilling `size` bytes of cold
        objects (shared by restore and pull)."""
        try:
            return self.store.create(oid, size, metadata=meta)
        except ObjectStoreFullError:
            st = self.store.stats()
            await self._spill_down_to(max(0, st["bytes_in_use"] - size))
            return self.store.create(oid, size, metadata=meta)

    async def _restore_object(self, oid: ObjectID) -> bool:
        """Bring a spilled object back into the shm store (spilling other
        cold objects out if the store is full)."""
        rec = self.spilled.get(oid.binary())
        if rec is None:
            return self.store.contains(oid)
        path, meta, _size = rec
        if not self.store.contains(oid):
            def read_file():
                with open(path, "rb") as f:
                    return f.read()

            try:
                data = await asyncio.to_thread(read_file)
            except OSError:
                return False
            try:
                view = await self._create_making_room(oid, len(data), meta)
                view[:] = data
                view.release()
                self.store.seal(oid)
            except FileExistsError:
                pass
        self.spilled.pop(oid.binary(), None)
        try:
            os.unlink(path)
        except OSError:
            pass
        return True

    async def rpc_restore_object(self, conn_id: int, payload: dict) -> dict:
        oid = ObjectID(payload["object_id"])
        if self.store.contains(oid):
            return {"ok": True}
        if oid.binary() in self.spilled:
            return {"ok": await self._restore_object(oid)}
        return {"ok": False, "unknown": True}

    # ------------------------------------------------------------------
    # object transfer (reference: object_manager.h:137, pull_manager.h:52)
    # ------------------------------------------------------------------

    async def rpc_fetch_object_info(self, conn_id: int, payload: dict) -> dict:
        oid = ObjectID(payload["object_id"])
        if oid.binary() in self.spilled:
            await self._restore_object(oid)
        res = self.store.get(oid)
        if res is None:
            return {"found": False}
        view, meta = res
        size = len(view)
        view.release()
        self.store.release(oid)
        return {"found": True, "size": size, "metadata": meta}

    async def rpc_fetch_chunk(self, conn_id: int, payload: dict) -> dict:
        oid = ObjectID(payload["object_id"])
        if oid.binary() in self.spilled:
            await self._restore_object(oid)
        res = self.store.get(oid)
        if res is None:
            return {"found": False}
        view, meta = res
        try:
            off, ln = payload["offset"], payload["length"]
            return {"found": True, "data": bytes(view[off : off + ln])}
        finally:
            view.release()
            self.store.release(oid)

    # -- remote-client puts (reference: ray client server-side object puts;
    # a storeless driver ships bytes here instead of mmapping shm) --------

    async def rpc_create_object(self, conn_id: int, payload: dict) -> dict:
        oid = ObjectID(payload["object_id"])
        if self.store.contains(oid) or oid.binary() in self.spilled:
            return {"ok": True, "exists": True}
        if oid.binary() in self._inbound_creates:
            return {"ok": True, "exists": False}
        try:
            view = await self._create_making_room(
                oid, payload["size"], payload.get("meta", 0))
        except FileExistsError:
            return {"ok": True, "exists": True}
        except ObjectStoreFullError as e:
            return {"ok": False, "error": str(e)}
        self._inbound_creates[oid.binary()] = (view, time.monotonic())
        return {"ok": True, "exists": False}

    async def rpc_write_chunk(self, conn_id: int, payload: dict) -> dict:
        entry = self._inbound_creates.get(payload["object_id"])
        if entry is None:
            return {"ok": False, "error": "no in-progress create for object"}
        view, _ = entry
        off = payload["offset"]
        view[off:off + len(payload["data"])] = payload["data"]
        self._inbound_creates[payload["object_id"]] = (view, time.monotonic())
        return {"ok": True}

    async def rpc_seal_object(self, conn_id: int, payload: dict) -> dict:
        entry = self._inbound_creates.pop(payload["object_id"], None)
        if entry is None:
            return {"ok": False, "error": "no in-progress create for object"}
        view, _ = entry
        view.release()
        self.store.seal(ObjectID(payload["object_id"]))
        return {"ok": True}

    def _sweep_stale_inbound_creates(self, max_age_s: float = 60.0):
        """Abort remote-client puts abandoned mid-transfer: release the
        creator pin and delete the unsealed allocation (unsealed entries are
        invisible to eviction/spill, so a leak here is permanent)."""
        if not self._inbound_creates:
            return
        now = time.monotonic()
        for ob, (view, ts) in list(self._inbound_creates.items()):
            if now - ts <= max_age_s:
                continue
            self._inbound_creates.pop(ob, None)
            view.release()
            try:
                self.store.release(ObjectID(ob))
                self.store.delete(ObjectID(ob))
            except Exception:  # noqa: BLE001
                pass
            logger.warning("aborted stale inbound create %s",
                           ObjectID(ob).hex()[:12])

    async def rpc_pull_object(self, conn_id: int, payload: dict) -> dict:
        """Pull an object from a remote node into the local store."""
        oid = ObjectID(payload["object_id"])
        if self.store.contains(oid):
            return {"ok": True}
        if oid.binary() in self.spilled:
            # pulled previously, then spilled: restore from local disk
            return {"ok": await self._restore_object(oid)}
        if payload["from_address"] in self._dead_peer_addrs:
            return {"ok": False,
                    "error": "source node recorded dead by control store"}
        key = oid.binary()
        fut = self._pulls_inflight.get(key)
        if fut is None:
            fut = spawn(self._do_pull(oid, payload["from_address"]))
            self._pulls_inflight[key] = fut
        try:
            await fut
            return {"ok": True}
        except Exception as e:  # noqa: BLE001
            return {"ok": False, "error": str(e)}
        finally:
            self._pulls_inflight.pop(key, None)

    async def _do_pull(self, oid: ObjectID, from_address: str):
        client = self._peer_clients.get(from_address)
        if client is None:
            client = RpcClient(from_address, name="daemon->peer")
            await client.connect()
            self._peer_clients[from_address] = client
        delay = GLOBAL_CONFIG.get("pull_retry_initial_delay_s")
        max_delay = GLOBAL_CONFIG.get("pull_retry_max_delay_s")
        deadline = time.monotonic() + 60
        while True:
            info = await client.call("fetch_object_info", {"object_id": oid.binary()})
            if info.get("found"):
                break
            if time.monotonic() > deadline:
                raise TimeoutError(f"object {oid} never appeared on {from_address}")
            await asyncio.sleep(delay)
            delay = min(delay * 2, max_delay)
        size, meta = info["size"], info["metadata"]
        try:
            view = await self._create_making_room(oid, size, meta)
        except FileExistsError:
            return
        # Parallel chunk fetch (reference: push_manager chunking).
        from ray_tpu.runtime.transfer import fetch_chunks

        try:
            await fetch_chunks(
                client.call, oid.binary(), size, view,
                chunk_bytes=GLOBAL_CONFIG.get("object_chunk_bytes"),
            )
        except Exception:
            view.release()
            # the creator ref is only dropped by seal; release it first or
            # delete refuses (pinned) and the unsealed allocation leaks
            self.store.release(oid)
            self.store.delete(oid)
            raise
        view.release()
        self.store.seal(oid)

    async def rpc_free_objects(self, conn_id: int, payload: dict) -> dict:
        for ob in payload["object_ids"]:
            self.store.delete(ObjectID(ob))
            rec = self.spilled.pop(ob, None)
            if rec is not None:
                try:
                    os.unlink(rec[0])
                except OSError:
                    pass
        return {"ok": True}

    async def rpc_store_stats(self, conn_id: int, payload) -> dict:
        st = self.store.stats()
        st["spilled_objects"] = len(self.spilled)
        st["spilled_bytes"] = sum(r[2] for r in self.spilled.values())
        return st

    async def rpc_node_info(self, conn_id: int, payload) -> dict:
        return {
            "node_id": self.node_id.hex(),
            "address": self.address,
            "store_name": self.store_name,
            "available": self.available.to_wire(),
            "total": self.total_resources.to_wire(),
            "num_workers": len(self.workers),
            "num_pending_leases": len(self.pending),
        }

    async def rpc_ping(self, conn_id: int, payload) -> dict:
        """Liveness probe for worker fate-sharing watchdogs."""
        return {"ok": True}

    # -- chaos scenario hooks (testing only; reference: rpc_chaos.h is
    # env-driven — these add runtime aim-ability, since daemon/worker
    # addresses are only known after spawn) -----------------------------

    # ------------------------------------------------------------------
    # metrics pre-aggregation + flight recorder (observability plane)
    # ------------------------------------------------------------------

    async def rpc_report_metrics(self, conn_id: int, payload: dict) -> dict:
        """Per-node metric aggregation point: every worker's delta series
        merge into one node-level pending set (counters/histograms add,
        gauges replace), capped in cardinality — the control store sees one
        reporter per NODE, not one per worker (reference: the per-node
        metrics agent in dashboard/modules/reporter)."""
        from ray_tpu.util.metrics import merge_series

        series = payload.get("metrics") or []
        delta = bool(payload.get("delta"))
        seq = payload.get("seq")
        reporter = payload.get("worker_id", b"")
        if delta and seq is not None:
            last = self._metrics_last_seq.get(reporter)
            if last is not None and seq <= last:
                return {"ok": True, "dup": True}
            self._metrics_last_seq[reporter] = seq
            self._metrics_last_seq.move_to_end(reporter)
            while len(self._metrics_last_seq) > 4096:
                self._metrics_last_seq.popitem(last=False)
        cap = GLOBAL_CONFIG.get("metrics_node_series_max")
        admitted = []
        for s in series:
            try:
                key = (s["name"], tuple(sorted(s["tags"].items())))
            except (KeyError, TypeError, AttributeError):
                continue
            if key not in self._metrics_keys:
                if len(self._metrics_keys) >= cap:
                    self._metrics_dropped += 1
                    continue
                self._metrics_keys.add(key)
            admitted.append(s)
        merge_series(self._metrics_pending, admitted, delta)
        return {"ok": True, "dropped_total": self._metrics_dropped}

    async def _metrics_ship_loop(self):
        """Forward the node's pending metric deltas (plus this daemon's own
        registry and the cardinality-drop counter) to the control store."""
        from ray_tpu.util import metrics as metrics_mod

        period = GLOBAL_CONFIG.get("telemetry_flush_period_s")
        # eagerly registered at zero so the series exists on the scrape
        # before the first drop happens
        dropped_counter = metrics_mod.get_or_create_counter(
            "rt_metrics_series_dropped_total",
            "Metric series dropped by the node daemon's cardinality cap "
            "(metrics_node_series_max)")
        dropped_counter.inc(0)
        shipped_drops = 0
        # frozen outbound batch (exactly-once: same seq retried verbatim
        # until the store acks; the store dedups by (node, seq))
        batch: Optional[list] = None  # [seq, series]
        seq = 0
        while not self._stopped:
            await asyncio.sleep(period)
            try:
                if self._metrics_dropped > shipped_drops:
                    metrics_mod.get_or_create_counter(
                        "rt_metrics_series_dropped_total").inc(
                            self._metrics_dropped - shipped_drops)
                    shipped_drops = self._metrics_dropped
                if batch is None:
                    own = metrics_mod.take_delta()
                    pending, self._metrics_pending = (
                        self._metrics_pending, {})
                    series = list(pending.values()) + own
                    if series:
                        seq += 1
                        batch = [seq, series]
                # an idle interval still sends an empty keepalive: the
                # store's stale prune must not collect this node's
                # accumulated totals while it merely has nothing new
                payload = {"worker_id": self.node_id.binary(),
                           "delta": True,
                           "metrics": batch[1] if batch else [],
                           **({"seq": batch[0]} if batch else {})}
                try:
                    await self.control.call(
                        "report_metrics", payload, timeout=10)
                    batch = None
                except Exception:  # noqa: BLE001 — store blip: the frozen
                    # batch retries with the same seq next tick (new worker
                    # reports keep accumulating in _metrics_pending)
                    pass
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — telemetry must never kill
                logger.debug("metrics ship loop error", exc_info=True)

    async def rpc_dump_flight_recorder(self, conn_id: int, payload) -> dict:
        return flight_recorder.dump()

    async def rpc_collect_flight_recorders(self, conn_id: int,
                                           payload) -> dict:
        """This daemon's ring plus every live local worker's — the one-stop
        per-node pull the dashboard's /api/flight_recorder endpoint and the
        cluster-wide dump use."""
        out = {"daemon": flight_recorder.dump(), "workers": {}}
        for w in list(self.workers.values()):
            if w.state == W_DEAD or not w.address:
                continue
            try:
                client = RpcClient(w.address, name="daemon->worker-fr",
                                   retries=0)
                await client.connect()
                try:
                    out["workers"][w.worker_id.hex()] = await client.call(
                        "dump_flight_recorder", {}, timeout=5)
                finally:
                    await client.close()
            except Exception as e:  # noqa: BLE001 — wedged worker: skip it
                logger.debug("flight-recorder pull from worker %s skipped: %r",
                             w.worker_id.hex()[:12], e)
                continue
        return out

    async def rpc_chaos_set(self, conn_id: int, payload: dict) -> dict:
        """Apply chaos/testing config flags to THIS daemon process at
        runtime (e.g. partition it from one peer address)."""
        cfg = payload.get("config", {})
        GLOBAL_CONFIG.apply_system_config(cfg)
        chaos.reset()
        # a wave spec landing at runtime re-runs the seeded draw NOW, so a
        # test can aim a correlated reclaim at a fleet that is already
        # mid-workload (the start()-time draw only covers daemons born
        # after the spec was set)
        if cfg.get("testing_preempt_wave"):
            wave = chaos.preempt_wave(
                self.labels.get("spot") == "true"
                or self.labels.get("preemptible") == "true")
            if wave is not None:
                offset_s, deadline_s = wave
                self._tasks.append(
                    spawn(self._chaos_preempt(offset_s, deadline_s)))
        return {"ok": True, "role": chaos.role(), "pid": os.getpid()}

    async def rpc_chaos_kill(self, conn_id: int, payload: dict) -> dict:
        """Kill a chosen worker process (by id, or any one leased/idle
        worker), or this daemon itself — the process-kill fault type aimed
        at a specific live process."""
        if payload.get("die"):
            # reply first so the injector isn't stuck on a lost RPC; the
            # exit runs after the response flushes. Crash path = flight
            # recorder dump: the post-mortem artifact survives the process.
            flight_recorder.crash_dump("chaos_kill")
            asyncio.get_running_loop().call_later(0.05, os._exit, 137)
            return {"ok": True, "target": "daemon"}
        wid = payload.get("worker_id")
        victims = [w for w in self.workers.values() if w.state != W_DEAD
                   and (wid is None or w.worker_id.binary() == wid)
                   and (not payload.get("actor") or w.state == W_ACTOR)]
        if not victims:
            return {"ok": False, "error": "no matching live worker"}
        victim = victims[0]
        # simulate a CRASH, not an administrative kill: SIGKILL the process
        # and run the same observation path the reap loop takes, so actor
        # death / lease release / death records all fire exactly as they
        # would for a real unexpected exit
        try:
            os.killpg(os.getpgid(victim.proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        await self._on_worker_death(victim,
                                    reason="worker crashed (chaos process_kill)")
        return {"ok": True, "target": victim.worker_id.hex()}

    async def rpc_drain(self, conn_id: int, payload) -> dict:
        """Graceful drain (reference: DrainRaylet node_manager.proto:510)
        carrying `{reason, deadline_s}`. Routed through the control store so
        the cluster-wide record agrees — a locally-set flag alone would be
        reverted by the next heartbeat's authoritative state sync. With a
        deadline the drain is terminal: the daemon finishes running work,
        replicates its primary copies, and exits with an expected-
        termination death record."""
        payload = payload or {}
        reason = payload.get("reason") or pb.DRAIN_REASON_MANUAL
        deadline_s = float(payload.get("deadline_s") or 0.0)
        return await self._self_drain(reason, deadline_s)

    async def _self_drain(self, reason: str, deadline_s: float) -> dict:
        flight_recorder.record("drain", "start", reason=reason,
                               deadline_s=deadline_s)
        try:
            await self.control.call(
                "drain_node",
                {"node_id": self.node_id.binary(), "reason": reason,
                 "deadline_s": deadline_s},
                timeout=10,
            )
        except Exception as e:  # noqa: BLE001 — partitioned from the store
            # a preemption notice is real whether or not the control store
            # heard about it: gate leases and run the orchestration locally;
            # unregister_node (retried inside) records the death when the
            # partition heals
            logger.warning("drain_node RPC failed (%s); draining locally", e)
            self._draining = True
            self._drain_sync_ts = time.monotonic()
            if deadline_s and self._drain_task is None:
                self._drain_task = spawn(self._drain_and_exit(
                    reason, time.monotonic() + deadline_s))
            # keep trying to file the drain cluster-wide: owners only
            # reroute leases/retries away from this node once the store
            # publishes the DRAINING notice
            spawn(self._register_drain_late(reason, deadline_s))
            return {"ok": True, "local_only": True}
        info = NodeInfo.from_wire(self._node_info.to_wire())
        info.state = pb.NODE_DRAINING
        info.drain_reason = reason
        info.drain_deadline = time.time() + deadline_s if deadline_s else 0.0
        self._sync_drain_state(info)
        return {"ok": True}

    async def _register_drain_late(self, reason: str, deadline_s: float):
        """A locally-initiated drain whose drain_node RPC failed (store
        partitioned at notice time) retries the cluster-wide registration
        until it lands or the drain budget runs out — without it no
        DRAINING notice ever tells owners to reroute. The retry budget is
        independent of the drain semantics: a reversible drain
        (deadline_s == 0) must stay reversible, so the registration
        forwards the ORIGINAL deadline (remaining wall-clock time for a
        terminal drain, 0.0 unchanged for a reversible one) — never the
        retry-loop budget."""
        drain_deadline = (
            time.monotonic() + deadline_s if deadline_s else None)
        retry_until = time.monotonic() + max(deadline_s, 10.0)
        while time.monotonic() < retry_until and not self._stopped:
            await asyncio.sleep(1.0)
            if (drain_deadline is not None
                    and time.monotonic() >= drain_deadline):
                # the node is about to exit anyway; the expected-death
                # unregister tells the cluster the story
                return
            try:
                await self.control.call(
                    "drain_node",
                    {"node_id": self.node_id.binary(), "reason": reason,
                     "deadline_s": (
                         max(0.1, drain_deadline - time.monotonic())
                         if drain_deadline is not None else 0.0)},
                    timeout=5,
                )
                return
            except Exception:  # noqa: BLE001 — still partitioned
                continue

    # ------------------------------------------------------------------
    # terminal drain orchestration (reference: the raylet's drain handling
    # — stop granting, let running leases finish to the deadline, hand off
    # primary copies, then die an EXPECTED death)
    # ------------------------------------------------------------------

    def _make_preempt_watcher(self, deadline_s: Optional[float] = None,
                              transport=None):
        """One construction site for real and synthetic preemption notices
        so both take the identical proactive path: publish the TTL'd
        notice, keep re-publishing (failover-proof), self-drain only when
        the control plane misses the grace window."""
        from ray_tpu.tpu.preemption import PreemptionWatcher

        return PreemptionWatcher(
            on_notice=self._self_drain,
            transport=transport,
            drain_deadline_s=deadline_s,
            publish=self._publish_preempt_notice,
            drain_started=lambda: self._draining or self._drain_task is not None,
        )

    async def _publish_preempt_notice(self, deadline_s: float) -> None:
        """File/refresh this node's TTL'd preemption notice at the control
        store (PREEMPTING state; the autoscaler treats our committed load
        as demand NOW). Raises on failure so the watcher retries."""
        reply = await self.control.call(
            "report_preemption_notice",
            {"node_id": self.node_id.binary(), "deadline_s": deadline_s},
            timeout=5,
        )
        if not reply.get("ok"):
            raise RuntimeError(f"report_preemption_notice refused: {reply}")

    async def _chaos_preempt(self, delay_s: float, deadline_s: float):
        """Seeded `testing_preempt_notice`/`testing_preempt_wave` fault: a
        deterministic stand-in for the GCE maintenance event — the notice
        lands mid-workload and must produce a non-event, not a recovery
        storm. Routed through the watcher's fire path so proactive mode
        (publish + pre-provision + deferred drain) is exercised exactly as
        a real metadata notice would."""
        await asyncio.sleep(delay_s)
        logger.warning("synthetic preemption notice (chaos): %.1fs deadline",
                       deadline_s)
        self._preempt_watcher = self._make_preempt_watcher(
            deadline_s=deadline_s)
        await self._preempt_watcher._fire("synthetic preemption (chaos)")

    async def _drain_and_exit(self, reason: str, deadline: float):
        try:
            # the deadline is HARD (a preempted VM is killed at it): budget
            # the phases inside it instead of letting a long-running lease
            # starve the replication/report handoff that makes the drain
            # cheap. The final control calls are small — reserve a tail
            # slice; everything clamps to the overall deadline.
            budget = max(0.0, deadline - time.monotonic())
            lease_deadline = time.monotonic() + budget * 0.6
            report_deadline = min(deadline, time.monotonic() + 30.0)
            await self._wait_for_leases(lease_deadline)
            replicas = await self._replicate_primaries(
                max(time.monotonic(), deadline - min(5.0, budget * 0.1)))
            if replicas:
                try:
                    # deadline-retried: a control-store failover mid-drain
                    # must not lose the replica map (owners would fall back
                    # to reconstructing everything)
                    await self.control.call(
                        "report_drain_replicas",
                        {"node_id": self.node_id.binary(),
                         "replicas": replicas},
                        timeout=10,
                        deadline=max(report_deadline,
                                     time.monotonic() + 2.0),
                    )
                except Exception:  # noqa: BLE001 — store blip: replicas
                    # still exist, owners just reconstruct instead
                    logger.warning("report_drain_replicas failed",
                                   exc_info=True)
            try:
                await self.control.call(
                    "unregister_node",
                    {"node_id": self.node_id.binary(), "expected": True,
                     "reason": f"drained ({reason})"},
                    timeout=10,
                    deadline=max(min(deadline, time.monotonic() + 30.0),
                                 time.monotonic() + 2.0),
                )
            except Exception:  # noqa: BLE001 — health checker will record
                # an (unexpected) death instead; replicas still serve
                logger.warning("drain unregister_node failed", exc_info=True)
            logger.info("drain complete (%s): exiting", reason)
            flight_recorder.record("drain", "complete", reason=reason,
                                   replicas=len(replicas or {}))
        except asyncio.CancelledError:
            raise
        except Exception:  # noqa: BLE001 — never die silently mid-drain
            logger.exception("drain orchestration failed; exiting anyway")
            flight_recorder.crash_dump("drain_failed")
        finally:
            self._stopped = True
            if self._exit_cb is not None:
                self._exit_cb()

    async def _wait_for_leases(self, deadline: float):
        """Let running work finish: leases stop being granted the moment the
        drain notice lands, so the busy set only shrinks.

        ACTOR workers hold the node open too — but only those some
        protocol will actually remove: the control store migrates non-PG
        actors immediately, and a `drain_cooperative` actor's owner runs
        its own removal (the elastic train controller live-shrinks its
        gang and releases the doomed ranks, killing their workers).
        Exiting the moment no TASK lease runs would strand those
        protocols with a dead node mid-handoff; a node hosting only
        actors would get no warning at all. PG-pinned non-cooperative
        actors are NOT waited for — nothing removes them before node
        death, and idling on them would eat the replication window that
        keeps the drain zero-reconstruction."""
        while time.monotonic() < deadline:
            busy = [w for w in self.workers.values()
                    if w.state == W_LEASED
                    or (w.state == W_ACTOR
                        and (w.actor_pg is None or w.drain_coop))]
            if not busy and not self.leases:
                return
            await asyncio.sleep(0.05)
        leased = [w for w in self.workers.values() if w.state == W_LEASED]
        actors = [w for w in self.workers.values() if w.state == W_ACTOR]
        if leased or actors:
            logger.warning(
                "drain deadline reached with %d lease(s) and %d actor "
                "worker(s) still running; tasks retry elsewhere, actors "
                "die with the node", len(leased), len(actors))

    async def _replicate_primaries(self, deadline: float) -> dict:
        """Proactively copy store-resident (and spilled) objects to live
        peers so owners fail over to the replicas with ZERO lineage
        reconstructions (reference: the object manager's primary-copy
        handoff on drain). Returns {oid_hex: {"node_id", "daemon"}}."""
        peers = [
            info for hexid, info in self.peer_nodes.items()
            if info.state == pb.NODE_ALIVE and hexid != self.node_id.hex()
        ]
        if not peers or self.store is None:
            return {}
        cap = GLOBAL_CONFIG.get("drain_replicate_max_objects")
        oids = [oid for oid, _sz in self.store.list_evictable(max_n=cap)]
        seen = {o.binary() for o in oids}
        spill_extra = [ob for ob in list(self.spilled) if ob not in seen]
        oids.extend(ObjectID(ob) for ob in spill_extra)  # restored on fetch
        # the evictable listing is itself capped at `cap`: count candidates
        # from the store's total object count so objects past the listing
        # cap are not silently missing from the dropped tally
        total = (self.store.stats().get("num_objects", len(oids))
                 + len(spill_extra))
        if len(oids) > cap:
            oids = oids[:cap]
        dropped = total - len(oids)
        if dropped > 0:
            logger.warning(
                "drain: %d object(s) beyond the replicate cap will rely on "
                "lineage reconstruction", dropped)
        replicas: dict = {}

        async def replicate_one(i: int, oid: ObjectID):
            peer = peers[i % len(peers)]
            try:
                client = self._peer_clients.get(peer.address)
                if client is None:
                    client = RpcClient(peer.address, name="daemon->peer")
                    await client.connect()
                    self._peer_clients[peer.address] = client
                r = await client.call(
                    "pull_object",
                    {"object_id": oid.binary(), "from_address": self.address},
                    timeout=max(1.0, min(30.0, deadline - time.monotonic())),
                )
                if r.get("ok"):
                    replicas[oid.hex()] = {
                        "node_id": peer.node_id.hex(),
                        "daemon": peer.address,
                    }
            except Exception:  # noqa: BLE001 — this object reconstructs
                logger.debug("drain replication of %s failed",
                             oid.hex()[:12], exc_info=True)

        batch = 16
        for b0 in range(0, len(oids), batch):
            if time.monotonic() >= deadline:
                logger.warning(
                    "drain deadline reached mid-replication: %d object(s) "
                    "unreplicated will rely on lineage reconstruction",
                    len(oids) - b0)
                break
            await asyncio.gather(*[
                replicate_one(b0 + j, oid)
                for j, oid in enumerate(oids[b0:b0 + batch])
            ])
        if replicas:
            logger.info("drain: replicated %d/%d primary object(s) to %d "
                        "peer(s)", len(replicas), len(oids), len(peers))
        return replicas


async def run_daemon(args):
    daemon = NodeDaemon(
        control_address=args.control_address,
        resources=json.loads(args.resources) if args.resources else None,
        labels=json.loads(args.labels) if args.labels else None,
        session_dir=args.session_dir,
        store_name=args.store_name or None,
    )
    addr = await daemon.start(args.port)
    if args.ready_file:
        # rtlint: disable=R001 one-shot startup marker write before the daemon serves traffic
        with open(args.ready_file, "w") as f:
            json.dump(
                {
                    "address": addr,
                    "node_id": daemon.node_id.hex(),
                    "store_name": daemon.store_name,
                },
                f,
            )
    stop = asyncio.Event()
    # a completed terminal drain exits the daemon process cleanly (the
    # expected-termination record is already filed with the control store)
    daemon._exit_cb = stop.set

    def _term(*_):
        stop.set()

    asyncio.get_running_loop().add_signal_handler(signal.SIGTERM, _term)
    await stop.wait()
    await daemon.stop()


def main():
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--control-address", required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--resources", default="")
    parser.add_argument("--labels", default="")
    parser.add_argument("--session-dir", default="/tmp/ray_tpu_sessions")
    parser.add_argument("--store-name", default="")
    parser.add_argument("--ready-file", default=None)
    parser.add_argument("--config-json", default="")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args()
    logging.basicConfig(
        level=os.environ.get("RT_LOG_LEVEL", args.log_level),
        format="%(asctime)s %(levelname)s daemon %(message)s",
    )
    if args.config_json:
        GLOBAL_CONFIG.load_overrides(args.config_json)
    try:
        asyncio.run(run_daemon(args))
    except KeyboardInterrupt:
        pass
    except BaseException:
        # fatal daemon crash: leave the flight-recorder ring next to the
        # logs before propagating (the post-mortem artifact)
        from ray_tpu._private import flight_recorder as _fr

        _fr.crash_dump("daemon_fatal")
        raise


if __name__ == "__main__":
    main()
