"""Unified retry policy: capped exponential backoff with decorrelated
jitter and deadline propagation.

One policy shared by every retried control-plane operation — RPC calls
(`runtime.rpc.RpcClient`), object fetch/reconstruction (`_private.recovery`),
lease requests and task resubmission (`_private.core_worker`) — replacing the
ad-hoc `base * 2**attempt` sleeps that used to be re-derived per call site.
Capability parity with the reference's retryable client (reference:
src/ray/rpc/retryable_grpc_client.h — server_unavailable_timeout +
exponential backoff with jitter).

Jitter is DECORRELATED (AWS architecture-blog style): each delay is drawn
uniformly from [base, prev * 3], capped. Compared to full jitter it keeps a
rising floor (quick first retries) while still desynchronizing retry storms
from many clients hitting one recovering server.

Determinism: when the chaos harness is seeded (`testing_chaos_seed`), jitter
draws come from the per-process seeded chaos PRNG, so a failing schedule
replays exactly from the seed.
"""

from __future__ import annotations

import time
from typing import Optional


class DeadlineExceeded(TimeoutError):
    """The operation's total deadline expired between/through retries."""


class RetryPolicy:
    """Immutable backoff shape: start at `base_s`, cap at `max_s`, widen by
    `multiplier` per attempt (3.0 = decorrelated-jitter sweet spot)."""

    __slots__ = ("base_s", "max_s", "multiplier")

    def __init__(self, base_s: float = 0.2, max_s: float = 5.0,
                 multiplier: float = 3.0):
        if base_s <= 0 or multiplier < 1.0:
            raise ValueError(
                f"bad retry policy: base={base_s} mult={multiplier}")
        self.base_s = base_s
        # clamp rather than raise: these values flow from user config
        # (retry_base_s/retry_max_s) on EVERY rpc call — a cap below the
        # base must degrade to constant-delay retries, not brick the
        # control plane with a ValueError per call
        self.max_s = max(max_s, base_s)
        self.multiplier = multiplier

    def backoff(self, deadline: Optional[float] = None,
                rng=None) -> "Backoff":
        return Backoff(self, deadline=deadline, rng=rng)


DEFAULT_POLICY = RetryPolicy()


class Backoff:
    """Per-operation backoff state. `next_delay()` yields the next sleep;
    a `deadline` (time.monotonic() timestamp) propagates through: delays are
    clipped to the remaining budget and `expired()` flips once it's spent,
    so a caller-level timeout bounds the whole retry chain instead of each
    attempt independently."""

    __slots__ = ("policy", "deadline", "_rng", "_prev", "attempts")

    def __init__(self, policy: RetryPolicy = DEFAULT_POLICY, *,
                 deadline: Optional[float] = None, rng=None):
        self.policy = policy
        self.deadline = deadline
        self._rng = rng
        self._prev = policy.base_s
        self.attempts = 0

    def _random(self):
        if self._rng is None:
            # resolved lazily: the chaos seed may be applied after import
            from ray_tpu._private import chaos

            self._rng = chaos.rng()
        return self._rng

    def remaining(self) -> Optional[float]:
        """Seconds left until the deadline (None = unbounded)."""
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    def expired(self) -> bool:
        rem = self.remaining()
        return rem is not None and rem <= 0.0

    def clamp(self, timeout: Optional[float]) -> Optional[float]:
        """Per-attempt timeout bounded by the remaining total budget."""
        rem = self.remaining()
        if rem is None:
            return timeout
        if timeout is None:
            return rem
        return min(timeout, rem)

    def next_delay(self) -> float:
        """Next backoff delay. Raises DeadlineExceeded when the deadline
        leaves no room for another attempt."""
        p = self.policy
        lo = p.base_s
        hi = max(lo, min(p.max_s, self._prev * p.multiplier))
        delay = lo if hi <= lo else self._random().uniform(lo, hi)
        self._prev = delay
        self.attempts += 1
        rem = self.remaining()
        if rem is not None:
            if rem <= 0.0:
                raise DeadlineExceeded(
                    f"deadline exhausted after {self.attempts} attempt(s)")
            delay = min(delay, rem)
        return delay

    async def sleep(self):
        """Sleep the next backoff delay (asyncio)."""
        import asyncio

        await asyncio.sleep(self.next_delay())


def deadline_from_timeout(timeout: Optional[float]) -> Optional[float]:
    """Convert a relative timeout into an absolute monotonic deadline."""
    return None if timeout is None else time.monotonic() + timeout
