"""cgroup-v2 resource isolation of system vs worker processes.

Reference surface: src/ray/common/cgroup2/cgroup_manager.h (CgroupManager —
a node's processes split into a `system` cgroup holding the daemon/store
processes with a guaranteed memory reservation, and a `workers` cgroup whose
memory/cpu are bounded so runaway user code pressures ITSELF before it can
starve the control plane) and sysfs_cgroup_driver.h / fake_cgroup_driver.h
(the real sysfs driver + the in-memory fake every test uses).

Layout under the configured base cgroup:

    <base>/system      daemon, object store, control processes
                       memory.min = system_reserved_memory_bytes
    <base>/workers     every spawned worker process
                       memory.high/max = worker_memory_{high,max}_bytes
                       cpu.weight = worker_cpu_weight

Opt-in via the `cgroup_isolation_enabled` config flag; when the cgroup2
filesystem is absent or unwritable (containers without delegation — the
common dev case) the manager disables itself with one log line and the
daemon runs exactly as before.
"""

from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

CGROUP_ROOT = "/sys/fs/cgroup"


class CgroupDriver:
    """Filesystem operations of the cgroup2 hierarchy (reference:
    sysfs_cgroup_driver.h). Paths are relative to the cgroup2 root."""

    def available(self) -> bool:
        raise NotImplementedError

    def create(self, path: str) -> None:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def write(self, path: str, filename: str, value: str) -> None:
        raise NotImplementedError

    def read(self, path: str, filename: str) -> str:
        raise NotImplementedError

    def move_pid(self, path: str, pid: int) -> None:
        self.write(path, "cgroup.procs", str(pid))

    def pids(self, path: str) -> List[int]:
        raw = self.read(path, "cgroup.procs")
        return [int(x) for x in raw.split() if x.strip()]


class SysFsCgroupDriver(CgroupDriver):
    """The real /sys/fs/cgroup (v2) driver."""

    def __init__(self, root: str = CGROUP_ROOT):
        self.root = root

    def _abs(self, path: str) -> str:
        return os.path.join(self.root, path.lstrip("/"))

    def available(self) -> bool:
        # presence of the v2 controllers file is the gate; WRITABILITY is
        # probed by setup() itself (delegated subtrees may be writable even
        # when the root is not)
        return os.path.exists(os.path.join(self.root, "cgroup.controllers"))

    def create(self, path: str) -> None:
        os.makedirs(self._abs(path), exist_ok=True)

    def delete(self, path: str) -> None:
        try:
            os.rmdir(self._abs(path))
        except OSError:
            pass

    def write(self, path: str, filename: str, value: str) -> None:
        with open(os.path.join(self._abs(path), filename), "w") as f:
            f.write(value)

    def read(self, path: str, filename: str) -> str:
        with open(os.path.join(self._abs(path), filename)) as f:
            return f.read()


class FakeCgroupDriver(CgroupDriver):
    """In-memory cgroup tree for tests (reference: fake_cgroup_driver.h) —
    the manager's protocol is exercised without a writable cgroupfs."""

    def __init__(self):
        self.tree: Dict[str, Dict[str, str]] = {"": {}}
        self.deleted: List[str] = []

    def available(self) -> bool:
        return True

    def _norm(self, path: str) -> str:
        return path.strip("/")

    def create(self, path: str) -> None:
        path = self._norm(path)
        parts = path.split("/")
        for i in range(1, len(parts) + 1):
            self.tree.setdefault("/".join(parts[:i]), {})

    def delete(self, path: str) -> None:
        path = self._norm(path)
        self.tree.pop(path, None)
        self.deleted.append(path)

    def write(self, path: str, filename: str, value: str) -> None:
        path = self._norm(path)
        if path not in self.tree:
            raise FileNotFoundError(path)
        if filename == "cgroup.procs":
            # cgroup2 semantics: writing a pid MOVES it from its old group
            for files in self.tree.values():
                pids = files.get("cgroup.procs", "").split()
                if value in pids:
                    pids.remove(value)
                    files["cgroup.procs"] = "\n".join(pids)
            existing = self.tree[path].get("cgroup.procs", "")
            self.tree[path]["cgroup.procs"] = (
                existing + "\n" + value if existing else value)
            return
        self.tree[path][filename] = value

    def read(self, path: str, filename: str) -> str:
        path = self._norm(path)
        return self.tree.get(path, {}).get(filename, "")


class CgroupManager:
    """Builds and owns the node's system/workers split (reference:
    cgroup_manager.h). All failures degrade to no-isolation."""

    def __init__(self, base: str, driver: Optional[CgroupDriver] = None, *,
                 system_reserved_memory_bytes: int = 0,
                 worker_memory_high_bytes: int = 0,
                 worker_memory_max_bytes: int = 0,
                 worker_cpu_weight: int = 0):
        self.base = base.strip("/")
        self.driver = driver or SysFsCgroupDriver()
        self.system_reserved = system_reserved_memory_bytes
        self.worker_high = worker_memory_high_bytes
        self.worker_max = worker_memory_max_bytes
        self.worker_cpu_weight = worker_cpu_weight
        self.enabled = False

    @property
    def system_path(self) -> str:
        return f"{self.base}/system"

    @property
    def workers_path(self) -> str:
        return f"{self.base}/workers"

    def setup(self, system_pids: Optional[List[int]] = None) -> bool:
        """Create the hierarchy, enable controllers, apply limits, and move
        the system processes in. Returns whether isolation is active."""
        d = self.driver
        if not d.available():
            logger.info("cgroup2 unavailable/unwritable: worker isolation "
                        "disabled")
            return False
        try:
            d.create(self.base)
            # leaf groups must exist BEFORE subtree_control (no-internal-
            # process rule: the base keeps no processes of its own)
            d.create(self.system_path)
            d.create(self.workers_path)
            # controllers must be delegated down EVERY ancestor or the
            # base's cgroup.controllers will lack memory/cpu and the leaf
            # limits below fail (cgroup2 top-down delegation)
            parts = self.base.split("/")
            for depth in range(len(parts)):
                ancestor = "/".join(parts[:depth]) if depth else ""
                try:
                    d.write(ancestor, "cgroup.subtree_control",
                            "+memory +cpu")
                except OSError:
                    if depth == 0:
                        # root-level delegation is often pre-configured (or
                        # forbidden in delegated subtrees): tolerate, the
                        # base-level write below is the authoritative check
                        continue
                    raise
            d.write(self.base, "cgroup.subtree_control", "+memory +cpu")
            if self.system_reserved > 0:
                d.write(self.system_path, "memory.min",
                        str(self.system_reserved))
            if self.worker_high > 0:
                d.write(self.workers_path, "memory.high",
                        str(self.worker_high))
            if self.worker_max > 0:
                d.write(self.workers_path, "memory.max",
                        str(self.worker_max))
            if self.worker_cpu_weight > 0:
                d.write(self.workers_path, "cpu.weight",
                        str(self.worker_cpu_weight))
            for pid in system_pids or []:
                d.move_pid(self.system_path, pid)
        except OSError as e:
            logger.warning("cgroup setup failed (%s): worker isolation "
                           "disabled", e)
            return False
        self.enabled = True
        return True

    def add_system_process(self, pid: int) -> None:
        if not self.enabled:
            return
        try:
            self.driver.move_pid(self.system_path, pid)
        except OSError:  # noqa: PERF203 — raced process exit
            pass

    def add_worker(self, pid: int) -> None:
        """Confine one spawned worker process."""
        if not self.enabled:
            return
        try:
            self.driver.move_pid(self.workers_path, pid)
        except OSError:
            pass  # worker died before confinement; fate-sharing reaps it

    def cleanup(self) -> None:
        """Tear the hierarchy down (processes still inside fall back to the
        parent cgroup when the dirs are removed after they exit)."""
        if not self.enabled:
            return
        for path in (self.workers_path, self.system_path, self.base):
            self.driver.delete(path)
        self.enabled = False


def manager_from_config(session_name: str) -> Optional[CgroupManager]:
    """Build the daemon's manager when the config flag is on; None keeps
    the daemon entirely cgroup-free."""
    from ray_tpu._private.config import GLOBAL_CONFIG

    if not GLOBAL_CONFIG.get("cgroup_isolation_enabled"):
        return None
    return CgroupManager(
        f"ray_tpu/{session_name}",
        system_reserved_memory_bytes=GLOBAL_CONFIG.get(
            "cgroup_system_reserved_memory_bytes"),
        worker_memory_high_bytes=GLOBAL_CONFIG.get(
            "cgroup_worker_memory_high_bytes"),
        worker_memory_max_bytes=GLOBAL_CONFIG.get(
            "cgroup_worker_memory_max_bytes"),
        worker_cpu_weight=GLOBAL_CONFIG.get("cgroup_worker_cpu_weight"),
    )


__all__ = ["CgroupDriver", "CgroupManager", "FakeCgroupDriver",
           "SysFsCgroupDriver", "manager_from_config"]
