"""Runtime environments: per-task/actor pip venvs, working_dir, py_modules,
env_vars.

Reference surface: python/ray/runtime_env/ + _private/runtime_env/
(ARCHITECTURE.md — env built once per URI, cached, applied before user
code; working_dir/py_modules are content-addressed zips; pip/conda envs
built by the per-node agent and workers exec'd inside them; worker_pool.h
keys cached workers by runtime-env hash). Here the packages travel through
the control store's KV (the reference's GCS-backed package store for small
URIs), and the per-node cache lives in the session dir.

ISOLATING env fields (`pip`, `working_dir`) contribute to an env key that
workers are POOLED BY: the daemon grants such leases only to workers
spawned for that exact env — a pip env's worker runs on the venv's own
interpreter, and working_dir is chdir'd once at worker startup. Two tasks
with conflicting deps or different working dirs therefore run concurrently
on one node in different worker processes, and the old process-wide-chdir-
on-pooled-workers hazard is gone. Venvs are content-addressed
(venvs/<hash> under the session cache) and built once per node with
--system-site-packages, so the framework and its baked deps resolve while
installed packages shadow them.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import subprocess
import sys
import zipfile
from typing import Any, Dict, List, Optional

KV_NS = "runtime_env"

# fields whose values require a dedicated worker process
_ISOLATING_FIELDS = ("pip", "uv", "working_dir_uri", "plugin_iso")

# every field the framework itself understands: user-facing inputs plus the
# wire-form fields prepare_runtime_env generates (a prepared env may be
# passed back in, e.g. an actor restart re-preparing its creation spec)
_BUILTIN_FIELDS = frozenset({
    "pip", "uv", "working_dir", "py_modules", "env_vars",
    "working_dir_uri", "py_module_uris", "env_key", "namespace",
    "detached", "plugin_iso", "_plugins",
})


# ---------------------------------------------------------------------------
# plugin architecture (reference: _private/runtime_env/ARCHITECTURE.md —
# each env field is a plugin with a driver-side prepare step and an
# executor-side setup step; third parties register their own)
# ---------------------------------------------------------------------------


class RuntimeEnvPlugin:
    """One runtime-env field. `name` is the runtime_env dict key the plugin
    owns. prepare() runs on the DRIVER at submission (return a wire-safe
    value — upload payloads through cw, never ship local paths); setup()
    runs in the EXECUTOR before user code. `isolating=True` pools workers
    by this field's value (a dedicated process per distinct value)."""

    name: str = ""
    isolating: bool = False

    async def prepare(self, value, runtime_env: Dict[str, Any], cw):
        return value

    async def setup(self, value, runtime_env: Dict[str, Any], cw):
        return None


_PLUGINS: Dict[str, RuntimeEnvPlugin] = {}


def register_runtime_env_plugin(plugin: RuntimeEnvPlugin):
    """Register a custom env field (reference: the plugin registry the
    runtime-env agent loads). Built-in fields cannot be overridden."""
    builtin = {"pip", "uv", "working_dir", "py_modules", "env_vars",
               "working_dir_uri", "py_module_uris", "env_key", "namespace"}
    if not plugin.name or plugin.name in builtin:
        raise ValueError(f"invalid plugin name {plugin.name!r}")
    _PLUGINS[plugin.name] = plugin


def unregister_runtime_env_plugin(name: str):
    _PLUGINS.pop(name, None)


def env_isolation_key(runtime_env: Optional[Dict[str, Any]]) -> str:
    """Stable key of the wire env's isolating fields; '' = any pooled
    worker may run it (reference: worker_pool.h runtime_env_hash)."""
    if not runtime_env:
        return ""
    parts = {k: runtime_env[k] for k in _ISOLATING_FIELDS if runtime_env.get(k)}
    if not parts:
        return ""
    for f in ("pip", "uv"):
        if f in parts:
            # order-insensitive, matching ensure_venv's cache key — reordered
            # but identical specs must share one worker pool
            parts[f] = sorted(parts[f])
    blob = json.dumps(parts, sort_keys=True).encode()
    return hashlib.blake2b(blob, digest_size=8).hexdigest()


def ensure_venv(pip_spec: List[str], cache_root: str,
                backend: str = "pip") -> str:
    """Build (or reuse) a content-addressed venv for `pip_spec`; returns its
    python executable. Concurrent builders serialize on an flock; the venv
    is built IN PLACE (crashed half-builds are tolerated by `venv` and
    rebuilt) and readers are gated by the .rt_ready marker written after a
    successful pip install. --no-build-isolation keeps local-path installs
    offline (the build env would otherwise fetch setuptools from the
    index). backend="uv" resolves/installs with uv (reference: the uv
    runtime-env plugin) — same cache layout, much faster cold builds."""
    key = hashlib.blake2b(
        json.dumps([backend, *sorted(pip_spec)]).encode(),
        digest_size=8).hexdigest()
    venv_dir = os.path.join(cache_root, "venvs", key)
    python = os.path.join(venv_dir, "bin", "python")
    ready = os.path.join(venv_dir, ".rt_ready")
    if os.path.exists(ready):
        return python
    os.makedirs(os.path.dirname(venv_dir), exist_ok=True)
    import fcntl

    with open(venv_dir + ".lock", "w") as lock:
        fcntl.flock(lock, fcntl.LOCK_EX)
        try:
            if os.path.exists(ready):  # built while we waited
                return python
            # build in place under the lock (venv tolerates an existing dir
            # from a crashed attempt); the .rt_ready marker gates readers
            subprocess.run(
                [sys.executable, "-m", "venv", venv_dir],
                check=True, capture_output=True, timeout=300,
            )
            # NOT --system-site-packages: a venv created from inside a venv
            # (this image's /opt/venv) chains to the BASE interpreter's
            # site-packages, losing jax/setuptools/the framework's deps.
            # Instead a .pth appends the PARENT interpreter's site dirs
            # after the venv's own — installs shadow, everything resolves.
            import glob as _glob

            vsite = _glob.glob(os.path.join(
                venv_dir, "lib", "python*", "site-packages"))[0]
            parent_sites = [
                p for p in sys.path
                if p.endswith("site-packages") and os.path.isdir(p)
            ]
            with open(os.path.join(vsite, "_rt_parent.pth"), "w") as f:
                f.write("\n".join(parent_sites) + "\n")
            if backend == "uv":
                import shutil as _sh

                uv = _sh.which("uv")
                if uv is None:
                    raise RuntimeError(
                        "runtime_env 'uv' requested but no uv binary on "
                        "this node")
                cmd = [uv, "pip", "install", "--python", python,
                       "--no-build-isolation", "--quiet", *pip_spec]
            else:
                cmd = [python, "-m", "pip", "install",
                       "--no-build-isolation", "--quiet",
                       "--retries", "1", "--timeout", "10", *pip_spec]
            r = subprocess.run(
                cmd, capture_output=True, timeout=600, text=True,
            )
            if r.returncode != 0:
                raise RuntimeError(
                    f"{backend} install {pip_spec} failed:\n"
                    f"{r.stderr[-2000:]}")
            with open(ready, "w") as f:
                f.write("ok")
            return python
        finally:
            fcntl.flock(lock, fcntl.LOCK_UN)


def _zip_dir_bytes(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in sorted(os.walk(path)):
            for f in sorted(files):
                full = os.path.join(root, f)
                # fixed timestamp: the URI must be a pure function of
                # CONTENT, or every mtime touch defeats package dedup
                info = zipfile.ZipInfo(os.path.relpath(full, path),
                                       date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                with open(full, "rb") as fh:
                    zf.writestr(info, fh.read())
    return buf.getvalue()


def _dir_signature(path: str) -> tuple:
    """Cheap change detector for the driver-side upload cache."""
    sig = []
    for root, _dirs, files in sorted(os.walk(path)):
        for f in sorted(files):
            full = os.path.join(root, f)
            st = os.stat(full)
            sig.append((os.path.relpath(full, path), st.st_size,
                        st.st_mtime_ns))
    return tuple(sig)


# driver-side memo: (abspath, dir signature) -> uploaded uri — without it
# every task submission re-zips and re-ships the whole directory
_UPLOAD_CACHE: Dict[str, tuple] = {}

# executor-side record of which py_module version is live per module name
_APPLIED_MODULES: Dict[str, str] = {}
_REMOTE_WD_CACHE: Dict[str, str] = {}


async def prepare_runtime_env(runtime_env: Optional[Dict[str, Any]],
                              cw) -> Optional[Dict[str, Any]]:
    """Driver side: upload local dirs as content-addressed zips; return the
    wire form ({..._uri} instead of local paths). Unknown fields — matching
    neither a builtin nor a registered plugin — fail the submission here
    with a clear error instead of being silently dropped (a typo'd 'pipp'
    must not no-op; reference: the runtime-env plugin manager rejects
    unknown fields the same way)."""
    if not runtime_env:
        return runtime_env
    unknown = [k for k in runtime_env
               if k not in _BUILTIN_FIELDS and k not in _PLUGINS]
    if unknown:
        known = sorted(k for k in _BUILTIN_FIELDS if not k.startswith("_"))
        raise ValueError(
            f"unknown runtime_env field(s) {sorted(unknown)!r}: each field "
            f"must be a builtin ({', '.join(known)}) or a registered "
            "runtime-env plugin (register_runtime_env_plugin)")
    out = dict(runtime_env)

    async def upload(path: str) -> str:
        path = os.path.abspath(path)
        sig = _dir_signature(path)
        cached = _UPLOAD_CACHE.get(path)
        if cached is not None and cached[0] == sig:
            return cached[1]
        blob = _zip_dir_bytes(path)
        uri = "pkg_" + hashlib.blake2b(blob, digest_size=16).hexdigest()
        await cw.control.call("kv_put", {
            "ns": KV_NS, "key": uri.encode(), "value": blob,
            "overwrite": False,
        })
        _UPLOAD_CACHE[path] = (sig, uri)
        return uri

    wd = out.pop("working_dir", None)
    if wd:
        if "://" in wd:
            # remote package source (gs://, s3://, memory://...): stage it
            # locally through the storage plane once per URI (cached), then
            # upload as usual; the staging dir is removed after upload
            # (reference: remote working_dir URIs in runtime_env packaging)
            cached = _REMOTE_WD_CACHE.get(wd)
            if cached is not None:
                out["working_dir_uri"] = cached
            else:
                import shutil
                import tempfile

                from ray_tpu.train._storage import get_storage

                staged = tempfile.mkdtemp(prefix="rt_wd_")
                try:
                    get_storage(wd).download_dir(wd, staged)
                    uri = await upload(staged)
                finally:
                    shutil.rmtree(staged, ignore_errors=True)
                _REMOTE_WD_CACHE[wd] = uri
                out["working_dir_uri"] = uri
        else:
            if not os.path.isdir(wd):
                raise ValueError(f"working_dir {wd!r} is not a directory")
            out["working_dir_uri"] = await upload(wd)
    mods = out.pop("py_modules", None)
    if mods:
        uris: List[str] = []
        for m in mods:
            if not os.path.isdir(m):
                raise ValueError(f"py_modules entry {m!r} is not a directory")
            uris.append(await upload(m) + ":" + os.path.basename(m.rstrip("/")))
        out["py_module_uris"] = uris
    for field in ("pip", "uv"):
        spec = out.get(field)
        if spec is None:
            continue
        if not isinstance(spec, (list, tuple)) or not all(
                isinstance(p, str) for p in spec):
            raise ValueError(f"runtime_env[{field!r}] must be a list of "
                             "requirement strings / local paths")
        # entries that LOOK like paths resolve against the DRIVER's cwd;
        # make them absolute so the daemon-side installer sees the same
        # files. Bare names stay requirement strings even if a same-named
        # file happens to exist in the cwd.
        def looks_like_path(p: str) -> bool:
            return p.startswith((".", "/", "~")) or os.sep in p

        out[field] = [
            os.path.abspath(os.path.expanduser(p))
            if looks_like_path(p) and os.path.exists(os.path.expanduser(p))
            else p
            for p in spec
        ]
    if out.get("pip") and out.get("uv"):
        raise ValueError("runtime_env takes 'pip' OR 'uv', not both")
    # registered custom plugins transform their fields to wire form; the
    # plugin OBJECT ships by value so executor processes (where nothing
    # registered it) can run its setup hook
    for name, plugin in _PLUGINS.items():
        if name in out:
            import cloudpickle

            out[name] = await plugin.prepare(out[name], out, cw)
            out.setdefault("_plugins", {})[name] = cloudpickle.dumps(plugin)
            if plugin.isolating:
                # isolating plugin values join the env key via a dedicated
                # wire field — daemons/workers recompute the key WITHOUT
                # knowing the plugin, so the value must be JSON-compatible
                out.setdefault("plugin_iso", {})[name] = out[name]
    out["env_key"] = env_isolation_key(out)
    return out


async def _fetch_extract(uri: str, cw, cache_root: str) -> str:
    dest = os.path.join(cache_root, uri)
    if os.path.isdir(dest):
        return dest
    reply = await cw.control.call("kv_get", {"ns": KV_NS, "key": uri.encode()})
    blob = reply.get("value")
    if blob is None:
        raise RuntimeError(f"runtime env package {uri} missing from KV")
    # per-process tmp dir: multiple pooled workers on a node can race the
    # same uncached URI, and a shared tmp path lets one process publish a
    # half-extracted tree out from under another
    tmp = f"{dest}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    zipfile.ZipFile(io.BytesIO(blob)).extractall(tmp)
    try:
        os.replace(tmp, dest)  # atomic publish; loser's replace fails
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


async def setup_runtime_env(runtime_env: Optional[Dict[str, Any]], cw,
                            dedicated: bool = False):
    """Executor side: apply env before user code runs (reference: the
    runtime-env agent builds the env, the worker execs inside it).
    `dedicated` = this process serves only this env (actor workers; task
    workers are instead spawned with RT_ENV_KEY by the daemon)."""
    if not runtime_env:
        return
    env_vars = runtime_env.get("env_vars") or {}
    if env_vars:
        os.environ.update(env_vars)
    for name, blob in (runtime_env.get("_plugins") or {}).items():
        plugin = _PLUGINS.get(name)
        if plugin is None:
            import cloudpickle

            # Trust note: the plugin object ships BY VALUE from the driver
            # and is unpickled+executed here during worker bootstrap. That
            # matches the trust model of task shipping (drivers already run
            # arbitrary code on workers via cloudpickled functions), but it
            # does widen what runs before any user task starts.
            plugin = cloudpickle.loads(blob)
        await plugin.setup(runtime_env.get(name), runtime_env, cw)
    cache_root = os.path.join(
        os.environ.get("RT_SESSION_DIR", "/tmp"), "runtime_env_cache")
    os.makedirs(cache_root, exist_ok=True)
    for entry in runtime_env.get("py_module_uris") or []:
        uri, _, modname = entry.partition(":")
        pkg_dir = await _fetch_extract(uri, cw, cache_root)
        # the zip holds the module's CONTENTS; expose it under its name
        named = os.path.join(cache_root, f"{uri}_as")
        target = os.path.join(named, modname)
        if not os.path.isdir(target):
            os.makedirs(named, exist_ok=True)
            try:
                os.symlink(pkg_dir, target)
            except FileExistsError:
                pass
        if named not in sys.path:
            sys.path.insert(0, named)
        # pooled worker previously imported an OLDER version of this module:
        # sys.modules would shadow the new path, silently serving stale code
        prev_uri = _APPLIED_MODULES.get(modname)
        if prev_uri is not None and prev_uri != uri:
            for loaded in [m for m in sys.modules
                           if m == modname or m.startswith(modname + ".")]:
                del sys.modules[loaded]
        _APPLIED_MODULES[modname] = uri
    wd_uri = runtime_env.get("working_dir_uri")
    if wd_uri:
        wd = await _fetch_extract(wd_uri, cw, cache_root)
        if wd not in sys.path:
            sys.path.insert(0, wd)
        # chdir only on a worker DEDICATED to this env (spawned with the
        # matching key, already chdir'd at startup — this is then a no-op
        # after a crash-restart). On a shared worker a process-wide chdir
        # would race concurrent tasks; sys.path covers imports instead.
        if dedicated or (
                os.environ.get("RT_ENV_KEY", "")
                == runtime_env.get("env_key", "")):
            os.chdir(wd)
