"""Runtime environments: per-task/actor working_dir, py_modules, env_vars.

Reference surface: python/ray/runtime_env/ + _private/runtime_env/
(ARCHITECTURE.md — env built once per URI, cached, applied before user
code; working_dir/py_modules are content-addressed zips). Here the packages
travel through the control store's KV (the reference's GCS-backed package
store for small URIs), and the per-node cache lives in the session dir.

Deviation noted: the reference starts a FRESH worker per runtime-env hash
(worker pool keyed by env). Here env_vars/py_modules apply per task on
pooled workers; `working_dir` performs a process-wide chdir, so it is
applied for actors (dedicated workers) and for tasks each time one runs —
two tasks with different working_dirs sharing a pooled worker see the
latest chdir between (not during) executions.
"""

from __future__ import annotations

import hashlib
import io
import os
import sys
import zipfile
from typing import Any, Dict, List, Optional

KV_NS = "runtime_env"


def _zip_dir_bytes(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in sorted(os.walk(path)):
            for f in sorted(files):
                full = os.path.join(root, f)
                # fixed timestamp: the URI must be a pure function of
                # CONTENT, or every mtime touch defeats package dedup
                info = zipfile.ZipInfo(os.path.relpath(full, path),
                                       date_time=(1980, 1, 1, 0, 0, 0))
                info.compress_type = zipfile.ZIP_DEFLATED
                with open(full, "rb") as fh:
                    zf.writestr(info, fh.read())
    return buf.getvalue()


def _dir_signature(path: str) -> tuple:
    """Cheap change detector for the driver-side upload cache."""
    sig = []
    for root, _dirs, files in sorted(os.walk(path)):
        for f in sorted(files):
            full = os.path.join(root, f)
            st = os.stat(full)
            sig.append((os.path.relpath(full, path), st.st_size,
                        st.st_mtime_ns))
    return tuple(sig)


# driver-side memo: (abspath, dir signature) -> uploaded uri — without it
# every task submission re-zips and re-ships the whole directory
_UPLOAD_CACHE: Dict[str, tuple] = {}

# executor-side record of which py_module version is live per module name
_APPLIED_MODULES: Dict[str, str] = {}


async def prepare_runtime_env(runtime_env: Optional[Dict[str, Any]],
                              cw) -> Optional[Dict[str, Any]]:
    """Driver side: upload local dirs as content-addressed zips; return the
    wire form ({..._uri} instead of local paths)."""
    if not runtime_env:
        return runtime_env
    out = dict(runtime_env)

    async def upload(path: str) -> str:
        path = os.path.abspath(path)
        sig = _dir_signature(path)
        cached = _UPLOAD_CACHE.get(path)
        if cached is not None and cached[0] == sig:
            return cached[1]
        blob = _zip_dir_bytes(path)
        uri = "pkg_" + hashlib.blake2b(blob, digest_size=16).hexdigest()
        await cw.control.call("kv_put", {
            "ns": KV_NS, "key": uri.encode(), "value": blob,
            "overwrite": False,
        })
        _UPLOAD_CACHE[path] = (sig, uri)
        return uri

    wd = out.pop("working_dir", None)
    if wd:
        if not os.path.isdir(wd):
            raise ValueError(f"working_dir {wd!r} is not a directory")
        out["working_dir_uri"] = await upload(wd)
    mods = out.pop("py_modules", None)
    if mods:
        uris: List[str] = []
        for m in mods:
            if not os.path.isdir(m):
                raise ValueError(f"py_modules entry {m!r} is not a directory")
            uris.append(await upload(m) + ":" + os.path.basename(m.rstrip("/")))
        out["py_module_uris"] = uris
    return out


async def _fetch_extract(uri: str, cw, cache_root: str) -> str:
    dest = os.path.join(cache_root, uri)
    if os.path.isdir(dest):
        return dest
    reply = await cw.control.call("kv_get", {"ns": KV_NS, "key": uri.encode()})
    blob = reply.get("value")
    if blob is None:
        raise RuntimeError(f"runtime env package {uri} missing from KV")
    # per-process tmp dir: multiple pooled workers on a node can race the
    # same uncached URI, and a shared tmp path lets one process publish a
    # half-extracted tree out from under another
    tmp = f"{dest}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    zipfile.ZipFile(io.BytesIO(blob)).extractall(tmp)
    try:
        os.replace(tmp, dest)  # atomic publish; loser's replace fails
    except OSError:
        import shutil

        shutil.rmtree(tmp, ignore_errors=True)
    return dest


async def setup_runtime_env(runtime_env: Optional[Dict[str, Any]], cw):
    """Executor side: apply env before user code runs (reference: the
    runtime-env agent builds the env, the worker execs inside it)."""
    if not runtime_env:
        return
    env_vars = runtime_env.get("env_vars") or {}
    if env_vars:
        os.environ.update(env_vars)
    cache_root = os.path.join(
        os.environ.get("RT_SESSION_DIR", "/tmp"), "runtime_env_cache")
    os.makedirs(cache_root, exist_ok=True)
    for entry in runtime_env.get("py_module_uris") or []:
        uri, _, modname = entry.partition(":")
        pkg_dir = await _fetch_extract(uri, cw, cache_root)
        # the zip holds the module's CONTENTS; expose it under its name
        named = os.path.join(cache_root, f"{uri}_as")
        target = os.path.join(named, modname)
        if not os.path.isdir(target):
            os.makedirs(named, exist_ok=True)
            try:
                os.symlink(pkg_dir, target)
            except FileExistsError:
                pass
        if named not in sys.path:
            sys.path.insert(0, named)
        # pooled worker previously imported an OLDER version of this module:
        # sys.modules would shadow the new path, silently serving stale code
        prev_uri = _APPLIED_MODULES.get(modname)
        if prev_uri is not None and prev_uri != uri:
            for loaded in [m for m in sys.modules
                           if m == modname or m.startswith(modname + ".")]:
                del sys.modules[loaded]
        _APPLIED_MODULES[modname] = uri
    wd_uri = runtime_env.get("working_dir_uri")
    if wd_uri:
        wd = await _fetch_extract(wd_uri, cw, cache_root)
        os.chdir(wd)
        if wd not in sys.path:
            sys.path.insert(0, wd)
