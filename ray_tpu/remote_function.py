"""@ray_tpu.remote functions.

Capability parity with the reference's remote function surface (reference:
python/ray/remote_function.py:347 RemoteFunction._remote and the options
system of python/ray/_private/ray_option_utils.py): `.remote()` exports the
function once through the control-store KV and submits tasks; `.options()`
returns a shallow override copy.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, Optional

import cloudpickle

from ray_tpu._private.core_worker import get_core_worker
from ray_tpu._private.protocol import (
    STRATEGY_NODE_AFFINITY,
    STRATEGY_PLACEMENT_GROUP,
    STRATEGY_SPREAD,
    SchedulingStrategy,
)

_VALID_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "num_returns", "max_retries",
    "retry_exceptions", "scheduling_strategy", "name", "label_selector",
    "placement_group", "placement_group_bundle_index", "runtime_env",
    "_generator_backpressure_num_objects",
}


def build_strategy(opts: Dict[str, Any]) -> SchedulingStrategy:
    strategy = opts.get("scheduling_strategy")
    if isinstance(strategy, SchedulingStrategy):
        s = strategy
    elif strategy == "SPREAD":
        s = SchedulingStrategy(kind=STRATEGY_SPREAD)
    elif isinstance(strategy, str) and strategy.startswith("node:"):
        s = SchedulingStrategy(kind=STRATEGY_NODE_AFFINITY, node_id=strategy[5:])
    else:
        s = SchedulingStrategy()
    pg = opts.get("placement_group")
    if pg is not None:
        pg_id = pg.id.hex() if hasattr(pg, "id") else str(pg)
        s = SchedulingStrategy(
            kind=STRATEGY_PLACEMENT_GROUP,
            placement_group_id=pg_id,
            bundle_index=opts.get("placement_group_bundle_index", -1),
        )
    if opts.get("label_selector"):
        s.label_selector = dict(opts["label_selector"])
    return s


def build_resources(opts: Dict[str, Any], default_cpu: float = 1.0) -> Dict[str, float]:
    res = dict(opts.get("resources") or {})
    if opts.get("num_tpus"):
        res["TPU"] = float(opts["num_tpus"])
    if "num_cpus" in opts and opts["num_cpus"] is not None:
        res["CPU"] = float(opts["num_cpus"])
    elif "CPU" not in res and not (res and opts.get("placement_group")):
        # the implicit 1-CPU scheduling default does NOT apply to a
        # placement-group request that already names custom resources
        # (including one expressed via num_tpus — TPU is folded in above
        # so it counts): the PG bundle is the resource envelope, and
        # silently adding CPU to a bundle that never reserved any makes
        # the request permanently unplaceable (it used to retry forever,
        # invisibly)
        res["CPU"] = default_cpu
    return res


def _value_digest(value) -> bytes:
    """Stable bytes for hashing a captured value into a function key."""
    try:
        return cloudpickle.dumps(value)
    except Exception:  # noqa: BLE001 — unpicklable capture: fall back
        return repr(value).encode()


class RemoteFunction:
    def __init__(self, fn, options: Optional[Dict[str, Any]] = None):
        self._fn = fn
        self._options = dict(options or {})
        for k in self._options:
            if k not in _VALID_OPTIONS:
                raise ValueError(f"invalid @remote option {k!r}")
        code = getattr(fn, "__code__", None)
        h = hashlib.blake2b(digest_size=8)
        h.update(fn.__module__.encode() if fn.__module__ else b"")
        h.update(fn.__qualname__.encode())
        if code is not None:
            h.update(code.co_code)
        # closure cells and defaults are part of the function's behavior:
        # two closures over the same code but different captured values must
        # not collide on one exported definition (the export is cached by
        # key cluster-wide)
        for cell in getattr(fn, "__closure__", None) or ():
            h.update(_value_digest(cell.cell_contents))
        for default in getattr(fn, "__defaults__", None) or ():
            h.update(_value_digest(default))
        self._function_key = f"{fn.__qualname__}:{h.hexdigest()}"
        self._exported = False

    @property
    def _function_name(self) -> str:
        return self._fn.__qualname__

    def options(self, **overrides) -> "RemoteFunction":
        merged = {**self._options, **overrides}
        clone = RemoteFunction.__new__(RemoteFunction)
        clone._fn = self._fn
        clone._options = merged
        for k in overrides:
            if k not in _VALID_OPTIONS:
                raise ValueError(f"invalid options() key {k!r}")
        clone._function_key = self._function_key
        clone._exported = self._exported
        return clone

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of executing (reference:
        remote_function bind — the ray.dag authoring surface)."""
        from ray_tpu.dag import FunctionNode

        return FunctionNode(self, args, kwargs)

    def remote(self, *args, **kwargs):
        cw = get_core_worker()
        cached = self.__dict__.get("_submit_cache")
        if cached is None:
            cached = self._build_submit_cache()
        streaming, num_returns, call_opts = cached

        # Non-blocking from every calling context (reference: .remote() never
        # waits on the data plane): args serialize on this thread so
        # serialization errors raise at the call site; the lease/push
        # pipeline continues on the loop.
        result = cw.submit_task_fast(
            self._fn, self._function_key, args, kwargs, **call_opts
        )
        if streaming or num_returns == 1:
            return result[0] if not streaming else result
        return result

    def _build_submit_cache(self):
        """Options are constant per RemoteFunction — resolve them (and the
        ResourceSet / strategy / lease key) once, not on every .remote()."""
        from ray_tpu._private.core_worker import compute_lease_key
        from ray_tpu._private.protocol import NUM_RETURNS_STREAMING, ResourceSet

        opts = self._options
        num_returns = opts.get("num_returns", 1)
        streaming = num_returns == "streaming"
        resources = ResourceSet(build_resources(opts))
        strategy = build_strategy(opts)
        call_opts = dict(
            num_returns=NUM_RETURNS_STREAMING if streaming else num_returns,
            resources=resources,
            strategy=strategy,
            max_retries=opts.get("max_retries"),
            name=self._function_name,
            runtime_env=opts.get("runtime_env"),
            stream_backpressure=opts.get("_generator_backpressure_num_objects", -1),
            lease_key=compute_lease_key(resources, strategy),
        )
        cached = (streaming, num_returns, call_opts)
        self._submit_cache = cached
        return cached

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function {self._function_name} cannot be called directly; "
            f"use .remote()"
        )
