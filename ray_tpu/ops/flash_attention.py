"""Flash attention forward kernel in Pallas (TPU).

Blockwise online-softmax attention that never materializes the (s, s) score
matrix: for each query block the kernel streams key/value blocks through VMEM,
keeping fp32 running max/denominator/accumulator in registers. Causal blocks
after the diagonal are skipped entirely (work ∝ s²/2). On non-TPU backends
(CPU tests) it transparently falls back to a fused XLA implementation.

Backward currently recomputes attention under `jax.custom_vjp` with the XLA
path — functional everywhere, with the memory win applying to inference and
the forward pass. (A full Pallas backward kernel is the known next step.)

Reference gap: the reference has no attention kernels at all (delegated to
vLLM/torch — SURVEY §2b); pallas_guide.md is the kernel playbook used here.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_INTERPRET = False  # set True to debug kernels on CPU interpreter


def _xla_attention(q, k, v, causal: bool):
    b, sq, h, hd = q.shape
    kvh = k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, -1e30)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v).astype(q.dtype)


def _flash_fwd_tpu(q, k, v, causal: bool, block_q: int, block_k: int):
    """q: (b, s, h, hd) bf16/f32; returns same. Requires s % block_q == 0."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, s, h, hd = q.shape
    kvh = k.shape[2]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)
    num_q_blocks = s // block_q

    # layout: (b*h, s, hd) programs over (bh, q_block)
    qt = q.transpose(0, 2, 1, 3).reshape(b * h, s, hd)
    kt = k.transpose(0, 2, 1, 3).reshape(b * kvh, s, hd)
    vt = v.transpose(0, 2, 1, 3).reshape(b * kvh, s, hd)

    def kernel(q_ref, k_ref, v_ref, o_ref):
        qi = pl.program_id(1)
        qb = q_ref[0].astype(jnp.float32) * scale          # (block_q, hd)
        # dynamic bound: causal → only K blocks up to (and including) the
        # diagonal; ceiling division so a partial diagonal block is processed
        # when block_q < block_k (masking handles the overhang)
        num_kb = (
            pl.cdiv(qi * block_q + block_q, block_k) if causal
            else s // block_k
        )
        n_steps = jnp.asarray(num_kb, jnp.int32)

        def body(j, carry):
            o, m, l = carry
            kb = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
            vb = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
            logits = jax.lax.dot_general(
                qb, kb, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )                                               # (block_q, block_k)
            if causal:
                q_pos = qi * block_q + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 0)
                k_pos = j * block_k + lax.broadcasted_iota(
                    jnp.int32, (block_q, block_k), 1)
                logits = jnp.where(q_pos >= k_pos, logits, -1e30)
            block_max = jnp.max(logits, axis=-1, keepdims=True)  # (bq, 1)
            new_m = jnp.maximum(m, block_max)
            corr = jnp.exp(m - new_m)
            p = jnp.exp(logits - new_m)
            new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
            new_o = o * corr + jax.lax.dot_general(
                p, vb, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            return new_o, new_m, new_l

        o0 = jnp.zeros((block_q, hd), jnp.float32)
        m0 = jnp.full((block_q, 1), -1e30, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        o, m, l = lax.fori_loop(0, n_steps, body, (o0, m0, l0))
        o_ref[0] = (o / l).astype(o_ref.dtype)

    grid = (b * h, num_q_blocks)
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b * h, s, hd), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, hd), lambda bh, qi: (bh, qi, 0)),
            # GQA: several q heads share one kv head — index map folds bh
            pl.BlockSpec((1, s, hd), lambda bh, qi: (bh // rep, 0, 0)),
            pl.BlockSpec((1, s, hd), lambda bh, qi: (bh // rep, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, hd), lambda bh, qi: (bh, qi, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(qt.size + kt.size + vt.size) * qt.dtype.itemsize,
            transcendentals=b * h * s * s,
        ),
        interpret=_INTERPRET,
    )(qt, kt, vt)
    return out.reshape(b, h, s, hd).transpose(0, 2, 1, 3)


def _supported_on_tpu(q, k, block_q, block_k):
    b, s, h, hd = q.shape
    return (
        jax.default_backend() == "tpu"
        and s % block_q == 0
        and s % block_k == 0
        and hd % 128 == 0
        and h % k.shape[2] == 0
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash(q, k, v, causal, block_q, block_k):
    if _supported_on_tpu(q, k, block_q, block_k):
        return _flash_fwd_tpu(q, k, v, causal, block_q, block_k)
    return _xla_attention(q, k, v, causal)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    return _flash(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v = res
    _, vjp = jax.vjp(lambda q, k, v: _xla_attention(q, k, v, causal), q, k, v)
    return vjp(g)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 256, block_k: int = 256):
    """Public entry. q/k/v: (batch, seq, heads, head_dim); GQA supported."""
    s = q.shape[1]
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    return _flash(q, k, v, causal, block_q, block_k)
