"""Flash attention (forward + backward) in Pallas for TPU.

Blockwise online-softmax attention that never materializes the (s, s) score
matrix in either direction:

* forward: for each query block the kernel streams key/value blocks through
  VMEM, keeping fp32 running max/denominator/accumulator in registers, and
  writes out the per-row logsumexp for the backward pass. Causal blocks after
  the diagonal are skipped (work ∝ s²/2).
* backward: two kernels (FlashAttention-2 style). `dq` iterates key blocks for
  each query block; `dk/dv` iterates query blocks for each key block. Both
  recompute p = exp(qkᵀ·scale − lse) from the saved logsumexp — no (s, s)
  residual is ever stored, which is what lets the surrounding model train
  without global rematerialization.

Layout is (batch, heads, seq, head_dim) end-to-end ("bhsd"): head_dim rides
the 128-wide lane dimension and no transposes are introduced around the
kernel. A (batch, seq, heads, head_dim) wrapper is kept for callers that use
the attention-standard layout. GQA is handled in the BlockSpec index maps
(query heads sharing a kv head read the same k/v block).

On non-TPU backends (CPU tests) everything transparently falls back to a
fused XLA implementation with identical semantics.

Reference gap: the reference has no attention kernels at all (delegated to
vLLM/torch — SURVEY §2b); pallas_guide.md is the kernel playbook used here.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_INTERPRET = False  # set True to debug kernels on CPU interpreter

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# XLA fallback (CPU tests / unsupported shapes)
# ---------------------------------------------------------------------------


def _xla_attention_bhsd(q, k, v, causal: bool):
    """q: (b, h, s, hd); k/v: (b, kvh, s, hd) → (b, h, s, hd)."""
    b, h, sq, hd = q.shape
    kvh = k.shape[1]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, causal, scale,
                block_q, block_k, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    qb = q_ref[0, 0].astype(jnp.float32) * scale           # (block_q, hd)
    hd = qb.shape[-1]

    num_kb = (
        pl.cdiv(qi * block_q + block_q, block_k) if causal
        else seq_len // block_k
    )

    def body(j, carry):
        o, m, l = carry
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        block_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, block_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        new_o = o * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_o, new_m, new_l

    o0 = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = lax.fori_loop(0, jnp.asarray(num_kb, jnp.int32), body,
                            (o0, m0, l0))
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


def _flash_fwd_tpu(q, k, v, causal, block_q, block_k):
    """q: (b, h, s, hd); k/v: (b, kvh, s, hd). Returns (o, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, hd = q.shape
    kvh = k.shape[1]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)
    grid = (b, h, s // block_q)

    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, seq_len=s)

    o, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, hd), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=int(b * h * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, *,
               causal, scale, block_q, block_k, seq_len):
    from jax.experimental import pallas as pl

    qi = pl.program_id(2)
    qb = q_ref[0, 0].astype(jnp.float32)                    # (block_q, hd)
    dob = do_ref[0, 0].astype(jnp.float32)                  # (block_q, hd)
    lse = lse_ref[0, 0]                                     # (block_q, 1)
    delta = delta_ref[0, 0]                                 # (block_q, 1)
    hd = qb.shape[-1]

    num_kb = (
        pl.cdiv(qi * block_q + block_q, block_k) if causal
        else seq_len // block_k
    )

    def body(j, dq):
        kb = k_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        vb = v_ref[0, 0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (block_q, block_k)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, jnp.asarray(num_kb, jnp.int32), body,
                       jnp.zeros((block_q, hd), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, causal, scale, block_q, block_k, seq_len):
    from jax.experimental import pallas as pl

    ki = pl.program_id(2)
    kb = k_ref[0, 0].astype(jnp.float32)                    # (block_k, hd)
    vb = v_ref[0, 0].astype(jnp.float32)                    # (block_k, hd)
    hd = kb.shape[-1]

    num_qb = seq_len // block_q
    # causal: only query blocks at/after this key block contribute
    start_qb = (ki * block_k) // block_q if causal else 0

    def body(i, carry):
        dk, dv = carry
        qb = q_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        dob = do_ref[0, 0, pl.ds(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.ds(i * block_q, block_q), :]
        delta = delta_ref[0, 0, pl.ds(i * block_q, block_q), :]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (block_q, block_k)
        # dv += pᵀ @ dO
        dv = dv + lax.dot_general(p, dob, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dk += dsᵀ @ q
        dk = dk + lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, hd), jnp.float32)
    dk, dv = lax.fori_loop(jnp.asarray(start_qb, jnp.int32),
                           jnp.asarray(num_qb, jnp.int32), body, (z, z))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd_tpu(q, k, v, o, lse, g, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, hd = q.shape
    kvh = k.shape[1]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)

    # delta[i] = Σ_d dO[i,d]·O[i,d] — cheap rowwise reduce, fused by XLA
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)

    dq_kernel = functools.partial(
        _dq_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, seq_len=s)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, s, hd), q.dtype),
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(3 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size * 3) * q.dtype.itemsize,
            transcendentals=int(b * h * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v, g, lse, delta)

    # dk/dv per *query* head (grid over h), reduced over the GQA group after.
    dkv_kernel = functools.partial(
        _dkv_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, seq_len=s)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, hd), jnp.float32),
        ),
        grid=(b, h, s // block_k),
        in_specs=[
            pl.BlockSpec((1, 1, s, hd), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda bi, hi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda bi, hi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, s, hd), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, s, 1), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_k, hd), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, block_k, hd), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size * 4) * q.dtype.itemsize,
            transcendentals=int(b * h * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v, g, lse, delta)

    if rep != 1:
        dk = dk.reshape(b, kvh, rep, s, hd).sum(axis=2)
        dv = dv.reshape(b, kvh, rep, s, hd).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# custom-vjp wiring (bhsd core)
# ---------------------------------------------------------------------------


def _supported_on_tpu(q, k, block_q, block_k):
    b, h, s, hd = q.shape
    return (
        jax.default_backend() == "tpu"
        and s % block_q == 0
        and s % block_k == 0
        and block_k % block_q == 0  # causal start-block math in dkv
        and hd % 128 == 0
        and h % k.shape[1] == 0
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_bhsd(q, k, v, causal, block_q, block_k):
    if _supported_on_tpu(q, k, block_q, block_k):
        return _flash_fwd_tpu(q, k, v, causal, block_q, block_k)[0]
    return _xla_attention_bhsd(q, k, v, causal)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k):
    if _supported_on_tpu(q, k, block_q, block_k):
        o, lse = _flash_fwd_tpu(q, k, v, causal, block_q, block_k)
        return o, (q, k, v, o, lse)
    return _xla_attention_bhsd(q, k, v, causal), (q, k, v, None, None)


def _flash_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    if o is not None:
        return _flash_bwd_tpu(q, k, v, o, lse, g, causal, block_q, block_k)
    _, vjp = jax.vjp(
        lambda q, k, v: _xla_attention_bhsd(q, k, v, causal), q, k, v)
    return vjp(g)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------


def flash_attention_bhsd(q, k, v, causal: bool = True,
                         block_q: int = 512, block_k: int = 512):
    """q: (batch, heads, seq, head_dim); k/v: (batch, kv_heads, seq, head_dim).

    The TPU-native layout: head_dim on the lane dimension, no transposes.
    """
    s = q.shape[2]
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if block_k % block_q != 0:
        block_q = block_k = min(block_q, block_k)
    return _flash_bhsd(q, k, v, causal, block_q, block_k)


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 512, block_k: int = 512):
    """Layout-standard entry. q/k/v: (batch, seq, heads, head_dim)."""
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    out = flash_attention_bhsd(q, k, v, causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3)
