"""Flash attention (forward + backward) in Pallas for TPU.

Blockwise online-softmax attention that never materializes the (s, s) score
matrix in either direction:

* forward: for each query block the kernel streams key/value blocks through
  VMEM, keeping fp32 running max/denominator/accumulator in registers, and
  writes out the per-row logsumexp for the backward pass. Causal blocks after
  the diagonal are skipped (work ∝ s²/2).
* backward: two kernels (FlashAttention-2 style). `dq` iterates key blocks for
  each query block; `dk/dv` iterates query blocks for each key block. Both
  recompute p = exp(qkᵀ·scale − lse) from the saved logsumexp — no (s, s)
  residual is ever stored, which is what lets the surrounding model train
  without global rematerialization.

Layout is (batch, heads, seq, head_dim) end-to-end ("bhsd"): head_dim rides
the 128-wide lane dimension and no transposes are introduced around the
kernel. A (batch, seq, heads, head_dim) wrapper is kept for callers that use
the attention-standard layout. GQA is handled in the BlockSpec index maps
(query heads sharing a kv head read the same k/v block).

On non-TPU backends (CPU tests) everything transparently falls back to a
fused XLA implementation with identical semantics.

Reference gap: the reference has no attention kernels at all (delegated to
vLLM/torch — SURVEY §2b); pallas_guide.md is the kernel playbook used here.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

_INTERPRET = False  # set True to debug kernels on CPU interpreter

NEG_INF = -1e30


def _compiler_params_cls(pltpu):
    # jax >= 0.8 spells it CompilerParams; the 0.4.x era TPUCompilerParams
    return getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams


# ---------------------------------------------------------------------------
# XLA fallback (CPU tests / unsupported shapes)
# ---------------------------------------------------------------------------


def _xla_attention_bhsd(q, k, v, causal: bool):
    """q: (b, h, s, hd); k/v: (b, kvh, s, hd) → (b, h, s, hd)."""
    b, h, sq, hd = q.shape
    kvh = k.shape[1]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[2]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, NEG_INF)
    p = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(q.dtype)


# ---------------------------------------------------------------------------
# forward kernel
# ---------------------------------------------------------------------------


def _kv_streamer(stream, block_k, bi, kh, k_src, v_src, scratch):
    """Returns (warmup, prefetch, load) for the per-iteration K/V tiles.

    stream=False: k_src/v_src are whole-s VMEM refs — direct slices, the
    BlockSpec auto-pipeline overlaps the HBM traffic (fastest; fits scoped
    VMEM through s=8192). stream=True: k_src/v_src stay in HBM and tiles
    move through double-buffered VMEM scratch — O(block) VMEM at any
    seq_len (whole-s refs overflow scoped VMEM at 16k+)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if not stream:
        def load(j, _slot):
            kb = k_src[0, 0, pl.ds(j * block_k, block_k), :]
            vb = v_src[0, 0, pl.ds(j * block_k, block_k), :]
            return kb.astype(jnp.float32), vb.astype(jnp.float32)

        return (lambda: None), (lambda j, limit: None), load

    k_buf, v_buf, k_sem, v_sem = scratch

    def dma(buf, hbm, sem, slot, j):
        return pltpu.make_async_copy(
            hbm.at[bi, kh, pl.ds(j * block_k, block_k), :],
            buf.at[slot], sem.at[slot])

    def warmup():
        dma(k_buf, k_src, k_sem, 0, 0).start()
        dma(v_buf, v_src, v_sem, 0, 0).start()

    def prefetch(j, limit):
        @pl.when(j + 1 < limit)
        def _():
            nxt = jax.lax.rem(j + 1, 2)
            dma(k_buf, k_src, k_sem, nxt, j + 1).start()
            dma(v_buf, v_src, v_sem, nxt, j + 1).start()

    def load(j, slot):
        dma(k_buf, k_src, k_sem, slot, j).wait()
        dma(v_buf, v_src, v_sem, slot, j).wait()
        return k_buf[slot].astype(jnp.float32), v_buf[slot].astype(jnp.float32)

    return warmup, prefetch, load


def _fwd_kernel(q_ref, k_src, v_src, o_ref, lse_ref, *scratch, causal,
                scale, block_q, block_k, seq_len, rep, stream):
    """Online-softmax forward for one (batch, head, q-block)."""
    from jax.experimental import pallas as pl

    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    qb = q_ref[0, 0].astype(jnp.float32) * scale           # (block_q, hd)
    hd = qb.shape[-1]

    num_kb = (
        pl.cdiv(qi * block_q + block_q, block_k) if causal
        else seq_len // block_k
    )
    warmup, prefetch, load = _kv_streamer(
        stream, block_k, bi, hi // rep, k_src, v_src, scratch)
    warmup()

    def body(j, carry):
        o, m, l = carry
        slot = jax.lax.rem(j, 2)
        prefetch(j, num_kb)
        kb, vb = load(j, slot)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        block_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, block_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        new_o = o * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_o, new_m, new_l

    o0 = jnp.zeros((block_q, hd), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    o, m, l = lax.fori_loop(0, jnp.asarray(num_kb, jnp.int32), body,
                            (o0, m0, l0))
    o_ref[0, 0] = (o / l).astype(o_ref.dtype)
    lse_ref[0, 0] = m + jnp.log(l)


# whole-s VMEM refs beat manual streaming while they fit under the 16MB
# scoped-VMEM ceiling (the BlockSpec auto-pipeline overlaps grid steps);
# measured cliffs on v5e with 512-blocks, hd=128: fwd/dq whole-s k/v holds
# through s=8192, dkv whole-s q/do through s=4096
_STREAM_KV_ELEMS = 8192 * 128    # fwd + dq: stream k/v above this s*hd
_STREAM_QDO_ELEMS = 4096 * 128   # dkv: stream q/do above this s*hd


def _qdo_specs(stream, s, hd, block_q, qdt, gdt):
    """in_specs (q, do) + scratch for the k-gridded dkv kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if stream:
        specs = [pl.BlockSpec(memory_space=pltpu.ANY),
                 pl.BlockSpec(memory_space=pltpu.ANY)]
        scratch = [
            pltpu.VMEM((2, block_q, hd), qdt),
            pltpu.VMEM((2, block_q, hd), gdt),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        return specs, scratch
    specs = [
        pl.BlockSpec((1, 1, s, hd), lambda bi, hi, ki: (bi, hi, 0, 0)),
        pl.BlockSpec((1, 1, s, hd), lambda bi, hi, ki: (bi, hi, 0, 0)),
    ]
    return specs, []


def _kv_specs(stream, s, hd, block_k, kdt, vdt, rep):
    """in_specs + scratch for the k/v pair of a q-gridded kernel."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    if stream:
        specs = [pl.BlockSpec(memory_space=pltpu.ANY),
                 pl.BlockSpec(memory_space=pltpu.ANY)]
        scratch = [
            pltpu.VMEM((2, block_k, hd), kdt),
            pltpu.VMEM((2, block_k, hd), vdt),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
        ]
        return specs, scratch
    specs = [
        pl.BlockSpec((1, 1, s, hd), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
        pl.BlockSpec((1, 1, s, hd), lambda bi, hi, qi: (bi, hi // rep, 0, 0)),
    ]
    return specs, []


def _flash_fwd_tpu(q, k, v, causal, block_q, block_k):
    """q: (b, h, s, hd); k/v: (b, kvh, s, hd). Returns (o, lse)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, hd = q.shape
    kvh = k.shape[1]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)
    grid = (b, h, s // block_q)
    stream = s * hd > _STREAM_KV_ELEMS

    kernel = functools.partial(
        _fwd_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, seq_len=s, rep=rep, stream=stream)
    kv_specs, kv_scratch = _kv_specs(stream, s, hd, block_k, k.dtype,
                                     v.dtype, rep)

    o, lse = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, hd), q.dtype),
            jax.ShapeDtypeStruct((b, h, s, 1), jnp.float32),
        ),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            *kv_specs,
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ),
        scratch_shapes=kv_scratch,
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size) * q.dtype.itemsize,
            transcendentals=int(b * h * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v)
    return o, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------


def _dq_kernel(q_ref, k_src, v_src, do_ref, lse_ref, delta_ref, dq_ref,
               *scratch, causal, scale, block_q, block_k, seq_len, rep,
               stream):
    """dq for one (batch, head, q-block); k/v via _kv_streamer."""
    from jax.experimental import pallas as pl

    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    qb = q_ref[0, 0].astype(jnp.float32) * scale            # (block_q, hd)
    dob = do_ref[0, 0].astype(jnp.float32)                  # (block_q, hd)
    lse = lse_ref[0, 0]                                     # (block_q, 1)
    delta = delta_ref[0, 0]                                 # (block_q, 1)
    hd = qb.shape[-1]

    num_kb = (
        pl.cdiv(qi * block_q + block_q, block_k) if causal
        else seq_len // block_k
    )
    warmup, prefetch, load = _kv_streamer(
        stream, block_k, bi, hi // rep, k_src, v_src, scratch)
    warmup()

    def body(j, dq):
        slot = jax.lax.rem(j, 2)
        prefetch(j, num_kb)
        kb, vb = load(j, slot)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (block_q, block_k)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        return dq + lax.dot_general(ds, kb, (((1,), (0,)), ((), ())),
                                    preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, jnp.asarray(num_kb, jnp.int32), body,
                       jnp.zeros((block_q, hd), jnp.float32))
    dq_ref[0, 0] = (dq * scale).astype(dq_ref.dtype)


def _dkv_kernel(q_src, k_ref, v_ref, do_src, lse_ref, delta_ref,
                dk_ref, dv_ref, *scratch, causal, scale, block_q, block_k,
                seq_len, rep, stream):
    """dk/dv for one (batch, query-head, k-block). stream=True moves q/do
    tiles from HBM through double-buffered VMEM scratch (O(block) VMEM at
    any seq_len — the whole-s q/do BlockSpec was the 8k/16k compile
    failure); stream=False keeps them whole-s in VMEM (faster when they
    fit). lse/delta always arrive as (b, h, 1, s) LANE-major rows, whole-s
    in VMEM: that layout pads only the sublane dim (8·s·4B, vs 128·s·4B
    for (s, 1) columns); each q-tile's rows are relayouted to a
    (block_q, 1) column in-kernel, which Mosaic supports."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bi, hi, ki = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    kb = k_ref[0, 0].astype(jnp.float32)                    # (block_k, hd)
    vb = v_ref[0, 0].astype(jnp.float32)                    # (block_k, hd)
    hd = kb.shape[-1]

    num_qb = seq_len // block_q
    # causal: only query blocks at/after this key block contribute
    start_qb = (ki * block_k) // block_q if causal else 0

    if stream:
        q_buf, do_buf, q_sem, do_sem = scratch

        def dma_rows(buf, hbm, sem, slot, i):
            return pltpu.make_async_copy(
                hbm.at[bi, hi, pl.ds(i * block_q, block_q), :],
                buf.at[slot], sem.at[slot])

        def start_all(slot, i):
            dma_rows(q_buf, q_src, q_sem, slot, i).start()
            dma_rows(do_buf, do_src, do_sem, slot, i).start()

        def prefetch(i):
            @pl.when(i + 1 < num_qb)
            def _():
                start_all(jax.lax.rem(i + 1, 2), i + 1)

        def load_rows(i, slot):
            dma_rows(q_buf, q_src, q_sem, slot, i).wait()
            dma_rows(do_buf, do_src, do_sem, slot, i).wait()
            return (q_buf[slot].astype(jnp.float32),
                    do_buf[slot].astype(jnp.float32))

        start_all(jax.lax.rem(jnp.asarray(start_qb, jnp.int32), 2),
                  jnp.asarray(start_qb, jnp.int32))
    else:
        def prefetch(i):
            pass

        def load_rows(i, _slot):
            qb = q_src[0, 0, pl.ds(i * block_q, block_q), :]
            dob = do_src[0, 0, pl.ds(i * block_q, block_q), :]
            return qb.astype(jnp.float32), dob.astype(jnp.float32)

    def body(i, carry):
        dk, dv = carry
        slot = jax.lax.rem(i, 2)
        prefetch(i)
        qb, dob = load_rows(i, slot)
        lse = lse_ref[0, 0, 0, pl.ds(i * block_q, block_q)][:, None]
        delta = delta_ref[0, 0, 0, pl.ds(i * block_q, block_q)][:, None]
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                                 # (block_q, block_k)
        # dv += pᵀ @ dO
        dv = dv + lax.dot_general(p, dob, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        dp = lax.dot_general(dob, vb, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        # dk += dsᵀ @ q
        dk = dk + lax.dot_general(ds, qb, (((0,), (0,)), ((), ())),
                                  preferred_element_type=jnp.float32)
        return dk, dv

    z = jnp.zeros((block_k, hd), jnp.float32)
    dk, dv = lax.fori_loop(jnp.asarray(start_qb, jnp.int32),
                           jnp.asarray(num_qb, jnp.int32), body, (z, z))
    dk_ref[0, 0] = (dk * scale).astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


def _flash_bwd_tpu(q, k, v, o, lse, g, causal, block_q, block_k,
                   dkv_block_q=None, dkv_block_k=None):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, s, hd = q.shape
    kvh = k.shape[1]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)
    dkv_block_q = dkv_block_q or block_q
    dkv_block_k = dkv_block_k or block_k

    # delta[i] = Σ_d dO[i,d]·O[i,d] — cheap rowwise reduce, fused by XLA
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1,
                    keepdims=True)

    kv_stream = s * hd > _STREAM_KV_ELEMS
    dq_kernel = functools.partial(
        _dq_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, seq_len=s, rep=rep,
        stream=kv_stream)
    kv_specs, kv_scratch = _kv_specs(kv_stream, s, hd, block_k, k.dtype,
                                     v.dtype, rep)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, s, hd), q.dtype),
        grid=(b, h, s // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            *kv_specs,
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        scratch_shapes=kv_scratch,
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(3 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size * 3) * q.dtype.itemsize,
            transcendentals=int(b * h * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v, g, lse, delta)

    # dk/dv per *query* head (grid over h), reduced over the GQA group after.
    qdo_stream = s * hd > _STREAM_QDO_ELEMS
    dkv_kernel = functools.partial(
        _dkv_kernel, causal=causal, scale=scale,
        block_q=dkv_block_q, block_k=dkv_block_k, seq_len=s, rep=rep,
        stream=qdo_stream)
    qdo_specs, qdo_scratch = _qdo_specs(qdo_stream, s, hd, dkv_block_q,
                                        q.dtype, g.dtype)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, s, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, h, s, hd), jnp.float32),
        ),
        grid=(b, h, s // dkv_block_k),
        in_specs=[
            qdo_specs[0],
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi // rep, ki, 0)),
            qdo_specs[1],
            pl.BlockSpec((1, 1, 1, s), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, s), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ),
        scratch_shapes=qdo_scratch,
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(4 * 2 * b * h * s * s * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size * 4) * q.dtype.itemsize,
            transcendentals=int(b * h * s * s * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v, g, lse.reshape(b, h, 1, s), delta.reshape(b, h, 1, s))

    if rep != 1:
        dk = dk.reshape(b, kvh, rep, s, hd).sum(axis=2)
        dv = dv.reshape(b, kvh, rep, s, hd).sum(axis=2)
    return dq, dk.astype(k.dtype), dv.astype(v.dtype)


# ---------------------------------------------------------------------------
# accumulator-carrying chunk attention (the ring-attention hop primitive)
# ---------------------------------------------------------------------------


def _chunk_xla(q, k, v, o, m, l, causal):
    """Online-softmax accumulation of one K/V chunk, XLA reference.

    q: (b, h, sq, hd); k/v: (b, kvh, sk, hd); o: (b, h, sq, hd) fp32;
    m/l: (b, h, sq, 1) fp32 running max / denominator.
    `causal` masks with LOCAL positions (the diagonal ring hop, sq == sk);
    off-diagonal hops are either fully unmasked or skipped by the caller.
    """
    b, h, sq, hd = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    if kvh != h:
        rep = h // kvh
        k = jnp.repeat(k, rep, axis=1)
        v = jnp.repeat(v, rep, axis=1)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        logits = jnp.where((q_pos >= k_pos)[None, None], logits, NEG_INF)
    block_max = jnp.max(logits, axis=-1, keepdims=True)
    new_m = jnp.maximum(m, block_max)
    corr = jnp.exp(m - new_m)
    p = jnp.exp(logits - new_m)
    new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    pv = jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    new_o = o * corr + pv
    return new_o, new_m, new_l


def _chunk_kernel(q_ref, k_src, v_src, oi_ref, mi_ref, li_ref,
                  oo_ref, mo_ref, lo_ref, *scratch,
                  causal, scale, block_q, block_k, sk, rep, stream):
    from jax.experimental import pallas as pl

    bi, hi, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    qb = q_ref[0, 0].astype(jnp.float32) * scale           # (block_q, hd)

    num_kb = (
        pl.cdiv(qi * block_q + block_q, block_k) if causal
        else sk // block_k
    )
    warmup, prefetch, load = _kv_streamer(
        stream, block_k, bi, hi // rep, k_src, v_src, scratch)
    warmup()

    def body(j, carry):
        o, m, l = carry
        slot = jax.lax.rem(j, 2)
        prefetch(j, num_kb)
        kb, vb = load(j, slot)
        s = lax.dot_general(qb, kb, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)
        if causal:
            q_pos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        block_max = jnp.max(s, axis=-1, keepdims=True)
        new_m = jnp.maximum(m, block_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m)
        new_l = l * corr + jnp.sum(p, axis=-1, keepdims=True)
        new_o = o * corr + lax.dot_general(
            p, vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return new_o, new_m, new_l

    o, m, l = lax.fori_loop(
        0, jnp.asarray(num_kb, jnp.int32), body,
        (oi_ref[0, 0], mi_ref[0, 0], li_ref[0, 0]))
    oo_ref[0, 0] = o
    mo_ref[0, 0] = m
    lo_ref[0, 0] = l


def _flash_chunk_tpu(q, k, v, o, m, l, causal, block_q, block_k):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, hd = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)

    stream = sk * hd > _STREAM_KV_ELEMS
    kernel = functools.partial(
        _chunk_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, sk=sk, rep=rep, stream=stream)
    kv_specs, kv_scratch = _kv_specs(stream, sk, hd, block_k, k.dtype,
                                     v.dtype, rep)
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, sq, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sq, 1), jnp.float32),
        ),
        grid=(b, h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            *kv_specs,
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ),
        scratch_shapes=kv_scratch,
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        cost_estimate=pl.CostEstimate(
            flops=int(2 * 2 * b * h * sq * sk * hd * (0.5 if causal else 1.0)),
            bytes_accessed=(q.size + k.size + v.size + o.size)
            * q.dtype.itemsize,
            transcendentals=int(b * h * sq * sk * (0.5 if causal else 1.0)),
        ),
        interpret=_INTERPRET,
    )(q, k, v, o, m, l)


def _chunk_supported(q, k, block_q, block_k):
    sq, hd = q.shape[2], q.shape[3]
    sk = k.shape[2]
    return (
        jax.default_backend() == "tpu"
        and sq % min(block_q, sq) == 0
        and sk % min(block_k, sk) == 0
        and hd % 128 == 0
        and q.shape[1] % k.shape[1] == 0
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(6, 7, 8))
def flash_chunk_bhsd(q, k, v, o, m, l, causal=False,
                     block_q: int = 512, block_k: int = 512):
    """One online-softmax accumulation hop with carried (o, m, l) state.

    The ring-attention primitive: forward runs the Pallas kernel (no (sq, sk)
    materialization); backward recomputes the hop in XLA — with custom_vjp
    the residuals are just the six inputs, so ring attention training stores
    O(s·d) per hop instead of the O(s²/sp) probability blocks JAX autodiff
    would save.
    """
    if _chunk_supported(q, k, block_q, block_k):
        return _flash_chunk_tpu(q, k, v, o, m, l, causal,
                                min(block_q, q.shape[2]),
                                min(block_k, k.shape[2]))
    return _chunk_xla(q, k, v, o, m, l, causal)


def _chunk_fwd_rule(q, k, v, o, m, l, causal, block_q, block_k):
    out = flash_chunk_bhsd(q, k, v, o, m, l, causal, block_q, block_k)
    return out, (q, k, v, o, m, l)


def _chunk_bwd_rule(causal, block_q, block_k, res, g):
    q, k, v, o, m, l = res
    _, vjp = jax.vjp(
        lambda q, k, v, o, m, l: _chunk_xla(q, k, v, o, m, l, causal),
        q, k, v, o, m, l)
    return vjp(g)


flash_chunk_bhsd.defvjp(_chunk_fwd_rule, _chunk_bwd_rule)


# ---------------------------------------------------------------------------
# ring hop backward (used by ring attention's ring-level custom VJP)
# ---------------------------------------------------------------------------


def _hop_bwd_xla(q, k, v, g, lse, delta, causal):
    """FA2-style backward for one ring hop, XLA fallback.

    q/g: (b, h, sq, hd); k/v: (b, kvh, sk, hd); lse/delta: (b, h, sq, 1)
    fp32 — the GLOBAL logsumexp / dO·O row sums saved by the ring forward.
    Returns (dq, dk, dv) in fp32 with dk/dv at kvh heads.
    """
    b, h, sq, hd = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    rep = h // kvh
    kr, vr = k, v
    if rep != 1:
        kr = jnp.repeat(k, rep, axis=1)
        vr = jnp.repeat(v, rep, axis=1)
    scale = 1.0 / math.sqrt(hd)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, kr,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        q_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 0)
        k_pos = lax.broadcasted_iota(jnp.int32, (sq, sk), 1)
        s = jnp.where((q_pos >= k_pos)[None, None], s, NEG_INF)
    p = jnp.exp(s - lse)
    g32 = g.astype(jnp.float32)
    dv = jnp.einsum("bhqk,bhqd->bhkd", p, g32)
    dp = jnp.einsum("bhqd,bhkd->bhqk", g32, vr.astype(jnp.float32))
    ds = p * (dp - delta)
    dq = jnp.einsum("bhqk,bhkd->bhqd", ds, kr.astype(jnp.float32)) * scale
    dk = jnp.einsum("bhqk,bhqd->bhkd", ds, q.astype(jnp.float32)) * scale
    if rep != 1:
        dk = dk.reshape(b, kvh, rep, sk, hd).sum(axis=2)
        dv = dv.reshape(b, kvh, rep, sk, hd).sum(axis=2)
    return dq, dk, dv


def _hop_bwd_tpu(q, k, v, g, lse, delta, causal, block_q, block_k,
                 dkv_block_q=None, dkv_block_k=None):
    """Pallas hop backward: the dq/dkv kernels against one K/V block with
    externally supplied (global) lse/delta — no (sq, sk) materialization."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, sq, hd = q.shape
    kvh, sk = k.shape[1], k.shape[2]
    rep = h // kvh
    scale = 1.0 / math.sqrt(hd)
    dkv_block_q = dkv_block_q or block_q
    dkv_block_k = dkv_block_k or block_k

    kv_stream = sk * hd > _STREAM_KV_ELEMS
    dq_kernel = functools.partial(
        _dq_kernel, causal=causal, scale=scale,
        block_q=block_q, block_k=block_k, seq_len=sk, rep=rep,
        stream=kv_stream)
    kv_specs, kv_scratch = _kv_specs(kv_stream, sk, hd, block_k, k.dtype,
                                     v.dtype, rep)
    dq = pl.pallas_call(
        dq_kernel,
        out_shape=jax.ShapeDtypeStruct((b, h, sq, hd), jnp.float32),
        grid=(b, h, sq // block_q),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            *kv_specs,
            pl.BlockSpec((1, 1, block_q, hd), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda bi, hi, qi: (bi, hi, qi, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, hd),
                               lambda bi, hi, qi: (bi, hi, qi, 0)),
        scratch_shapes=kv_scratch,
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(q, k, v, g, lse, delta)

    qdo_stream = sq * hd > _STREAM_QDO_ELEMS
    dkv_kernel = functools.partial(
        _dkv_kernel, causal=causal, scale=scale,
        block_q=dkv_block_q, block_k=dkv_block_k, seq_len=sq, rep=rep,
        stream=qdo_stream)
    qdo_specs, qdo_scratch = _qdo_specs(qdo_stream, sq, hd, dkv_block_q,
                                        q.dtype, g.dtype)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, h, sk, hd), jnp.float32),
            jax.ShapeDtypeStruct((b, h, sk, hd), jnp.float32),
        ),
        grid=(b, h, sk // dkv_block_k),
        in_specs=[
            qdo_specs[0],
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi // rep, ki, 0)),
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi // rep, ki, 0)),
            qdo_specs[1],
            pl.BlockSpec((1, 1, 1, sq), lambda bi, hi, ki: (bi, hi, 0, 0)),
            pl.BlockSpec((1, 1, 1, sq), lambda bi, hi, ki: (bi, hi, 0, 0)),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, dkv_block_k, hd), lambda bi, hi, ki: (bi, hi, ki, 0)),
        ),
        scratch_shapes=qdo_scratch,
        compiler_params=_compiler_params_cls(pltpu)(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=_INTERPRET,
    )(q, k, v, g, lse.reshape(b, h, 1, sq), delta.reshape(b, h, 1, sq))

    if rep != 1:
        dk = dk.reshape(b, kvh, rep, sk, hd).sum(axis=2)
        dv = dv.reshape(b, kvh, rep, sk, hd).sum(axis=2)
    return dq, dk, dv


def flash_hop_bwd(q, k, v, g, lse, delta, causal,
                  block_q: int = 512, block_k: int = 512):
    """Backward of one ring-attention hop given global lse/delta rows."""
    bq = min(block_q, q.shape[2])
    bk = min(block_k, k.shape[2])
    if _chunk_supported(q, k, bq, bk):
        # streamed dq/dkv kernels: O(block) VMEM at any per-shard length
        return _hop_bwd_tpu(q, k, v, g, lse, delta, causal, bq, bk,
                            dkv_block_q=bq, dkv_block_k=bk)
    return _hop_bwd_xla(q, k, v, g, lse, delta, causal)


# ---------------------------------------------------------------------------
# custom-vjp wiring (bhsd core)
# ---------------------------------------------------------------------------


def _supported_on_tpu(q, k, block_q, block_k):
    # NOTE: the dkv kernel's causal start block `(ki*block_k)//block_q` is a
    # floor and stays correct for ANY block_q/block_k combination (including
    # the mismatched 512/256 long-context backward blocks), so no
    # divisibility constraint between the two is required.
    b, h, s, hd = q.shape
    return (
        jax.default_backend() == "tpu"
        and s % block_q == 0
        and s % block_k == 0
        and hd % 128 == 0
        and h % k.shape[1] == 0
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def _flash_bhsd(q, k, v, causal, block_q, block_k, bwd_block_q, bwd_block_k):
    if _supported_on_tpu(q, k, block_q, block_k):
        return _flash_fwd_tpu(q, k, v, causal, block_q, block_k)[0]
    return _xla_attention_bhsd(q, k, v, causal)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, bwd_block_q,
                    bwd_block_k):
    if _supported_on_tpu(q, k, block_q, block_k):
        o, lse = _flash_fwd_tpu(q, k, v, causal, block_q, block_k)
        return o, (q, k, v, o, lse)
    return _xla_attention_bhsd(q, k, v, causal), (q, k, v, None, None)


def _flash_bwd_rule(causal, block_q, block_k, bwd_block_q, bwd_block_k,
                    res, g):
    q, k, v, o, lse = res
    if o is not None:
        # dq runs at the full forward block size; only dkv (which holds
        # full-s q AND do in VMEM) needs the smaller long-context blocks
        return _flash_bwd_tpu(q, k, v, o, lse, g, causal, block_q, block_k,
                              dkv_block_q=bwd_block_q,
                              dkv_block_k=bwd_block_k)
    _, vjp = jax.vjp(
        lambda q, k, v: _xla_attention_bhsd(q, k, v, causal), q, k, v)
    return vjp(g)


_flash_bhsd.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# public entry points
# ---------------------------------------------------------------------------


def flash_attention_bhsd(q, k, v, causal: bool = True,
                         block_q: int = 512, block_k: int = 512):
    """q: (batch, heads, seq, head_dim); k/v: (batch, kv_heads, seq, head_dim).

    The TPU-native layout: head_dim on the lane dimension, no transposes.
    """
    s = q.shape[2]
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if block_k % block_q != 0:
        block_q = block_k = min(block_q, block_k)
    # backward blocks match the forward: the dq/dkv kernels stream their
    # full-sequence operands from HBM through double-buffered tiles, so
    # VMEM use is O(block) at any seq_len (the old whole-s BlockSpecs
    # overflowed scoped VMEM at 8k/16k and forced 256-blocks)
    return _flash_bhsd(q, k, v, causal, block_q, block_k, block_q, block_k)


def flash_attention(q, k, v, causal: bool = True,
                    block_q: int = 512, block_k: int = 512):
    """Layout-standard entry. q/k/v: (batch, seq, heads, head_dim)."""
    q = q.transpose(0, 2, 1, 3)
    k = k.transpose(0, 2, 1, 3)
    v = v.transpose(0, 2, 1, 3)
    out = flash_attention_bhsd(q, k, v, causal, block_q, block_k)
    return out.transpose(0, 2, 1, 3)
