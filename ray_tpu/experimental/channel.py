"""Preallocated shared-memory channels — the compiled-graph data plane.

Reference surface: python/ray/experimental/channel/shared_memory_channel.py
(mutable-plasma channels preallocated per compiled-DAG edge) +
experimental_mutable_object_manager.h. Redesign: a channel is ONE sealed
object in the node's serverless shm store holding a native SPSC ring
(ray_tpu/native/shm_channel.cc); producer and consumer map the same segment
and synchronize through C++ atomics — a hop costs one serialize + memcpy +
atomic publish, with no RPC, task submission, scheduling, or allocation.

Ring capacity doubles as pipeline backpressure: `write` blocks when the
consumer is `nslots` executions behind, exactly how the reference bounds
in-flight compiled-DAG executions via its channel buffers.

Blocking reads/writes park on a futex doorbell in the shared header
(rt_chan_wait_readable / rt_chan_wait_writable) — no sleep-polling, so an
idle compiled-DAG executor loop costs zero CPU and a hop wakes at kernel
futex latency instead of a poll interval (the reference's channels block on
OS primitives the same way). Waits are chunked so Python signal handlers
(Ctrl-C) still run between kernel sleeps.
"""

from __future__ import annotations

import ctypes
import time
from typing import Any, Optional

from ray_tpu._private.ids import ObjectID
from ray_tpu.native.build import lib_path

# futex waits release the GIL but block signal delivery for their duration;
# cap each kernel sleep so KeyboardInterrupt lands within this bound
_WAIT_CHUNK_S = 0.5


class _Lib:
    _instance = None

    def __new__(cls):
        if cls._instance is None:
            lib = ctypes.CDLL(lib_path("shm_channel"))
            lib.rt_chan_required_size.restype = ctypes.c_uint64
            lib.rt_chan_required_size.argtypes = [ctypes.c_uint64, ctypes.c_uint64]
            lib.rt_chan_init.argtypes = [
                ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
            lib.rt_chan_validate.argtypes = [ctypes.c_void_p]
            lib.rt_chan_reserve.restype = ctypes.c_int64
            lib.rt_chan_reserve.argtypes = [ctypes.c_void_p]
            lib.rt_chan_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
            lib.rt_chan_acquire.restype = ctypes.c_int64
            lib.rt_chan_acquire.argtypes = [
                ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
            lib.rt_chan_release.argtypes = [ctypes.c_void_p]
            lib.rt_chan_close.argtypes = [ctypes.c_void_p]
            lib.rt_chan_readable.restype = ctypes.c_uint64
            lib.rt_chan_readable.argtypes = [ctypes.c_void_p]
            lib.rt_chan_wait_readable.restype = ctypes.c_int
            lib.rt_chan_wait_readable.argtypes = [
                ctypes.c_void_p, ctypes.c_int64]
            lib.rt_chan_wait_writable.restype = ctypes.c_int
            lib.rt_chan_wait_writable.argtypes = [
                ctypes.c_void_p, ctypes.c_int64]
            lib.rt_chan_prefault.argtypes = [ctypes.c_void_p, ctypes.c_int]
            cls._instance = lib
        return cls._instance


def channel_object_id(dag_id: str, edge: str) -> ObjectID:
    import hashlib

    digest = hashlib.sha256(f"rtchan:{dag_id}:{edge}".encode()).digest()
    return ObjectID(digest[:24])


class ShmChannel:
    """One compiled-DAG edge. Create once (creator=True), then open from any
    process on the node that shares the store.

    Channel state mutates after seal BY DESIGN — these are the framework's
    mutable objects (reference: experimental_mutable_object_manager.h); the
    seal only publishes the region. All mutation goes through the native
    SPSC ring ops against the store's writable mapping; the object stays
    pinned by this handle's get() refcount so the LRU can never evict a
    live channel."""

    def __init__(self, store, oid: ObjectID, *, creator: bool = False,
                 nslots: int = 8, slot_size: int = 1 << 20):
        self._lib = _Lib()
        self._lib.rt_chan_slot_size.restype = ctypes.c_uint64
        self._lib.rt_chan_slot_size.argtypes = [ctypes.c_void_p]
        self._store = store
        self.oid = oid
        size = self._lib.rt_chan_required_size(nslots, slot_size)
        if creator:
            view = store.create(oid, size)
            # the ring header must be valid BEFORE seal publishes the
            # object: a peer's open (get_blocking) returns the instant the
            # seal lands, and an uninitialized header fails its magic check
            addr = ctypes.addressof(ctypes.c_uint8.from_buffer(view))
            rc = self._lib.rt_chan_init(addr, size, nslots, slot_size)
            view.release()
            if rc != 0:
                raise RuntimeError(f"channel init failed rc={rc}")
            store.seal(oid)
            self._chan_off, _ = self._pin()
            self._base = self._map_addr() + self._chan_off
            self.slot_size = slot_size
        else:
            got = store.get_blocking(oid, timeout=30)
            if got is None:
                raise TimeoutError(f"channel object {oid} never appeared")
            view, _ = got
            view.release()
            # get_blocking pinned the object once; keep that pin for life
            self._chan_off, _ = self._query_offset()
            self._base = self._map_addr() + self._chan_off
            if self._lib.rt_chan_validate(self._base) != 0:
                raise RuntimeError(f"object {oid} is not a channel")
            self.slot_size = self._lib.rt_chan_slot_size(self._base)

    def _map_addr(self) -> int:
        return ctypes.addressof(
            ctypes.c_uint8.from_buffer(self._store._map))

    def _query_offset(self):
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        meta = ctypes.c_uint64()
        rc = self._store._lib.rt_object_get(
            self._store._handle, self.oid.binary(), ctypes.byref(off),
            ctypes.byref(size), ctypes.byref(meta))
        if rc != 0:
            raise RuntimeError("channel object vanished")
        # rt_object_get pinned it again; drop the extra pin (the original
        # one from __init__ stays)
        self._store._lib.rt_object_release(self._store._handle,
                                           self.oid.binary())
        return off.value, size.value

    def _pin(self):
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        meta = ctypes.c_uint64()
        rc = self._store._lib.rt_object_get(
            self._store._handle, self.oid.binary(), ctypes.byref(off),
            ctypes.byref(size), ctypes.byref(meta))
        if rc != 0:
            raise RuntimeError("channel object vanished after create")
        return off.value, size.value

    # -- raw byte API ---------------------------------------------------

    def try_write_bytes(self, payload) -> bool:
        n = len(payload)
        if n > self.slot_size:
            # MUST be checked before the copy: an oversized memcpy would
            # trash the next slot / neighboring store objects for every
            # process mapping the segment
            raise ValueError(
                f"payload of {n} bytes exceeds channel slot size "
                f"{self.slot_size}")
        off = self._lib.rt_chan_reserve(self._base)
        if off == -3:
            # ring closed (reader tore down, or writer hang-up): writes must
            # fail fast instead of blocking into freed/teardown state
            raise EOFError("channel closed")
        if off < 0:
            return False
        dst = self._chan_off + off
        self._store._mv[dst:dst + n] = payload
        rc = self._lib.rt_chan_commit(self._base, n)
        if rc != 0:
            raise ValueError(f"payload of {n} bytes exceeds channel slot size")
        return True

    def _wait(self, waiter, deadline: Optional[float]) -> bool:
        """One parked doorbell wait (chunked); False once the deadline has
        passed. `waiter` is rt_chan_wait_readable/_writable."""
        if deadline is None:
            waiter(self._base, int(_WAIT_CHUNK_S * 1e6))
            return True
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            return False
        waiter(self._base, int(min(remaining, _WAIT_CHUNK_S) * 1e6))
        return True

    def write_bytes(self, payload, timeout: Optional[float] = None) -> None:
        deadline = None if timeout is None else time.monotonic() + timeout
        while not self.try_write_bytes(payload):
            if not self._wait(self._lib.rt_chan_wait_writable, deadline):
                raise TimeoutError("channel full (consumer stalled?)")

    def try_read_bytes(self) -> Optional[bytes]:
        ln = ctypes.c_uint64()
        off = self._lib.rt_chan_acquire(self._base, ctypes.byref(ln))
        if off == -1:
            return None
        if off == -2:
            raise EOFError("channel closed by writer")
        src = self._chan_off + off
        data = bytes(self._store._mv[src:src + ln.value])
        self._lib.rt_chan_release(self._base)
        return data

    # -- zero-copy slot access (consumers that reduce/deserialize in
    # place; the ring slot is reused, so pages fault once and stay hot —
    # unlike per-transfer store objects whose fresh pages fault per call)

    def reserve_view(self, nbytes: int,
                     timeout: Optional[float] = None) -> memoryview:
        """Blocking writer half of a zero-copy write: returns a writable
        view of the next slot; fill it, then call commit(nbytes)."""
        if nbytes > self.slot_size:
            raise ValueError(
                f"payload of {nbytes} bytes exceeds channel slot size "
                f"{self.slot_size}")
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            off = self._lib.rt_chan_reserve(self._base)
            if off >= 0:
                dst = self._chan_off + off
                return self._store._mv[dst:dst + nbytes]
            if off == -3:
                raise EOFError("channel closed")
            if not self._wait(self._lib.rt_chan_wait_writable, deadline):
                raise TimeoutError("channel full (consumer stalled?)")

    def commit(self, nbytes: int) -> None:
        rc = self._lib.rt_chan_commit(self._base, nbytes)
        if rc != 0:
            raise ValueError(
                f"payload of {nbytes} bytes exceeds channel slot size")

    def read_view(self, timeout: Optional[float] = None) -> memoryview:
        """Blocking reader half of a zero-copy read: returns a readonly
        view of the next slot's payload; call consume() when done (the
        view must not be used after)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        ln = ctypes.c_uint64()
        while True:
            off = self._lib.rt_chan_acquire(self._base, ctypes.byref(ln))
            if off >= 0:
                src = self._chan_off + off
                return self._store._mv[src:src + ln.value].toreadonly()
            if off == -2:
                raise EOFError("channel closed by writer")
            if not self._wait(self._lib.rt_chan_wait_readable, deadline):
                raise TimeoutError("channel empty (producer stalled?)")

    def consume(self) -> None:
        self._lib.rt_chan_release(self._base)

    def prefault(self, write: bool) -> None:
        """Touch every slot's payload pages in this process's mapping so
        first transfers run at memcpy speed (no per-4KB minor faults).
        write=True is for the producer side and is only safe while the
        ring carries no committed slots."""
        self._lib.rt_chan_prefault(self._base, 1 if write else 0)

    def read_bytes(self, timeout: Optional[float] = None) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            data = self.try_read_bytes()
            if data is not None:
                return data
            if not self._wait(self._lib.rt_chan_wait_readable, deadline):
                raise TimeoutError("channel empty (producer stalled?)")

    # -- object API -----------------------------------------------------

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        from ray_tpu._private import serialization as ser

        self.write_bytes(ser.serialize(value).to_bytes(), timeout)

    def read(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu._private import serialization as ser

        return ser.deserialize(self.read_bytes(timeout))

    def close(self) -> None:
        """Writer hang-up: readers see EOFError after draining."""
        self._lib.rt_chan_close(self._base)

    def readable(self) -> int:
        return self._lib.rt_chan_readable(self._base)

    def unpin(self) -> None:
        self._store.release(self.oid)


class RemoteChannel:
    """Writer half of a compiled-DAG edge whose ring lives on ANOTHER
    node: payload bytes ship over the worker RPC plane into the reader
    process, which lands them in its local shm ring (rpc_chan_write on
    the reader's core worker). Same write/write_bytes surface as
    ShmChannel — the executor loop can't tell the difference. Reference:
    python/ray/experimental/channel/torch_tensor_accelerator_channel.py
    (cross-node channel endpoints), redesigned for the RPC plane.

    Backpressure carries through: the reader-side write blocks on the
    ring's futex doorbell up to `timeout`, and a full ring surfaces here
    as the same TimeoutError a local writer would see."""

    def __init__(self, dag_id: str, edge: str, address: str,
                 slot_size: int = 1 << 20):
        from ray_tpu._private.core_worker import get_core_worker

        self._cw = get_core_worker()
        self._dag_id = dag_id
        self._edge = edge
        self._address = address
        self.slot_size = slot_size
        # per-edge slot counter: makes chan_write idempotent under RPC
        # retries (a duplicate slot would shift every later execution)
        self._seq = 0

    async def _write_async(self, payload: bytes, timeout: Optional[float]):
        client = await self._cw._worker_client(self._address)
        rpc_timeout = 30.0 if timeout is None else timeout + 30.0
        return await client.call("chan_write", {
            "dag_id": self._dag_id,
            "edge": self._edge,
            "payload": payload,
            "seq": self._seq,
            # the reader registers its ring at executor-loop start, which
            # can queue behind earlier work on that actor — wait at least
            # as long as a same-node writer's 30s blocking open would
            "open_timeout": 60.0,
            # cap the remote blocking write so the RPC reply (and our
            # rpc_timeout above) always outlives it
            "timeout": 25.0 if timeout is None else timeout,
        }, timeout=rpc_timeout)

    def write_bytes(self, payload, timeout: Optional[float] = None) -> None:
        if len(payload) > self.slot_size:
            raise ValueError(
                f"payload of {len(payload)} bytes exceeds channel slot "
                f"size {self.slot_size}")
        payload = bytes(payload)
        deadline = None if timeout is None else time.monotonic() + timeout
        conn_retries = 3
        while True:
            remaining = (None if deadline is None
                         else max(0.1, deadline - time.monotonic()))
            try:
                res = self._cw.run_sync(
                    self._write_async(payload, remaining),
                    timeout=(remaining or 30.0) + 60.0)
            except Exception as exc:  # noqa: BLE001 — transport failure
                # the write may or may not have landed; the seq watermark
                # makes a retry safe (duplicate slots are dropped)
                conn_retries -= 1
                if conn_retries < 0:
                    raise RuntimeError(
                        f"remote channel {self._dag_id}:{self._edge} @ "
                        f"{self._address}: transport failed ({exc})") from exc
                time.sleep(0.2)
                continue
            err = res.get("error")
            if err is None:
                self._seq += 1  # slot landed (or deduped): next slot
                return
            if err == "full":
                if deadline is not None and time.monotonic() >= deadline:
                    raise TimeoutError("channel full (consumer stalled?)")
                continue  # timeout=None: keep blocking like a local writer
            if err.startswith("value:"):
                raise ValueError(err[len("value:"):])
            raise RuntimeError(
                f"remote channel {self._dag_id}:{self._edge} @ "
                f"{self._address}: {err}")

    def write(self, value: Any, timeout: Optional[float] = None) -> None:
        from ray_tpu._private import serialization as ser

        self.write_bytes(ser.serialize(value).to_bytes(), timeout)

    def unpin(self) -> None:
        pass  # the ring is pinned by its reader
