"""Device-direct object transport for jax.Arrays (TPU RDT).

Reference surface: python/ray/experimental/rdt/ (rdt_manager.py, the NIXL /
CUDA-IPC tensor transports) — GPU tensors move out-of-band while the object
store holds metadata. The TPU-native shape of that idea: a device array put
into the object plane keeps living in HBM in its producer process; the store
carries a host-staged copy plus a transport id. A consumer in the SAME
process gets the original on-device array back untouched (no h2d upload, no
d2h round trip, `is`-identical while the producer's array is alive); a
consumer elsewhere rebuilds from the host bytes with `jax.device_put`.

Unlike NIXL/CUDA-IPC there is no cross-process device-to-device path on TPU
outside a mesh program: inter-chip movement belongs to XLA collectives
(ppermute/all_gather inside jit), so the out-of-band transport here is
process-local HBM reuse + host staging, which is what the hardware offers.
"""

from __future__ import annotations

import os
import weakref
from collections import OrderedDict
from typing import Any, Dict, Optional

_STRONG_CAP = 256


class DeviceObjectManager:
    """Process-local registry of live device arrays keyed by transport id.

    Weak references wherever the array type allows them (the registry must
    not pin HBM the producer has dropped); a bounded strong-ref LRU
    otherwise."""

    def __init__(self, strong_cap: int = _STRONG_CAP):
        self._weak: Dict[bytes, weakref.ref] = {}
        self._strong: "OrderedDict[bytes, Any]" = OrderedDict()
        self._strong_cap = strong_cap
        # transport outcome counters: `device_hits` = same-process consumer
        # got the original HBM-resident array back untouched; `host_rebuilds`
        # = the consumer re-uploaded from the host staging bytes (cross-
        # process, or the producer donated/dropped the buffer). The elastic
        # train resize asserts on these: a shard that keeps its holder must
        # be a device hit, never an upload.
        self.stats: Dict[str, int] = {"registered": 0, "device_hits": 0,
                                      "host_rebuilds": 0}

    def register(self, arr: Any) -> bytes:
        tid = os.urandom(16)
        self.stats["registered"] += 1
        try:
            self._weak[tid] = weakref.ref(
                arr, lambda _r, t=tid: self._weak.pop(t, None)
            )
        except TypeError:
            self._strong[tid] = arr
            while len(self._strong) > self._strong_cap:
                self._strong.popitem(last=False)
        return tid

    def lookup(self, tid: bytes) -> Optional[Any]:
        r = self._weak.get(tid)
        if r is not None:
            return r()
        arr = self._strong.get(tid)
        if arr is not None:
            self._strong.move_to_end(tid)  # true LRU: hot entries survive
        return arr

    def __len__(self) -> int:
        return len(self._weak) + len(self._strong)


_manager: Optional[DeviceObjectManager] = None


def device_object_manager() -> DeviceObjectManager:
    global _manager
    if _manager is None:
        _manager = DeviceObjectManager()
    return _manager


def _rebuild_device_array(tid: bytes, host: Any) -> Any:
    """Unpickle hook: same-process → the original HBM-resident array;
    elsewhere → upload the host staging copy."""
    mgr = device_object_manager()
    arr = mgr.lookup(tid)
    if arr is not None:
        # A producer that donated its array to a jitted step after put()
        # (donate_argnums — the standard training loop) leaves a deleted
        # buffer registered here; handing it out would fail gets that the
        # host staging bytes can serve (advisor r2).
        deleted = getattr(arr, "is_deleted", None)
        if deleted is None or not deleted():
            mgr.stats["device_hits"] += 1
            return arr
    import jax

    mgr.stats["host_rebuilds"] += 1
    return jax.device_put(host)


def maybe_reduce_device_array(obj: Any):
    """Custom-reduce hook used by the serializer: device arrays become
    (transport id, host bytes) with the live array registered out-of-band.
    Returns NotImplemented for everything that is not a concrete, fully
    addressable jax.Array."""
    import sys

    if "jax" not in sys.modules:
        return NotImplemented  # no jax imported → can't be a jax.Array
    from ray_tpu._private.config import GLOBAL_CONFIG

    if not GLOBAL_CONFIG.get("device_object_transport"):
        return NotImplemented
    import jax

    if not isinstance(obj, jax.Array):
        return NotImplemented
    try:
        import numpy as np

        if not obj.is_fully_addressable:
            return NotImplemented  # multi-host array: owner can't stage it
        host = np.asarray(obj)  # one d2h copy for the store's staging bytes
    except Exception:  # noqa: BLE001 — tracers, deleted buffers, etc.
        return NotImplemented
    tid = device_object_manager().register(obj)
    return (_rebuild_device_array, (tid, host))


__all__ = [
    "DeviceObjectManager",
    "device_object_manager",
    "maybe_reduce_device_array",
]
