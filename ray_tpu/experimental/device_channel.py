"""Device-native channel: a compiled-graph edge that carries jax.Arrays
chip-to-chip through XLA collective-permute instead of host serialization.

Reference surface: python/ray/experimental/channel/
torch_tensor_accelerator_channel.py (NCCL p2p channels between accelerator
workers inside compiled DAGs). TPU redesign: there is no out-of-band
device-to-device DMA outside a mesh program — inter-chip movement belongs
to XLA collectives — so a device edge is a RENDEZVOUS: both endpoint
processes enter the same jitted collective-permute step and the payload
rides ICI when the endpoints share a slice (DCN across slices), never
touching host memory. The host shm/RPC channel plane remains the fallback
for edges that leave the gang (ray_tpu/experimental/channel.py).

Contract: write(src side) and read(dst side) are the two halves of ONE
collective call, so the endpoints must invoke them in matching order —
exactly what a compiled DAG's static per-actor schedules guarantee
(reference: compiled_dag_node.py orders NCCL sends/recvs the same way).
The reader declares shape/dtype up front (channels are typed, like the
reference's TorchTensorType annotation), so no metadata round-trip is
needed at transfer time.
"""

from __future__ import annotations

from typing import Any, Tuple

import numpy as np


class DeviceChannel:
    """One directed device edge between two members of an XLA collective
    gang (ray_tpu.util.collective, backend="xla").

    Every rank of the group participates in the underlying permute (SPMD
    collectives are group-wide); use dedicated 2-member groups per edge —
    the natural shape for pipeline-stage handoffs — so a transfer only
    synchronizes its endpoints.
    """

    def __init__(self, group_name: str, src_rank: int, dst_rank: int,
                 shape: Tuple[int, ...], dtype: Any):
        if src_rank == dst_rank:
            raise ValueError("device channel endpoints must differ")
        self.group_name = group_name
        self.src_rank = src_rank
        self.dst_rank = dst_rank
        self.shape = tuple(shape)
        self.dtype = np.dtype(dtype)

    def _permute(self, contribution):
        from ray_tpu.util import collective as col

        return col.permute(contribution,
                           [(self.src_rank, self.dst_rank)],
                           group_name=self.group_name)

    def write(self, arr) -> None:
        """Producer half: contribute the payload. Blocks until the
        consumer enters its matching read (collective semantics — this IS
        the channel's backpressure)."""
        import jax.numpy as jnp

        arr = jnp.asarray(arr, self.dtype)
        if tuple(arr.shape) != self.shape:
            raise ValueError(
                f"device channel is typed {self.shape}/{self.dtype}; "
                f"got {tuple(arr.shape)}/{arr.dtype}")
        self._permute(arr)

    def read(self):
        """Consumer half: contribute zeros, receive the producer's
        payload as a device array."""
        import jax.numpy as jnp

        out = self._permute(jnp.zeros(self.shape, self.dtype))
        return out


__all__ = ["DeviceChannel"]
