"""@ray_tpu.remote actor classes.

Capability parity with the reference's actor surface (reference:
python/ray/actor.py:1545 ActorClass, :1875 ActorClass._remote, :2266
ActorHandle, :848 ActorMethod): `.remote()` registers the actor with the
control store which schedules and instantiates it on a node; handles submit
ordered method tasks directly to the actor's worker; handles pickle by actor
id and rebind through the control store on the receiving side.
"""

from __future__ import annotations

import hashlib
import weakref
from typing import Any, Dict, Optional

from ray_tpu._private.core_worker import get_core_worker
from ray_tpu._private.ids import ActorID
from ray_tpu.remote_function import build_resources, build_strategy

_VALID_ACTOR_OPTIONS = {
    "num_cpus", "num_tpus", "resources", "max_restarts", "max_task_retries",
    "max_concurrency", "name", "namespace", "lifetime", "scheduling_strategy",
    "label_selector", "placement_group", "placement_group_bundle_index",
    "runtime_env", "concurrency_groups", "drain_cooperative",
}

_VALID_METHOD_OPTIONS = {"num_returns", "concurrency_group"}


def method(**opts):
    """Per-method options decorator (reference: ray.method, actor.py:848) —
    ``@ray_tpu.method(concurrency_group="io", num_returns=2)``."""
    for k in opts:
        if k not in _VALID_METHOD_OPTIONS:
            raise ValueError(f"invalid @method option {k!r}")

    def decorate(fn):
        fn.__rt_method_opts__ = dict(opts)
        return fn

    return decorate


class ActorMethod:
    __slots__ = ("_handle", "_method_name", "_num_returns", "_concurrency_group")

    def __init__(self, handle: "ActorHandle", method_name: str,
                 num_returns: int = 1, concurrency_group: str = ""):
        self._handle = handle
        self._method_name = method_name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def remote(self, *args, **kwargs):
        return self._handle._submit(
            self._method_name, args, kwargs, num_returns=self._num_returns,
            concurrency_group=self._concurrency_group,
        )

    def options(self, num_returns: Optional[int] = None,
                concurrency_group: Optional[str] = None) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._method_name,
            self._num_returns if num_returns is None else num_returns,
            self._concurrency_group if concurrency_group is None
            else concurrency_group,
        )

    def bind(self, *args, **kwargs):
        """Build a DAG node instead of executing (reference: actor.py bind —
        the ray.dag authoring surface)."""
        from ray_tpu.dag import ClassMethodNode

        return ClassMethodNode(self._handle, self._method_name, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor method {self._method_name} cannot be called directly; use .remote()"
        )


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_key: str, method_meta: Optional[dict],
                 max_task_retries: int = 0, concurrent: bool = False,
                 _owned: bool = False):
        self._actor_id = actor_id
        self._class_key = class_key
        self._method_meta = method_meta or {}
        self._max_task_retries = max_task_retries
        # async/threaded/concurrency-group actor: executions overlap, so
        # pushes bypass reply batching (see CoreWorker._actor_push)
        self._concurrent = concurrent
        self._owned = _owned
        if _owned:
            cw = get_core_worker()
            cw.add_actor_handle_ref(actor_id.binary())
            # Pin the session that holds the refcount: a handle GC'd late
            # (cycle collector) after shutdown()+init() must not decrement
            # a colliding actor id in the NEW session's core worker.
            self._owner_cw = weakref.ref(cw)

    def __del__(self):
        if getattr(self, "_owned", False):
            try:
                cw = self._owner_cw()
                if cw is not None and cw is get_core_worker():
                    cw.remove_actor_handle_ref(self._actor_id.binary())
            except Exception:  # noqa: BLE001 — interpreter shutdown
                pass

    def _submit(self, method_name: str, args, kwargs, num_returns: int = 1,
                concurrency_group: str = ""):
        from ray_tpu._private.protocol import NUM_RETURNS_STREAMING

        cw = get_core_worker()
        streaming = num_returns == "streaming"
        wire_returns = NUM_RETURNS_STREAMING if streaming else num_returns
        # non-blocking from every context: seq assignment happens on the
        # calling thread (ordering decided here), serialization + delivery
        # continue on the event loop. A per-call blocking loop hop would
        # cap pipelined submission at the thread-handoff rate.
        result = cw.submit_actor_task_nowait(
            self._actor_id.binary(), method_name, args, kwargs,
            num_returns=wire_returns,
            max_task_retries=self._max_task_retries,
            concurrency_group=concurrency_group,
            concurrent=self._concurrent,
        )
        if streaming:
            return result
        return result[0] if num_returns == 1 else result

    def __getattr__(self, name: str):
        if name.startswith("_") and name != "__rt_call__":
            raise AttributeError(name)
        meta = self._method_meta.get(name)
        if isinstance(meta, int):  # legacy form: bare num_returns
            meta = {"num_returns": meta}
        meta = meta or {}
        return ActorMethod(
            self, name, meta.get("num_returns", 1),
            meta.get("concurrency_group", ""),
        )

    def __repr__(self):
        return f"ActorHandle({self._actor_id.hex()[:16]})"

    def __reduce__(self):
        return (
            ActorHandle,
            (self._actor_id, self._class_key, self._method_meta,
             self._max_task_retries, self._concurrent),
        )

    def _actor_info(self) -> dict:
        cw = get_core_worker()
        return cw.run_sync(
            cw.control.call("get_actor_info", {"actor_id": self._actor_id.binary()})
        )["actor"]


class ActorClass:
    def __init__(self, cls: type, options: Optional[Dict[str, Any]] = None):
        self._cls = cls
        self._options = dict(options or {})
        for k in self._options:
            if k not in _VALID_ACTOR_OPTIONS:
                raise ValueError(f"invalid actor @remote option {k!r}")
        h = hashlib.blake2b(digest_size=8)
        h.update(cls.__module__.encode() if cls.__module__ else b"")
        h.update(cls.__qualname__.encode())
        for attr in sorted(vars(cls)):
            fn = vars(cls)[attr]
            if callable(fn) and hasattr(fn, "__code__"):
                h.update(fn.__code__.co_code)
        self._class_key = f"actor:{cls.__qualname__}:{h.hexdigest()}"

    def options(self, **overrides) -> "ActorClass":
        for k in overrides:
            if k not in _VALID_ACTOR_OPTIONS:
                raise ValueError(f"invalid options() key {k!r}")
        clone = ActorClass.__new__(ActorClass)
        clone._cls = self._cls
        clone._options = {**self._options, **overrides}
        clone._class_key = self._class_key
        return clone

    def _method_meta(self) -> Dict[str, dict]:
        """Collect @ray_tpu.method options declared on the class, walking
        the MRO so base-class declarations apply to subclass actors
        (subclass overrides win)."""
        meta: Dict[str, dict] = {}
        for klass in reversed(self._cls.__mro__):
            for attr, fn in vars(klass).items():
                mopts = getattr(fn, "__rt_method_opts__", None)
                if mopts:
                    meta[attr] = dict(mopts)
        return meta

    def remote(self, *args, **kwargs) -> ActorHandle:
        cw = get_core_worker()
        opts = self._options
        is_async = _is_async_actor(self._cls)
        method_meta = self._method_meta()
        groups = dict(opts.get("concurrency_groups") or {})
        for mname, mopts in method_meta.items():
            g = mopts.get("concurrency_group")
            if g and g not in groups:
                raise ValueError(
                    f"method {mname!r} uses undeclared concurrency group {g!r}"
                    f" (declare it via concurrency_groups={{...}})"
                )

        async def create():
            await cw.export_function(self._class_key, self._cls)
            return await cw.create_actor(
                self._class_key,
                args,
                kwargs,
                resources=build_resources(opts),
                max_restarts=opts.get("max_restarts", 0),
                max_task_retries=opts.get("max_task_retries", 0),
                max_concurrency=opts.get(
                    "max_concurrency", 1000 if is_async else 1
                ),
                is_async=is_async,
                strategy=build_strategy(opts),
                name=opts.get("name", ""),
                namespace=opts.get("namespace", ""),
                detached=opts.get("lifetime") == "detached",
                runtime_env=opts.get("runtime_env"),
                concurrency_groups=groups,
                method_meta=method_meta,
                drain_cooperative=opts.get("drain_cooperative", False),
            )

        if cw._loop_running_here():
            # inside an async actor: non-blocking creation
            actor_id = cw.create_actor_nowait(
                self._cls, self._class_key, args, kwargs,
                resources=build_resources(opts),
                max_restarts=opts.get("max_restarts", 0),
                max_task_retries=opts.get("max_task_retries", 0),
                max_concurrency=opts.get("max_concurrency", 1000 if is_async else 1),
                is_async=is_async,
                strategy=build_strategy(opts),
                name=opts.get("name", ""),
                namespace=opts.get("namespace", ""),
                detached=opts.get("lifetime") == "detached",
                runtime_env=opts.get("runtime_env"),
                concurrency_groups=groups,
                method_meta=method_meta,
                drain_cooperative=opts.get("drain_cooperative", False),
            )
        else:
            actor_id = cw.run_sync(create())
        # Unnamed, non-detached actors are GC'd with the creator's last handle.
        owned = not opts.get("name") and opts.get("lifetime") != "detached"
        concurrent = bool(
            is_async or opts.get("max_concurrency", 0) > 1 or groups)
        return ActorHandle(
            actor_id, self._class_key, method_meta,
            max_task_retries=opts.get("max_task_retries", 0),
            concurrent=concurrent,
            _owned=owned,
        )

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class {self._cls.__name__} cannot be instantiated directly; "
            f"use .remote()"
        )


def _is_async_actor(cls: type) -> bool:
    import inspect

    for attr in dir(cls):
        if attr.startswith("__"):
            continue
        if inspect.iscoroutinefunction(getattr(cls, attr, None)):
            return True
    return False
