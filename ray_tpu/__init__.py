"""ray_tpu — a TPU-native distributed compute framework.

The capability surface of Ray (tasks, actors, objects, placement groups,
libraries) re-designed TPU-first: the data plane is JAX/XLA over ICI meshes,
the control plane is an asyncio msgpack RPC fabric with a shared-memory object
store, and gang scheduling is slice-topology native.

Public API parity reference: python/ray/__init__.py of the reference.
"""

from ray_tpu._private.core_worker import (
    ObjectRef,
    ObjectRefGenerator,
    get_core_worker,
)
from ray_tpu._private.errors import (
    ActorDiedError,
    ActorUnavailableError,
    GetTimeoutError,
    ObjectLostError,
    ObjectStoreFullError,
    RayTpuError,
    TaskCancelledError,
    TaskError,
    WorkerCrashedError,
)
from ray_tpu._private.worker import (
    available_resources,
    cancel,
    cluster_resources,
    get,
    get_actor,
    init,
    is_initialized,
    kill,
    nodes,
    put,
    shutdown,
    wait,
)


def remote(*args, **kwargs):
    """`@ray_tpu.remote` decorator for functions and actor classes.

    Reference: python/ray/remote_function.py:347 and python/ray/actor.py:1545.
    """
    import inspect

    from ray_tpu.actor import ActorClass
    from ray_tpu.remote_function import RemoteFunction

    def decorate(target, options=None):
        if inspect.isclass(target):
            return ActorClass(target, options)
        if not callable(target):
            raise TypeError("@remote requires a function or class")
        return RemoteFunction(target, options)

    if len(args) == 1 and not kwargs and (inspect.isclass(args[0]) or callable(args[0])):
        return decorate(args[0])
    if args:
        raise TypeError("@remote() accepts only keyword options")

    def wrapper(target):
        return decorate(target, kwargs)

    return wrapper


def method(**opts):
    """Per-actor-method options (reference: ray.method) — e.g.
    ``@ray_tpu.method(concurrency_group="io", num_returns=2)``."""
    from ray_tpu.actor import method as _method

    return _method(**opts)


__version__ = "0.1.0"

__all__ = [
    "ObjectRef",
    "ObjectRefGenerator",
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "method",
    "get",
    "put",
    "wait",
    "cancel",
    "kill",
    "TaskCancelledError",
    "get_actor",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_core_worker",
    "RayTpuError",
    "TaskError",
    "ActorDiedError",
    "ActorUnavailableError",
    "WorkerCrashedError",
    "ObjectLostError",
    "ObjectStoreFullError",
    "GetTimeoutError",
    "__version__",
]
