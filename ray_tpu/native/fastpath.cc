// Native control-plane fast path: the C++ submission/completion engine.
//
// Capability parity with the reference's compiled submission seam
// (reference: python/ray/_raylet.pyx:3817 submit_task — every .remote()
// crosses into C++ there, which is how the reference sustains a 1M+-queued
// single-node envelope). This framework's pure-Python submit path tops out
// two orders of magnitude lower; this engine owns the three hot inner loops:
//
//   (a) SPEC ENCODING — a TaskSpec-shaped dict is serialized into the wire
//       msgpack format in C++. Repeated byte strings (map keys, function
//       descriptors, owner addresses, resource/strategy sub-maps) are
//       interned ONCE as pre-encoded msgpack fragments: a registered
//       "template" is the full wire map split around its two per-task
//       fields (task_id, args), so encoding one spec is three memcpys plus
//       two headers instead of a 25-key dict walk in the interpreter.
//   (b) SUBMISSION RING — encoded specs enter a lock-free bounded MPMC ring
//       (Vyukov sequence-number scheme) straight from the caller thread;
//       no event-loop hop, no allocation beyond the entry itself. Feeder
//       coroutines pop in batches.
//   (c) BATCHED FRAMES — a popped batch is assembled into ONE complete
//       length-prefixed RPC frame ([_REQ, req_id, "push_task_batch",
//       {"specs": [...]}]) in a single buffer handed to the asyncio sender
//       as one write. On the completion side, a stream SPLITTER carves the
//       raw TCP bytes into frames and pre-parses each header (kind,
//       req_id, method) so Python resolves a whole chunk of futures per
//       read() instead of one coroutine iteration per reply.
//
// Loaded via ctypes (see _private/fastpath.py) like the sibling shm_store /
// shm_channel libraries; when the toolchain is missing the Python path runs
// unchanged (config flag `native_fastpath`).

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <mutex>

namespace {

constexpr int32_t kMaxRings = 256;
constexpr int32_t kMaxTemplates = 4096;
constexpr uint32_t kMaxTidLen = 32;
constexpr uint64_t kMaxFrame = 512ULL * 1024 * 1024;  // matches rpc.MAX_FRAME

// ---------------------------------------------------------------------------
// msgpack emit helpers (writer side only needs a tiny subset)
// ---------------------------------------------------------------------------

inline uint64_t uint_size(uint64_t v) {
  if (v < 0x80) return 1;
  if (v <= 0xff) return 2;
  if (v <= 0xffff) return 3;
  if (v <= 0xffffffffULL) return 5;
  return 9;
}

inline uint8_t* emit_uint(uint8_t* p, uint64_t v) {
  if (v < 0x80) {
    *p++ = static_cast<uint8_t>(v);
  } else if (v <= 0xff) {
    *p++ = 0xcc;
    *p++ = static_cast<uint8_t>(v);
  } else if (v <= 0xffff) {
    *p++ = 0xcd;
    *p++ = static_cast<uint8_t>(v >> 8);
    *p++ = static_cast<uint8_t>(v);
  } else if (v <= 0xffffffffULL) {
    *p++ = 0xce;
    for (int s = 24; s >= 0; s -= 8) *p++ = static_cast<uint8_t>(v >> s);
  } else {
    *p++ = 0xcf;
    for (int s = 56; s >= 0; s -= 8) *p++ = static_cast<uint8_t>(v >> s);
  }
  return p;
}

inline uint64_t array_hdr_size(uint32_t n) {
  if (n < 16) return 1;
  if (n <= 0xffff) return 3;
  return 5;
}

inline uint8_t* emit_array_hdr(uint8_t* p, uint32_t n) {
  if (n < 16) {
    *p++ = 0x90 | static_cast<uint8_t>(n);
  } else if (n <= 0xffff) {
    *p++ = 0xdc;
    *p++ = static_cast<uint8_t>(n >> 8);
    *p++ = static_cast<uint8_t>(n);
  } else {
    *p++ = 0xdd;
    for (int s = 24; s >= 0; s -= 8) *p++ = static_cast<uint8_t>(n >> s);
  }
  return p;
}

// bin8 header (task ids are <= 32 bytes)
inline uint8_t* emit_bin8(uint8_t* p, const uint8_t* data, uint32_t len) {
  *p++ = 0xc4;
  *p++ = static_cast<uint8_t>(len);
  memcpy(p, data, len);
  return p + len;
}

// ---------------------------------------------------------------------------
// entries and the Vyukov bounded MPMC ring
// ---------------------------------------------------------------------------

inline uint64_t mono_ns() {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<uint64_t>(ts.tv_sec) * 1000000000ULL +
         static_cast<uint64_t>(ts.tv_nsec);
}

struct FpEntry {
  uint32_t tid_len;
  uint8_t tid[kMaxTidLen];
  uint64_t len;     // encoded spec bytes
  uint64_t enq_ns;  // CLOCK_MONOTONIC stamp at ring enqueue (the per-hop
                    // telemetry's ring_wait hop; ~20ns per encode, cheap
                    // enough to stamp unconditionally)
  // spec bytes follow inline
  uint8_t* data() { return reinterpret_cast<uint8_t*>(this + 1); }
};

struct Cell {
  std::atomic<uint64_t> seq;
  FpEntry* ent;
};

struct Ring {
  Cell* cells;
  uint64_t mask;
  alignas(64) std::atomic<uint64_t> enqueue_pos;
  alignas(64) std::atomic<uint64_t> dequeue_pos;

  explicit Ring(uint64_t slots) {
    // round up to a power of two
    uint64_t cap = 1;
    while (cap < slots) cap <<= 1;
    cells = static_cast<Cell*>(calloc(cap, sizeof(Cell)));
    mask = cap - 1;
    // tsan: relaxed init stores — single-threaded constructor; the Ring is
    // published to other threads only via nrings.store(release) in
    // rt_fp_ring_create, which orders all of these before any reader.
    for (uint64_t i = 0; i < cap; i++)
      cells[i].seq.store(i, std::memory_order_relaxed);
    enqueue_pos.store(0, std::memory_order_relaxed);
    dequeue_pos.store(0, std::memory_order_relaxed);
  }
  ~Ring() { free(cells); }

  // Vyukov bounded MPMC: the cell's `seq` is the only synchronization edge
  // for the payload. Positions are mere tickets — a stale read just retries.
  bool push(FpEntry* e) {
    Cell* cell;
    // tsan: relaxed — enqueue_pos is a ticket counter, not a publication
    // point; a stale value fails the seq check below and reloads.
    uint64_t pos = enqueue_pos.load(std::memory_order_relaxed);
    for (;;) {
      cell = &cells[pos & mask];
      // acquire pairs with the consumer's seq.store(release) in pop():
      // seeing seq==pos proves the previous occupant's payload read is done.
      uint64_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif = static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos);
      if (dif == 0) {
        // tsan: relaxed CAS — winning only claims the ticket; the payload
        // publication below rides cell->seq.store(release), not this CAS.
        if (enqueue_pos.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed))
          break;
      } else if (dif < 0) {
        return false;  // full
      } else {
        // tsan: relaxed — refresh the ticket after losing a race; validated
        // by the next acquire load of cell->seq.
        pos = enqueue_pos.load(std::memory_order_relaxed);
      }
    }
    cell->ent = e;
    // release publishes cell->ent to the consumer's acquire load of seq.
    cell->seq.store(pos + 1, std::memory_order_release);
    return true;
  }

  FpEntry* pop() {
    Cell* cell;
    // tsan: relaxed — dequeue_pos is a ticket counter (see push()).
    uint64_t pos = dequeue_pos.load(std::memory_order_relaxed);
    for (;;) {
      cell = &cells[pos & mask];
      // acquire pairs with the producer's seq.store(pos+1, release): seeing
      // seq==pos+1 makes the cell->ent write below visible to this thread.
      uint64_t seq = cell->seq.load(std::memory_order_acquire);
      intptr_t dif =
          static_cast<intptr_t>(seq) - static_cast<intptr_t>(pos + 1);
      if (dif == 0) {
        // tsan: relaxed CAS — claims the ticket only; the payload was
        // already acquired via cell->seq above.
        if (dequeue_pos.compare_exchange_weak(pos, pos + 1,
                                              std::memory_order_relaxed))
          break;
      } else if (dif < 0) {
        return nullptr;  // empty
      } else {
        // tsan: relaxed — ticket refresh after a lost race (see push()).
        pos = dequeue_pos.load(std::memory_order_relaxed);
      }
    }
    FpEntry* e = cell->ent;
    // release hands the cell back to a producer one lap ahead: pairs with
    // push()'s acquire load and orders our cell->ent read before reuse.
    cell->seq.store(pos + mask + 1, std::memory_order_release);
    return e;
  }

  uint64_t approx_len() {
    uint64_t e = enqueue_pos.load(std::memory_order_acquire);
    uint64_t d = dequeue_pos.load(std::memory_order_acquire);
    return e > d ? e - d : 0;
  }
};

// a template is the wire spec map split around (task_id, args)
struct Template {
  uint8_t *pre, *mid, *suf;
  uint64_t pre_len, mid_len, suf_len;
};

struct Engine {
  std::mutex reg_mu;  // ring/template registration only (cold path)
  Ring* rings[kMaxRings];
  Template templates[kMaxTemplates];
  std::atomic<int32_t> nrings{0};
  std::atomic<int32_t> ntemplates{0};
  uint64_t ring_slots;
};

uint8_t* dup_bytes(const uint8_t* p, uint64_t n) {
  uint8_t* out = static_cast<uint8_t*>(malloc(n ? n : 1));
  if (out && n) memcpy(out, p, n);
  return out;
}

}  // namespace

extern "C" {

int32_t rt_fp_abi_version() { return 2; }

void* rt_fp_engine_create(uint64_t ring_slots) {
  Engine* e = new Engine();
  e->ring_slots = ring_slots ? ring_slots : 65536;
  return e;
}

void rt_fp_engine_destroy(void* h) {
  Engine* e = static_cast<Engine*>(h);
  int32_t nr = e->nrings.load(std::memory_order_acquire);
  for (int32_t i = 0; i < nr; i++) {
    for (FpEntry* ent = e->rings[i]->pop(); ent; ent = e->rings[i]->pop())
      free(ent);
    delete e->rings[i];
  }
  int32_t nt = e->ntemplates.load(std::memory_order_acquire);
  for (int32_t i = 0; i < nt; i++) {
    free(e->templates[i].pre);
    free(e->templates[i].mid);
    free(e->templates[i].suf);
  }
  delete e;
}

int32_t rt_fp_ring_create(void* h) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->reg_mu);
  // tsan: relaxed — only registrars mutate nrings and they serialize on
  // reg_mu; concurrent readers use the acquire load at the call sites.
  int32_t id = e->nrings.load(std::memory_order_relaxed);
  if (id >= kMaxRings) return -1;
  e->rings[id] = new Ring(e->ring_slots);
  // release publishes rings[id] (and the Ring's relaxed init) to readers'
  // acquire loads of nrings.
  e->nrings.store(id + 1, std::memory_order_release);
  return id;
}

int32_t rt_fp_template_register(void* h, const uint8_t* pre, uint64_t pre_len,
                                const uint8_t* mid, uint64_t mid_len,
                                const uint8_t* suf, uint64_t suf_len) {
  Engine* e = static_cast<Engine*>(h);
  std::lock_guard<std::mutex> g(e->reg_mu);
  // tsan: relaxed — writers serialize on reg_mu (see rt_fp_ring_create).
  int32_t id = e->ntemplates.load(std::memory_order_relaxed);
  if (id >= kMaxTemplates) return -1;
  Template& t = e->templates[id];
  t.pre = dup_bytes(pre, pre_len);
  t.mid = dup_bytes(mid, mid_len);
  t.suf = dup_bytes(suf, suf_len);
  t.pre_len = pre_len;
  t.mid_len = mid_len;
  t.suf_len = suf_len;
  e->ntemplates.store(id + 1, std::memory_order_release);
  return id;
}

// Encode one spec from a template + the two per-task fields and push it onto
// `ring`. `args` is a complete pre-encoded msgpack value (the wire args
// list). Returns 0, -1 if the ring is full, -2 on a bad id.
int32_t rt_fp_encode(void* h, int32_t ring, int32_t tmpl, const uint8_t* tid,
                     uint32_t tid_len, const uint8_t* args,
                     uint64_t args_len) {
  Engine* e = static_cast<Engine*>(h);
  if (ring < 0 || ring >= e->nrings.load(std::memory_order_acquire) ||
      tmpl < 0 || tmpl >= e->ntemplates.load(std::memory_order_acquire) ||
      tid_len > kMaxTidLen)
    return -2;
  const Template& t = e->templates[tmpl];
  uint64_t spec_len =
      t.pre_len + 2 + tid_len + t.mid_len + args_len + t.suf_len;
  FpEntry* ent =
      static_cast<FpEntry*>(malloc(sizeof(FpEntry) + spec_len));
  if (!ent) return -1;
  ent->tid_len = tid_len;
  memcpy(ent->tid, tid, tid_len);
  ent->len = spec_len;
  ent->enq_ns = mono_ns();
  uint8_t* p = ent->data();
  memcpy(p, t.pre, t.pre_len);
  p += t.pre_len;
  p = emit_bin8(p, tid, tid_len);
  memcpy(p, t.mid, t.mid_len);
  p += t.mid_len;
  memcpy(p, args, args_len);
  p += args_len;
  memcpy(p, t.suf, t.suf_len);
  if (!e->rings[ring]->push(ent)) {
    free(ent);
    return -1;
  }
  return 0;
}

// Push an already fully-encoded wire spec (the fallback for shapes with no
// registered template, and for retries re-entering the ring).
int32_t rt_fp_encode_raw(void* h, int32_t ring, const uint8_t* tid,
                         uint32_t tid_len, const uint8_t* spec,
                         uint64_t spec_len) {
  Engine* e = static_cast<Engine*>(h);
  if (ring < 0 || ring >= e->nrings.load(std::memory_order_acquire) ||
      tid_len > kMaxTidLen)
    return -2;
  FpEntry* ent =
      static_cast<FpEntry*>(malloc(sizeof(FpEntry) + spec_len));
  if (!ent) return -1;
  ent->tid_len = tid_len;
  memcpy(ent->tid, tid, tid_len);
  ent->len = spec_len;
  ent->enq_ns = mono_ns();
  memcpy(ent->data(), spec, spec_len);
  if (!e->rings[ring]->push(ent)) {
    free(ent);
    return -1;
  }
  return 0;
}

uint64_t rt_fp_ring_len(void* h, int32_t ring) {
  Engine* e = static_cast<Engine*>(h);
  if (ring < 0 || ring >= e->nrings.load(std::memory_order_acquire)) return 0;
  return e->rings[ring]->approx_len();
}

// Pop up to `max_n` entries. Fills `out_handles` (opaque entry pointers the
// caller now owns), `out_tids` (max_n slots of [1-byte len][kMaxTidLen
// bytes]) and `out_wait_ns` (per-entry ring residency: now − enqueue stamp —
// the ring_wait hop of the latency decomposition). Returns the number
// popped.
int32_t rt_fp_pop(void* h, int32_t ring, int32_t max_n, uint64_t* out_handles,
                  uint8_t* out_tids, uint64_t* out_wait_ns) {
  Engine* e = static_cast<Engine*>(h);
  if (ring < 0 || ring >= e->nrings.load(std::memory_order_acquire)) return 0;
  Ring* r = e->rings[ring];
  int32_t n = 0;
  uint64_t now = mono_ns();
  while (n < max_n) {
    FpEntry* ent = r->pop();
    if (!ent) break;
    out_handles[n] = reinterpret_cast<uint64_t>(ent);
    uint8_t* slot = out_tids + n * (1 + kMaxTidLen);
    slot[0] = static_cast<uint8_t>(ent->tid_len);
    memcpy(slot + 1, ent->tid, ent->tid_len);
    out_wait_ns[n] = now > ent->enq_ns ? now - ent->enq_ns : 0;
    n++;
  }
  return n;
}

void rt_fp_entry_free(uint64_t handle) {
  free(reinterpret_cast<FpEntry*>(handle));
}

// Total bytes of the complete frame rt_fp_batch_build would produce.
uint64_t rt_fp_batch_frame_size(const uint64_t* handles, int32_t n,
                                uint64_t req_id, const uint8_t* method,
                                uint32_t method_len) {
  uint64_t body = 1                  // fixarray(4)
                  + 1                // kind (_REQ = 0, positive fixint)
                  + uint_size(req_id)
                  + 1 + method_len   // fixstr header + bytes (len < 32)
                  + 1                // fixmap(1)
                  + 6                // fixstr "specs"
                  + array_hdr_size(static_cast<uint32_t>(n));
  for (int32_t i = 0; i < n; i++)
    body += reinterpret_cast<FpEntry*>(handles[i])->len;
  return 4 + body;  // u32 little-endian length prefix
}

// Build one complete RPC frame: [u32 len][msgpack [0, req_id, method,
// {"specs": [spec...]}]]. Frees every entry. Returns bytes written, or -1
// if `cap` is too small / the frame would exceed the transport limit (the
// entries are NOT freed in that case).
int64_t rt_fp_batch_build(const uint64_t* handles, int32_t n, uint64_t req_id,
                          const uint8_t* method, uint32_t method_len,
                          uint8_t* out, uint64_t cap) {
  if (method_len >= 32) return -1;
  uint64_t total = rt_fp_batch_frame_size(handles, n, req_id, method,
                                          method_len);
  if (total > cap || total - 4 > kMaxFrame) return -1;
  uint8_t* p = out;
  uint64_t body = total - 4;
  *p++ = static_cast<uint8_t>(body);
  *p++ = static_cast<uint8_t>(body >> 8);
  *p++ = static_cast<uint8_t>(body >> 16);
  *p++ = static_cast<uint8_t>(body >> 24);
  *p++ = 0x94;  // [kind, req_id, method, payload]
  *p++ = 0x00;  // _REQ
  p = emit_uint(p, req_id);
  *p++ = 0xa0 | static_cast<uint8_t>(method_len);
  memcpy(p, method, method_len);
  p += method_len;
  *p++ = 0x81;  // {"specs": [...]}
  *p++ = 0xa5;
  memcpy(p, "specs", 5);
  p += 5;
  p = emit_array_hdr(p, static_cast<uint32_t>(n));
  for (int32_t i = 0; i < n; i++) {
    FpEntry* ent = reinterpret_cast<FpEntry*>(handles[i]);
    memcpy(p, ent->data(), ent->len);
    p += ent->len;
    free(ent);
  }
  return static_cast<int64_t>(p - out);
}

// ---------------------------------------------------------------------------
// completion-side stream splitter
// ---------------------------------------------------------------------------

namespace {

struct Splitter {
  uint8_t* buf = nullptr;
  uint64_t cap = 0;
  uint64_t len = 0;  // valid bytes
  uint64_t rd = 0;   // consumed bytes
};

// minimal msgpack reads for the frame header [kind:int, req_id:int,
// method:str, payload:any]
bool parse_uint_at(const uint8_t* p, const uint8_t* end, uint64_t* val,
                   const uint8_t** next) {
  if (p >= end) return false;
  uint8_t b = *p++;
  if (b < 0x80) {
    *val = b;
  } else if (b == 0xcc) {
    if (p + 1 > end) return false;
    *val = *p++;
  } else if (b == 0xcd) {
    if (p + 2 > end) return false;
    *val = (static_cast<uint64_t>(p[0]) << 8) | p[1];
    p += 2;
  } else if (b == 0xce) {
    if (p + 4 > end) return false;
    *val = 0;
    for (int i = 0; i < 4; i++) *val = (*val << 8) | p[i];
    p += 4;
  } else if (b == 0xcf) {
    if (p + 8 > end) return false;
    *val = 0;
    for (int i = 0; i < 8; i++) *val = (*val << 8) | p[i];
    p += 8;
  } else {
    return false;
  }
  *next = p;
  return true;
}

bool parse_array_hdr_at(const uint8_t* p, const uint8_t* end, uint32_t* n,
                        const uint8_t** next) {
  if (p >= end) return false;
  uint8_t b = *p++;
  if ((b & 0xf0) == 0x90) {
    *n = b & 0x0f;
  } else if (b == 0xdc) {
    if (p + 2 > end) return false;
    *n = (static_cast<uint32_t>(p[0]) << 8) | p[1];
    p += 2;
  } else if (b == 0xdd) {
    if (p + 4 > end) return false;
    *n = 0;
    for (int i = 0; i < 4; i++) *n = (*n << 8) | p[i];
    p += 4;
  } else {
    return false;
  }
  *next = p;
  return true;
}

bool parse_str_at(const uint8_t* p, const uint8_t* end, uint64_t* off,
                  uint32_t* slen, const uint8_t* base, const uint8_t** next) {
  if (p >= end) return false;
  uint8_t b = *p++;
  uint32_t n;
  if ((b & 0xe0) == 0xa0) {
    n = b & 0x1f;
  } else if (b == 0xd9) {
    if (p + 1 > end) return false;
    n = *p++;
  } else if (b == 0xda) {
    if (p + 2 > end) return false;
    n = (static_cast<uint32_t>(p[0]) << 8) | p[1];
    p += 2;
  } else {
    return false;
  }
  if (p + n > end) return false;
  *off = static_cast<uint64_t>(p - base);
  *slen = n;
  *next = p + n;
  return true;
}

}  // namespace

void* rt_fp_splitter_create() { return new Splitter(); }

void rt_fp_splitter_destroy(void* h) {
  Splitter* s = static_cast<Splitter*>(h);
  free(s->buf);
  delete s;
}

// Append raw stream bytes. Returns 0 on success, -1 on allocation failure.
int32_t rt_fp_splitter_feed(void* h, const uint8_t* data, uint64_t n) {
  Splitter* s = static_cast<Splitter*>(h);
  // compact consumed prefix when it dominates the buffer
  if (s->rd == s->len) {
    s->rd = 0;
    s->len = 0;
  } else if (s->rd > (1 << 20) && s->rd > s->len / 2) {
    memmove(s->buf, s->buf + s->rd, s->len - s->rd);
    s->len -= s->rd;
    s->rd = 0;
  }
  if (s->len + n > s->cap) {
    uint64_t want = s->cap ? s->cap : 65536;
    while (want < s->len + n) want <<= 1;
    uint8_t* nb = static_cast<uint8_t*>(realloc(s->buf, want));
    if (!nb) return -1;
    s->buf = nb;
    s->cap = want;
  }
  memcpy(s->buf + s->len, data, n);
  s->len += n;
  return 0;
}

const uint8_t* rt_fp_splitter_base(void* h) {
  return static_cast<Splitter*>(h)->buf;
}

// Carve the next complete frame. Returns:
//   1  — a frame was produced; *frame_off/*frame_len cover the msgpack body
//        (length prefix stripped); if the header parsed, *kind/*req_id and
//        the method/payload spans are filled, else *kind = 0xffffffff and
//        the caller must unpack the whole body.
//   0  — need more bytes.
//  -1  — oversized frame (protocol violation; caller should drop the
//        connection, matching MAX_FRAME on the Python side).
// Offsets are relative to rt_fp_splitter_base() and remain valid until the
// next feed() call.
int32_t rt_fp_splitter_next(void* h, uint64_t* frame_off, uint64_t* frame_len,
                            uint32_t* kind, uint64_t* req_id,
                            uint64_t* method_off, uint32_t* method_len,
                            uint64_t* payload_off, uint64_t* payload_len) {
  Splitter* s = static_cast<Splitter*>(h);
  if (s->len - s->rd < 4) return 0;
  const uint8_t* p = s->buf + s->rd;
  uint64_t body = static_cast<uint64_t>(p[0]) |
                  (static_cast<uint64_t>(p[1]) << 8) |
                  (static_cast<uint64_t>(p[2]) << 16) |
                  (static_cast<uint64_t>(p[3]) << 24);
  if (body > kMaxFrame) return -1;
  if (s->len - s->rd - 4 < body) return 0;
  const uint8_t* start = p + 4;
  const uint8_t* end = start + body;
  *frame_off = static_cast<uint64_t>(start - s->buf);
  *frame_len = body;
  s->rd += 4 + body;

  // best-effort header pre-parse; any surprise defers to Python's unpacker
  *kind = 0xffffffffu;
  uint32_t nelem;
  const uint8_t* q = start;
  uint64_t k, rid;
  uint32_t mlen;
  uint64_t moff;
  if (!parse_array_hdr_at(q, end, &nelem, &q) || nelem != 4) return 1;
  if (!parse_uint_at(q, end, &k, &q)) return 1;
  if (!parse_uint_at(q, end, &rid, &q)) return 1;
  if (!parse_str_at(q, end, &moff, &mlen, s->buf, &q)) return 1;
  *kind = static_cast<uint32_t>(k);
  *req_id = rid;
  *method_off = moff;
  *method_len = mlen;
  *payload_off = static_cast<uint64_t>(q - s->buf);
  *payload_len = static_cast<uint64_t>(end - q);
  return 1;
}

}  // extern "C"
