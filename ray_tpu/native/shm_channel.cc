// Shared-memory SPSC channels: the compiled-graph data plane.
//
// Capability parity with the reference's preallocated mutable-plasma channels
// (reference: python/ray/experimental/channel/shared_memory_channel.py backed
// by src/ray/core_worker/experimental_mutable_object_manager.h), redesigned
// for this framework's serverless shm store: a channel is ONE sealed store
// object whose payload holds [Header | Slot0 | Slot1 | ...]; producer and
// consumer processes both map the segment and synchronize through C++11
// atomics on the header — no RPC, no task submission, no allocation on the
// hot path. Single-producer single-consumer ring (a compiled DAG edge has
// exactly one writer and one reader); capacity doubles as pipeline
// backpressure (reference bounds in-flight executions via channel buffers
// the same way).
//
// The API is zero-copy on both sides: the writer reserves a slot pointer and
// commits with a length; the reader acquires the slot pointer and releases it
// after deserializing. memory_order_release on publish / acquire on consume
// pairs make the payload bytes visible before the sequence number.

#include <atomic>
#include <cstdint>
#include <cstring>
#include <new>

namespace {

constexpr uint64_t kChanMagic = 0x52544348414E0001ULL;  // "RTCHAN" v1

struct ChannelHeader {
  uint64_t magic;
  uint64_t nslots;
  uint64_t slot_size;           // payload bytes per slot
  std::atomic<uint64_t> write_seq;  // slots produced
  std::atomic<uint64_t> read_seq;   // slots consumed
  std::atomic<uint64_t> closed;     // writer hung up (reader sees EOF)
};

struct Slot {
  uint64_t len;
  // payload follows
};

inline uint64_t slot_stride(uint64_t slot_size) {
  return sizeof(Slot) + ((slot_size + 63) & ~63ULL);  // 64B-align payloads
}

inline Slot* slot_at(ChannelHeader* h, uint64_t idx) {
  auto* base = reinterpret_cast<uint8_t*>(h) + sizeof(ChannelHeader);
  return reinterpret_cast<Slot*>(base +
                                 (idx % h->nslots) * slot_stride(h->slot_size));
}

}  // namespace

extern "C" {

uint64_t rt_chan_required_size(uint64_t nslots, uint64_t slot_size) {
  return sizeof(ChannelHeader) + nslots * slot_stride(slot_size);
}

int rt_chan_init(void* base, uint64_t region_size, uint64_t nslots,
                 uint64_t slot_size) {
  if (region_size < rt_chan_required_size(nslots, slot_size)) return -1;
  auto* h = new (base) ChannelHeader();
  h->magic = kChanMagic;
  h->nslots = nslots;
  h->slot_size = slot_size;
  h->write_seq.store(0, std::memory_order_relaxed);
  h->read_seq.store(0, std::memory_order_relaxed);
  h->closed.store(0, std::memory_order_relaxed);
  return 0;
}

int rt_chan_validate(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  return h->magic == kChanMagic ? 0 : -1;
}

// Writer side. rt_chan_reserve returns the offset (from base) of the slot
// payload to write into, or -1 if the ring is full (backpressure).
int64_t rt_chan_reserve(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  uint64_t r = h->read_seq.load(std::memory_order_acquire);
  if (w - r >= h->nslots) return -1;  // full
  auto* s = slot_at(h, w);
  return reinterpret_cast<uint8_t*>(s) + sizeof(Slot) -
         reinterpret_cast<uint8_t*>(base);
}

int rt_chan_commit(void* base, uint64_t len) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  if (len > h->slot_size) return -2;
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  slot_at(h, w)->len = len;
  h->write_seq.store(w + 1, std::memory_order_release);
  return 0;
}

// Reader side. rt_chan_acquire returns the payload offset and length of the
// next unread slot, or -1 if empty, -2 if empty AND closed (EOF).
int64_t rt_chan_acquire(void* base, uint64_t* out_len) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  uint64_t w = h->write_seq.load(std::memory_order_acquire);
  if (r == w) {
    return h->closed.load(std::memory_order_acquire) ? -2 : -1;
  }
  auto* s = slot_at(h, r);
  *out_len = s->len;
  return reinterpret_cast<uint8_t*>(s) + sizeof(Slot) -
         reinterpret_cast<uint8_t*>(base);
}

int rt_chan_release(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  h->read_seq.store(r + 1, std::memory_order_release);
  return 0;
}

void rt_chan_close(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  h->closed.store(1, std::memory_order_release);
}

uint64_t rt_chan_readable(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  return h->write_seq.load(std::memory_order_acquire) -
         h->read_seq.load(std::memory_order_acquire);
}

uint64_t rt_chan_slot_size(void* base) {
  return reinterpret_cast<ChannelHeader*>(base)->slot_size;
}

}  // extern "C"
