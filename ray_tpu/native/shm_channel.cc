// Shared-memory SPSC channels: the compiled-graph data plane.
//
// Capability parity with the reference's preallocated mutable-plasma channels
// (reference: python/ray/experimental/channel/shared_memory_channel.py backed
// by src/ray/core_worker/experimental_mutable_object_manager.h), redesigned
// for this framework's serverless shm store: a channel is ONE sealed store
// object whose payload holds [Header | Slot0 | Slot1 | ...]; producer and
// consumer processes both map the segment and synchronize through C++11
// atomics on the header — no RPC, no task submission, no allocation on the
// hot path. Single-producer single-consumer ring (a compiled DAG edge has
// exactly one writer and one reader); capacity doubles as pipeline
// backpressure (reference bounds in-flight executions via channel buffers
// the same way).
//
// The API is zero-copy on both sides: the writer reserves a slot pointer and
// commits with a length; the reader acquires the slot pointer and releases it
// after deserializing. memory_order_release on publish / acquire on consume
// pairs make the payload bytes visible before the sequence number.
//
// Blocking waits ride a FUTEX DOORBELL in the shared header instead of
// sleep-polling (reference: its channels block on OS primitives —
// shared_memory_channel.py reads park in plasma): commit/close ring
// `write_ding`, release rings `read_ding`, and a blocked peer FUTEX_WAITs on
// the ding word. Wakes are issued only when the waiter count is nonzero, so
// the uncontended hot path stays syscall-free. An idle compiled-DAG executor
// parked in rt_chan_wait_readable costs zero CPU.

#include <atomic>
#include <cerrno>
#include <climits>
#include <cstdint>
#include <cstring>
#include <ctime>
#include <new>

#include <linux/futex.h>
#include <sys/syscall.h>
#include <unistd.h>

namespace {

constexpr uint64_t kChanMagic = 0x52544348414E0002ULL;  // "RTCHAN" v2 (futex)

struct ChannelHeader {
  uint64_t magic;
  uint64_t nslots;
  uint64_t slot_size;           // payload bytes per slot
  std::atomic<uint64_t> write_seq;  // slots produced
  std::atomic<uint64_t> read_seq;   // slots consumed
  std::atomic<uint64_t> closed;     // writer hung up (reader sees EOF)
  // doorbells (32-bit: futex words must be 4 bytes)
  std::atomic<uint32_t> write_ding;     // bumped on commit/close
  std::atomic<uint32_t> read_ding;      // bumped on release
  std::atomic<uint32_t> read_waiters;   // readers parked on write_ding
  std::atomic<uint32_t> write_waiters;  // writers parked on read_ding
};

int futex_wait(std::atomic<uint32_t>* word, uint32_t expected,
               int64_t timeout_us) {
  struct timespec ts;
  struct timespec* tsp = nullptr;
  if (timeout_us >= 0) {
    ts.tv_sec = timeout_us / 1000000;
    ts.tv_nsec = (timeout_us % 1000000) * 1000;
    tsp = &ts;
  }
  long rc = syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAIT,
                    expected, tsp, nullptr, 0);
  if (rc == -1 && errno == ETIMEDOUT) return -1;
  // 0 (woken), EAGAIN (value already changed), EINTR (signal): let the
  // caller re-check the ring — all are "maybe ready"
  return 0;
}

void futex_wake_all(std::atomic<uint32_t>* word) {
  syscall(SYS_futex, reinterpret_cast<uint32_t*>(word), FUTEX_WAKE, INT_MAX,
          nullptr, nullptr, 0);
}

struct Slot {
  uint64_t len;
  // payload follows
};

inline uint64_t slot_stride(uint64_t slot_size) {
  return sizeof(Slot) + ((slot_size + 63) & ~63ULL);  // 64B-align payloads
}

inline Slot* slot_at(ChannelHeader* h, uint64_t idx) {
  auto* base = reinterpret_cast<uint8_t*>(h) + sizeof(ChannelHeader);
  return reinterpret_cast<Slot*>(base +
                                 (idx % h->nslots) * slot_stride(h->slot_size));
}

}  // namespace

extern "C" {

uint64_t rt_chan_required_size(uint64_t nslots, uint64_t slot_size) {
  return sizeof(ChannelHeader) + nslots * slot_stride(slot_size);
}

int rt_chan_init(void* base, uint64_t region_size, uint64_t nslots,
                 uint64_t slot_size) {
  if (region_size < rt_chan_required_size(nslots, slot_size)) return -1;
  auto* h = new (base) ChannelHeader();
  h->magic = kChanMagic;
  h->nslots = nslots;
  h->slot_size = slot_size;
  // tsan: relaxed init stores — rt_chan_init runs before the region's fd/
  // name is handed to the peer, so no second thread can observe them yet.
  h->write_seq.store(0, std::memory_order_relaxed);
  h->read_seq.store(0, std::memory_order_relaxed);
  h->closed.store(0, std::memory_order_relaxed);
  h->write_ding.store(0, std::memory_order_relaxed);
  h->read_ding.store(0, std::memory_order_relaxed);
  h->read_waiters.store(0, std::memory_order_relaxed);
  h->write_waiters.store(0, std::memory_order_relaxed);
  return 0;
}

int rt_chan_validate(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  return h->magic == kChanMagic ? 0 : -1;
}

// Writer side. rt_chan_reserve returns the offset (from base) of the slot
// payload to write into, -1 if the ring is full (backpressure), or -3 if
// the ring is closed (either end hung up — writes must fail fast, e.g. a
// teardown-racing rpc_chan_write against a reader that already closed).
int64_t rt_chan_reserve(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  if (h->closed.load(std::memory_order_acquire)) return -3;
  // tsan: relaxed — SPSC: write_seq is only ever stored by this (the single
  // writer) thread, so reading our own last store needs no ordering.
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  // acquire pairs with rt_chan_release's read_seq.store(release): seeing
  // r proves the reader is done with slots below r, so reuse is safe.
  uint64_t r = h->read_seq.load(std::memory_order_acquire);
  if (w - r >= h->nslots) return -1;  // full
  auto* s = slot_at(h, w);
  return reinterpret_cast<uint8_t*>(s) + sizeof(Slot) -
         reinterpret_cast<uint8_t*>(base);
}

int rt_chan_commit(void* base, uint64_t len) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  if (len > h->slot_size) return -2;
  // tsan: relaxed — writer-owned counter (see rt_chan_reserve).
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  slot_at(h, w)->len = len;
  // release publishes the payload + len to the reader's acquire load.
  h->write_seq.store(w + 1, std::memory_order_release);
  h->write_ding.fetch_add(1, std::memory_order_release);
  if (h->read_waiters.load(std::memory_order_acquire) != 0)
    futex_wake_all(&h->write_ding);
  return 0;
}

// Reader side. rt_chan_acquire returns the payload offset and length of the
// next unread slot, or -1 if empty, -2 if empty AND closed (EOF).
int64_t rt_chan_acquire(void* base, uint64_t* out_len) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  // tsan: relaxed — SPSC: read_seq is only ever stored by this (the single
  // reader) thread, so reading our own last store needs no ordering.
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  // acquire pairs with rt_chan_commit's write_seq.store(release) and makes
  // the slot payload + len visible before we touch them.
  uint64_t w = h->write_seq.load(std::memory_order_acquire);
  if (r == w) {
    return h->closed.load(std::memory_order_acquire) ? -2 : -1;
  }
  auto* s = slot_at(h, r);
  *out_len = s->len;
  return reinterpret_cast<uint8_t*>(s) + sizeof(Slot) -
         reinterpret_cast<uint8_t*>(base);
}

int rt_chan_release(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  // tsan: relaxed — reader-owned counter (see rt_chan_acquire).
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  // release returns the slot to the writer: pairs with rt_chan_reserve's
  // acquire load and orders our payload reads before slot reuse.
  h->read_seq.store(r + 1, std::memory_order_release);
  h->read_ding.fetch_add(1, std::memory_order_release);
  if (h->write_waiters.load(std::memory_order_acquire) != 0)
    futex_wake_all(&h->read_ding);
  return 0;
}

void rt_chan_close(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  h->closed.store(1, std::memory_order_release);
  // close must reach parked readers even with no payload in flight, AND
  // parked writers (a reader closing a full ring at teardown must fail
  // blocked producers fast, not strand them until timeout)
  h->write_ding.fetch_add(1, std::memory_order_release);
  futex_wake_all(&h->write_ding);
  h->read_ding.fetch_add(1, std::memory_order_release);
  futex_wake_all(&h->read_ding);
}

// Park until the ring is (probably) readable: data available or closed.
// Returns 0 = re-check now (data/closed/spurious wake), -1 = timed out.
// timeout_us < 0 waits indefinitely. Callers always loop over
// try-acquire, so a spurious 0 is harmless.
int rt_chan_wait_readable(void* base, int64_t timeout_us) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  uint32_t ding = h->write_ding.load(std::memory_order_acquire);
  // tsan: relaxed — reader-owned counter; only the reader parks here.
  uint64_t r = h->read_seq.load(std::memory_order_relaxed);
  if (h->write_seq.load(std::memory_order_acquire) != r ||
      h->closed.load(std::memory_order_acquire))
    return 0;
  h->read_waiters.fetch_add(1, std::memory_order_acq_rel);
  // A commit between the ding load and the kernel's futex compare bumps
  // write_ding, so FUTEX_WAIT returns EAGAIN instead of sleeping — no
  // lost-wakeup window.
  int rc = futex_wait(&h->write_ding, ding, timeout_us);
  h->read_waiters.fetch_sub(1, std::memory_order_acq_rel);
  return rc;
}

// Park until the ring has (probably) a free slot. Same contract as
// rt_chan_wait_readable.
int rt_chan_wait_writable(void* base, int64_t timeout_us) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  uint32_t ding = h->read_ding.load(std::memory_order_acquire);
  if (h->closed.load(std::memory_order_acquire)) return 0;  // fail fast
  // tsan: relaxed — writer-owned counter; only the writer parks here.
  uint64_t w = h->write_seq.load(std::memory_order_relaxed);
  if (w - h->read_seq.load(std::memory_order_acquire) < h->nslots) return 0;
  h->write_waiters.fetch_add(1, std::memory_order_acq_rel);
  int rc = futex_wait(&h->read_ding, ding, timeout_us);
  h->write_waiters.fetch_sub(1, std::memory_order_acq_rel);
  return rc;
}

uint64_t rt_chan_readable(void* base) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  return h->write_seq.load(std::memory_order_acquire) -
         h->read_seq.load(std::memory_order_acquire);
}

uint64_t rt_chan_slot_size(void* base) {
  return reinterpret_cast<ChannelHeader*>(base)->slot_size;
}

// Touch every payload page of every slot in THIS process's mapping so the
// first real transfer doesn't eat a minor fault per 4KB (shmem THP is
// commonly disabled). write=1 does a read-modify-write (installs writable
// PTEs for the producer side); only safe while the ring carries no
// committed slots.
void rt_chan_prefault(void* base, int write) {
  auto* h = reinterpret_cast<ChannelHeader*>(base);
  for (uint64_t i = 0; i < h->nslots; i++) {
    auto* p = reinterpret_cast<volatile uint8_t*>(slot_at(h, i)) +
              sizeof(Slot);
    for (uint64_t off = 0; off < h->slot_size; off += 4096) {
      if (write) {
        p[off] = p[off];
      } else {
        (void)p[off];
      }
    }
  }
}

}  // extern "C"
