// Shared-memory object store: the node-local zero-copy object plane.
//
// Capability parity with the reference's plasma store
// (reference: src/ray/object_manager/plasma/store.h:55, object_store.h:76,
// object_lifecycle_manager.h, eviction_policy.h, plasma_allocator.h, dlmalloc.cc),
// redesigned without a store server process: instead of a socket protocol with
// fd-passing (reference: plasma/fling.cc), every process on the node maps the same
// named POSIX shm segment which contains the allocator heap, the object table, and
// a robust process-shared mutex. create/seal/get/release are direct shm operations
// (~sub-microsecond), reads are zero-copy mmap views, and crash recovery relies on
// robust-mutex EOWNERDEAD plus per-process reference reconciliation done by the
// node daemon.
//
// Layout:
//   [StoreHeader | ObjectEntry[capacity] | heap ...]
//
// Heap: first-fit free list with coalescing (simplified dlmalloc-style, reference
// vendors dlmalloc at src/ray/thirdparty/dlmalloc.c). Eviction: LRU over sealed,
// unreferenced objects (reference: plasma/eviction_policy.h).

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <stdint.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <vector>

namespace {

constexpr uint64_t kMagic = 0x52415954505553ULL;  // "RAYTPUS"
constexpr int kIdSize = 24;
constexpr uint32_t kEntryFree = 0;
constexpr uint32_t kEntryCreated = 1;
constexpr uint32_t kEntrySealed = 2;

struct ObjectEntry {
  uint8_t id[kIdSize];
  uint32_t state;
  int32_t refcount;
  uint64_t data_size;
  uint64_t offset;  // from segment base
  uint64_t lru_tick;
  uint64_t metadata;  // small user tag (e.g. error bit)
};

struct FreeBlock {
  uint64_t size;      // includes this header
  uint64_t next_off;  // offset of next free block, 0 = end
};

struct StoreHeader {
  uint64_t magic;
  uint64_t total_size;
  uint64_t heap_offset;
  uint64_t heap_size;
  uint64_t capacity;      // object table slots
  uint64_t free_head;     // offset of first free block (0 = none)
  uint64_t lru_clock;
  uint64_t bytes_in_use;
  uint64_t num_objects;
  pthread_mutex_t lock;
  // tsan: seal_seq is the store's only atomic — every other header field is
  // written exclusively under the robust `lock` (Guard). It stays seq_cst
  // (defaulted orders) so a poller may read it WITHOUT the lock and still
  // see a monotone value; today's callers happen to hold the Guard anyway.
  std::atomic<uint64_t> seal_seq;  // bumped on every seal, for pollers
};

struct Store {
  StoreHeader* hdr;
  ObjectEntry* table;
  uint8_t* base;
  uint64_t map_size;
  int fd;
};

inline uint64_t align8(uint64_t v) { return (v + 7) & ~7ULL; }

class Guard {
 public:
  explicit Guard(StoreHeader* h) : h_(h) {
    int rc = pthread_mutex_lock(&h->lock);
    if (rc == EOWNERDEAD) {
      // A process died holding the lock; state is still structurally valid
      // because all mutations are ordered to be crash-consistent enough for
      // the daemon to reconcile. Mark consistent and continue.
      pthread_mutex_consistent(&h->lock);
    }
  }
  ~Guard() { pthread_mutex_unlock(&h_->lock); }

 private:
  StoreHeader* h_;
};

uint64_t hash_id(const uint8_t* id) {
  uint64_t h = 1469598103934665603ULL;
  for (int i = 0; i < kIdSize; i++) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

// Open-addressed lookup. Returns entry with matching id, or (if insert) the
// first free slot on the probe path, or nullptr.
ObjectEntry* find_entry(Store* s, const uint8_t* id, bool insert) {
  uint64_t cap = s->hdr->capacity;
  uint64_t idx = hash_id(id) % cap;
  ObjectEntry* first_free = nullptr;
  for (uint64_t probe = 0; probe < cap; probe++) {
    ObjectEntry* e = &s->table[(idx + probe) % cap];
    if (e->state == kEntryFree) {
      if (first_free == nullptr) first_free = e;
      // Free slot ends the probe chain only if never-used; we use simple
      // convention: stop at free slot (no tombstones: deletes compact by
      // re-inserting the rest of the cluster).
      break;
    }
    if (memcmp(e->id, id, kIdSize) == 0) return e;
  }
  return insert ? first_free : nullptr;
}

// Robin-hood style cluster fix after deletion to keep probing correct.
void rehash_cluster(Store* s, uint64_t hole_idx) {
  uint64_t cap = s->hdr->capacity;
  uint64_t idx = (hole_idx + 1) % cap;
  while (s->table[idx].state != kEntryFree) {
    ObjectEntry copy = s->table[idx];
    s->table[idx].state = kEntryFree;
    ObjectEntry* slot = find_entry(s, copy.id, true);
    *slot = copy;
    idx = (idx + 1) % cap;
  }
}

uint64_t heap_alloc(Store* s, uint64_t want) {
  want = align8(want);
  if (want < sizeof(FreeBlock)) want = sizeof(FreeBlock);
  uint64_t prev_off = 0;
  uint64_t off = s->hdr->free_head;
  while (off) {
    FreeBlock* b = reinterpret_cast<FreeBlock*>(s->base + off);
    if (b->size >= want + sizeof(uint64_t)) {
      uint64_t remain = b->size - want - sizeof(uint64_t);
      uint64_t data_off;
      if (remain >= sizeof(FreeBlock) + sizeof(uint64_t)) {
        // split: allocate from the tail of the block
        b->size -= (want + sizeof(uint64_t));
        uint64_t alloc_off = off + b->size;
        *reinterpret_cast<uint64_t*>(s->base + alloc_off) = want + sizeof(uint64_t);
        data_off = alloc_off + sizeof(uint64_t);
      } else {
        // take whole block
        uint64_t next = b->next_off;
        uint64_t bsize = b->size;
        if (prev_off) {
          reinterpret_cast<FreeBlock*>(s->base + prev_off)->next_off = next;
        } else {
          s->hdr->free_head = next;
        }
        *reinterpret_cast<uint64_t*>(s->base + off) = bsize;
        data_off = off + sizeof(uint64_t);
      }
      return data_off;
    }
    prev_off = off;
    off = b->next_off;
  }
  return 0;
}

void heap_free(Store* s, uint64_t data_off) {
  uint64_t block_off = data_off - sizeof(uint64_t);
  uint64_t bsize = *reinterpret_cast<uint64_t*>(s->base + block_off);
  // insert sorted by offset, coalesce neighbors
  uint64_t prev = 0, cur = s->hdr->free_head;
  while (cur && cur < block_off) {
    prev = cur;
    cur = reinterpret_cast<FreeBlock*>(s->base + cur)->next_off;
  }
  FreeBlock* nb = reinterpret_cast<FreeBlock*>(s->base + block_off);
  nb->size = bsize;
  nb->next_off = cur;
  if (prev) {
    reinterpret_cast<FreeBlock*>(s->base + prev)->next_off = block_off;
  } else {
    s->hdr->free_head = block_off;
  }
  // coalesce with next
  if (cur && block_off + nb->size == cur) {
    FreeBlock* cb = reinterpret_cast<FreeBlock*>(s->base + cur);
    nb->size += cb->size;
    nb->next_off = cb->next_off;
  }
  // coalesce with prev
  if (prev) {
    FreeBlock* pb = reinterpret_cast<FreeBlock*>(s->base + prev);
    if (prev + pb->size == block_off) {
      pb->size += nb->size;
      pb->next_off = nb->next_off;
    }
  }
}

// Evict LRU sealed objects with refcount==0 until at least `need` bytes are
// freed. One scan collects all candidates, sorts by LRU tick, then evicts in
// order — victims are re-located by id because rehash_cluster moves entries
// (reference design: intrusive LRU list in plasma/eviction_policy.h).
uint64_t evict_lru(Store* s, uint64_t need) {
  struct Cand {
    uint64_t tick;
    uint64_t size;
    uint8_t id[kIdSize];
  };
  std::vector<Cand> cands;
  for (uint64_t i = 0; i < s->hdr->capacity; i++) {
    ObjectEntry* e = &s->table[i];
    if (e->state == kEntrySealed && e->refcount == 0) {
      Cand c;
      c.tick = e->lru_tick;
      c.size = e->data_size;
      memcpy(c.id, e->id, kIdSize);
      cands.push_back(c);
    }
  }
  std::sort(cands.begin(), cands.end(),
            [](const Cand& a, const Cand& b) { return a.tick < b.tick; });
  uint64_t freed = 0;
  for (const Cand& c : cands) {
    if (freed >= need) break;
    ObjectEntry* e = find_entry(s, c.id, false);
    if (e == nullptr || e->state != kEntrySealed || e->refcount != 0) continue;
    freed += e->data_size;
    s->hdr->bytes_in_use -= e->data_size;
    s->hdr->num_objects--;
    heap_free(s, e->offset);
    uint64_t idx = (uint64_t)(e - s->table);
    e->state = kEntryFree;
    rehash_cluster(s, idx);
  }
  return freed;
}

}  // namespace

extern "C" {

// Error codes
enum {
  RT_OK = 0,
  RT_ERR_EXISTS = -1,
  RT_ERR_NOT_FOUND = -2,
  RT_ERR_FULL = -3,
  RT_ERR_STATE = -4,
  RT_ERR_SYS = -5,
};

void* rt_store_create(const char* name, uint64_t size, uint64_t capacity) {
  shm_unlink(name);
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) return nullptr;
  uint64_t table_bytes = capacity * sizeof(ObjectEntry);
  uint64_t total = align8(sizeof(StoreHeader)) + align8(table_bytes) + size;
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->map_size = total;
  s->fd = fd;
  s->hdr = reinterpret_cast<StoreHeader*>(base);
  memset(s->hdr, 0, sizeof(StoreHeader));
  s->hdr->magic = kMagic;
  s->hdr->total_size = total;
  s->hdr->capacity = capacity;
  s->hdr->heap_offset = align8(sizeof(StoreHeader)) + align8(table_bytes);
  s->hdr->heap_size = size;
  s->table = reinterpret_cast<ObjectEntry*>(s->base + align8(sizeof(StoreHeader)));
  memset(s->table, 0, table_bytes);
  // init heap: one big free block
  FreeBlock* fb = reinterpret_cast<FreeBlock*>(s->base + s->hdr->heap_offset);
  fb->size = size;
  fb->next_off = 0;
  s->hdr->free_head = s->hdr->heap_offset;
  // robust process-shared mutex
  pthread_mutexattr_t attr;
  pthread_mutexattr_init(&attr);
  pthread_mutexattr_setpshared(&attr, PTHREAD_PROCESS_SHARED);
  pthread_mutexattr_setrobust(&attr, PTHREAD_MUTEX_ROBUST);
  pthread_mutex_init(&s->hdr->lock, &attr);
  // tsan: seq_cst init store — runs before the segment name is returned to
  // any peer, so no concurrent observer exists yet.
  s->hdr->seal_seq.store(0);
  return s;
}

void* rt_store_open(const char* name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    close(fd);
    return nullptr;
  }
  void* base = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  Store* s = new Store();
  s->base = static_cast<uint8_t*>(base);
  s->map_size = (uint64_t)st.st_size;
  s->fd = fd;
  s->hdr = reinterpret_cast<StoreHeader*>(base);
  if (s->hdr->magic != kMagic) {
    munmap(base, s->map_size);
    close(fd);
    delete s;
    return nullptr;
  }
  s->table = reinterpret_cast<ObjectEntry*>(
      s->base + align8(sizeof(StoreHeader)));
  return s;
}

void rt_store_close(void* handle) {
  Store* s = static_cast<Store*>(handle);
  munmap(s->base, s->map_size);
  close(s->fd);
  delete s;
}

void rt_store_destroy(const char* name) { shm_unlink(name); }

// Allocates space for an object. On success *out_offset is the byte offset of
// the data region from the mapped base (stable across processes).
// allow_evict=0 makes a failed allocation return RT_ERR_FULL instead of
// destroying LRU objects — required when the node daemon spills under
// pressure (eviction would delete sole copies the spiller could have saved;
// reference: plasma eviction is only safe because raylet spills first).
int rt_object_create_ex(void* handle, const uint8_t* id, uint64_t data_size,
                        uint64_t metadata, int allow_evict,
                        uint64_t* out_offset) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s, id, true);
  if (e == nullptr) return RT_ERR_FULL;  // table full
  if (e->state != kEntryFree) return RT_ERR_EXISTS;
  uint64_t off = heap_alloc(s, data_size ? data_size : 8);
  if (off == 0) {
    if (!allow_evict) return RT_ERR_FULL;
    evict_lru(s, data_size + 64);
    off = heap_alloc(s, data_size ? data_size : 8);
    if (off == 0) return RT_ERR_FULL;
    e = find_entry(s, id, true);  // eviction may have moved slots
    if (e == nullptr) return RT_ERR_FULL;
    if (e->state != kEntryFree) return RT_ERR_EXISTS;
  }
  memcpy(e->id, id, kIdSize);
  e->state = kEntryCreated;
  e->refcount = 1;  // creator holds a ref until seal+release
  e->data_size = data_size;
  e->offset = off;
  e->metadata = metadata;
  e->lru_tick = ++s->hdr->lru_clock;
  s->hdr->bytes_in_use += data_size;
  s->hdr->num_objects++;
  *out_offset = off;
  return RT_OK;
}

int rt_object_create(void* handle, const uint8_t* id, uint64_t data_size,
                     uint64_t metadata, uint64_t* out_offset) {
  return rt_object_create_ex(handle, id, data_size, metadata, /*allow_evict=*/1,
                             out_offset);
}

int rt_object_seal(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s, id, false);
  if (e == nullptr) return RT_ERR_NOT_FOUND;
  if (e->state != kEntryCreated) return RT_ERR_STATE;
  e->state = kEntrySealed;
  e->refcount -= 1;  // drop creator ref
  // tsan: seq_cst bump under the Guard; ordered after the state flip above
  // so a lock-free poller that sees the new seq can safely take the lock
  // and find the object sealed.
  s->hdr->seal_seq.fetch_add(1);
  return RT_OK;
}

// Get a sealed object; bumps refcount (pin). Returns offset+size+metadata.
int rt_object_get(void* handle, const uint8_t* id, uint64_t* out_offset,
                  uint64_t* out_size, uint64_t* out_metadata) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s, id, false);
  if (e == nullptr || e->state != kEntrySealed) return RT_ERR_NOT_FOUND;
  e->refcount++;
  e->lru_tick = ++s->hdr->lru_clock;
  *out_offset = e->offset;
  *out_size = e->data_size;
  *out_metadata = e->metadata;
  return RT_OK;
}

int rt_object_release(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s, id, false);
  if (e == nullptr) return RT_ERR_NOT_FOUND;
  if (e->refcount > 0) e->refcount--;
  return RT_OK;
}

int rt_object_contains(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s, id, false);
  return (e != nullptr && e->state == kEntrySealed) ? 1 : 0;
}

int rt_object_delete(void* handle, const uint8_t* id) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  ObjectEntry* e = find_entry(s, id, false);
  if (e == nullptr) return RT_ERR_NOT_FOUND;
  if (e->refcount > 0) return RT_ERR_STATE;  // pinned
  uint64_t idx = (uint64_t)(e - s->table);
  s->hdr->bytes_in_use -= e->data_size;
  s->hdr->num_objects--;
  heap_free(s, e->offset);
  e->state = kEntryFree;
  rehash_cluster(s, idx);
  return RT_OK;
}

// List spill/eviction candidates (sealed, unpinned), LRU-first. Fills up to
// max_n ids (kIdSize bytes each) and sizes; returns the count written. Used
// by the node daemon's spill loop (reference: local_object_manager.h:45
// SpillObjectsOfSize choosing from the eviction policy's LRU order).
uint64_t rt_store_list_evictable(void* handle, uint8_t* out_ids,
                                 uint64_t* out_sizes, uint64_t max_n) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  struct Cand {
    uint64_t tick;
    uint64_t size;
    const uint8_t* id;
  };
  std::vector<Cand> cands;
  for (uint64_t i = 0; i < s->hdr->capacity; i++) {
    ObjectEntry* e = &s->table[i];
    if (e->state == kEntrySealed && e->refcount == 0) {
      cands.push_back({e->lru_tick, e->data_size, e->id});
    }
  }
  std::sort(cands.begin(), cands.end(),
            [](const Cand& a, const Cand& b) { return a.tick < b.tick; });
  uint64_t n = std::min<uint64_t>(cands.size(), max_n);
  for (uint64_t i = 0; i < n; i++) {
    memcpy(out_ids + i * kIdSize, cands[i].id, kIdSize);
    out_sizes[i] = cands[i].size;
  }
  return n;
}

void rt_store_stats(void* handle, uint64_t* bytes_in_use, uint64_t* num_objects,
                    uint64_t* heap_size, uint64_t* seal_seq) {
  Store* s = static_cast<Store*>(handle);
  Guard g(s->hdr);
  *bytes_in_use = s->hdr->bytes_in_use;
  *num_objects = s->hdr->num_objects;
  *heap_size = s->hdr->heap_size;
  *seal_seq = s->hdr->seal_seq.load();
}

uint8_t* rt_store_base(void* handle) {
  return static_cast<Store*>(handle)->base;
}

uint64_t rt_store_map_size(void* handle) {
  return static_cast<Store*>(handle)->map_size;
}

}  // extern "C"
