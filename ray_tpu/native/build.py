"""Builds the native C++ components into shared libraries, cached by source hash.

The reference builds its native runtime with Bazel (reference: BUILD.bazel); here a
minimal g++ invocation keeps the loop fast and hermetic. Artifacts land in
ray_tpu/native/_build/ and are rebuilt only when sources change.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()

_LIBS = {
    "shm_store": ["shm_store.cc"],
    "shm_channel": ["shm_channel.cc"],
    "fastpath": ["fastpath.cc"],
}


def lib_path(name: str) -> str:
    """Compile (if stale) and return the path of the shared library `name`."""
    sources = [os.path.join(_DIR, s) for s in _LIBS[name]]
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    tag = h.hexdigest()[:16]
    out = os.path.join(_BUILD, f"lib{name}-{tag}.so")
    if os.path.exists(out):
        return out
    with _LOCK:
        if os.path.exists(out):
            return out
        os.makedirs(_BUILD, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
            "-o", tmp, *sources, "-lpthread", "-lrt",
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    return out
