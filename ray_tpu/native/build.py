"""Builds the native C++ components into shared libraries, cached by source hash.

The reference builds its native runtime with Bazel (reference: BUILD.bazel); here a
minimal g++ invocation keeps the loop fast and hermetic. Artifacts land in
ray_tpu/native/_build/ and are rebuilt only when sources change.

Sanitizer modes (opt-in, mutually exclusive — one runtime per process):

  RAY_TPU_NATIVE_SANITIZE=1|address   ASan+UBSan (reference: the bazel
      asan/ubsan config the reference's CI runs its C++ unit tests under).
  RAY_TPU_NATIVE_SANITIZE=thread      ThreadSanitizer, for the lock-free
      fastpath ring / SPSC channel / shm store memory-ordering audit
      (tests/test_tsan.py drives race-amplifier workloads under it).

Sanitized artifacts are cached under a distinct tag+suffix per mode so they
never mix with production builds or each other. Loading them into a stock
CPython requires LD_PRELOADing the sanitizer runtime — `sanitizer_preload()`
returns the right library (libasan or libtsan) for the active mode;
tests/test_sanitize.py and tests/test_tsan.py drive the flow in subprocesses.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()

_LIBS = {
    "shm_store": ["shm_store.cc"],
    "shm_channel": ["shm_channel.cc"],
    "fastpath": ["fastpath.cc"],
}

_ASAN_FLAGS = [
    "-fsanitize=address,undefined",
    "-fno-sanitize-recover=all",
    "-fno-omit-frame-pointer",
]

_TSAN_FLAGS = [
    "-fsanitize=thread",
    "-fno-omit-frame-pointer",
]

_MODES = {
    # mode -> (compile flags, cache suffix, preload runtime soname)
    "address": (_ASAN_FLAGS, "-san", "libasan.so"),
    "thread": (_TSAN_FLAGS, "-tsan", "libtsan.so"),
}


def sanitize_mode() -> str:
    """'' | 'address' | 'thread'. The historical truthy values (1/true/...)
    keep meaning ASan+UBSan; asan and tsan cannot coexist in one process."""
    raw = os.environ.get("RAY_TPU_NATIVE_SANITIZE", "").strip().lower()
    if raw in ("1", "true", "yes", "on", "address", "asan"):
        return "address"
    if raw in ("thread", "tsan"):
        return "thread"
    return ""


def sanitize_enabled() -> bool:
    return sanitize_mode() != ""


def sanitizer_preload(mode: str | None = None) -> str:
    """Path of the sanitizer runtime to LD_PRELOAD when loading sanitized
    libraries into a non-instrumented python (libasan for mode=address,
    libtsan for mode=thread); '' when unavailable. `mode` defaults to the
    active env mode, falling back to 'address' so test harnesses can probe
    for the runtime before exporting RAY_TPU_NATIVE_SANITIZE themselves."""
    mode = mode or sanitize_mode() or "address"
    runtime = _MODES[mode][2]
    try:
        out = subprocess.run(
            ["g++", f"-print-file-name={runtime}"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
    except (OSError, subprocess.CalledProcessError):
        return ""
    return out if out and os.path.sep in out and os.path.exists(out) else ""


def lib_path(name: str) -> str:
    """Compile (if stale) and return the path of the shared library `name`."""
    sources = [os.path.join(_DIR, s) for s in _LIBS[name]]
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    mode = sanitize_mode()
    flags, suffix = (_MODES[mode][0], _MODES[mode][1]) if mode else ([], "")
    if mode:
        h.update(b"sanitize:" + " ".join(flags).encode())
    tag = h.hexdigest()[:16]
    out = os.path.join(_BUILD, f"lib{name}-{tag}{suffix}.so")
    if os.path.exists(out):
        return out
    with _LOCK:
        if os.path.exists(out):
            return out
        os.makedirs(_BUILD, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
            *flags,
            "-o", tmp, *sources, "-lpthread", "-lrt",
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    return out
