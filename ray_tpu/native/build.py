"""Builds the native C++ components into shared libraries, cached by source hash.

The reference builds its native runtime with Bazel (reference: BUILD.bazel); here a
minimal g++ invocation keeps the loop fast and hermetic. Artifacts land in
ray_tpu/native/_build/ and are rebuilt only when sources change.

Sanitizer mode (opt-in): env RAY_TPU_NATIVE_SANITIZE=1 compiles every library
with ASan+UBSan (reference: the bazel asan/ubsan config the reference's CI
runs its C++ unit tests under). Sanitized artifacts are cached under a
distinct tag so they never mix with production builds. Loading them into a
stock CPython requires LD_PRELOADing libasan — `sanitizer_preload()` returns
the path; tests/test_sanitize.py drives the whole flow in a subprocess.
"""

from __future__ import annotations

import hashlib
import os
import subprocess
import threading

_DIR = os.path.dirname(os.path.abspath(__file__))
_BUILD = os.path.join(_DIR, "_build")
_LOCK = threading.Lock()

_LIBS = {
    "shm_store": ["shm_store.cc"],
    "shm_channel": ["shm_channel.cc"],
    "fastpath": ["fastpath.cc"],
}

_SANITIZE_FLAGS = [
    "-fsanitize=address,undefined",
    "-fno-sanitize-recover=all",
    "-fno-omit-frame-pointer",
]


def sanitize_enabled() -> bool:
    return os.environ.get("RAY_TPU_NATIVE_SANITIZE", "").strip() in (
        "1", "true", "yes", "on")


def sanitizer_preload() -> str:
    """Path of the ASan runtime to LD_PRELOAD when loading sanitized
    libraries into a non-instrumented python; '' when unavailable."""
    try:
        out = subprocess.run(
            ["g++", "-print-file-name=libasan.so"],
            capture_output=True, text=True, check=True,
        ).stdout.strip()
    except (OSError, subprocess.CalledProcessError):
        return ""
    return out if out and os.path.sep in out and os.path.exists(out) else ""


def lib_path(name: str) -> str:
    """Compile (if stale) and return the path of the shared library `name`."""
    sources = [os.path.join(_DIR, s) for s in _LIBS[name]]
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    sanitize = sanitize_enabled()
    if sanitize:
        h.update(b"sanitize:" + " ".join(_SANITIZE_FLAGS).encode())
    tag = h.hexdigest()[:16]
    suffix = "-san" if sanitize else ""
    out = os.path.join(_BUILD, f"lib{name}-{tag}{suffix}.so")
    if os.path.exists(out):
        return out
    with _LOCK:
        if os.path.exists(out):
            return out
        os.makedirs(_BUILD, exist_ok=True)
        tmp = out + f".tmp{os.getpid()}"
        cmd = [
            "g++", "-O2", "-g", "-shared", "-fPIC", "-std=c++17",
            *(_SANITIZE_FLAGS if sanitize else []),
            "-o", tmp, *sources, "-lpthread", "-lrt",
        ]
        subprocess.run(cmd, check=True, capture_output=True, text=True)
        os.replace(tmp, out)
    return out
