"""Checkpointing: sharded-pytree save/restore + top-K retention manager.

Capability parity with the reference's Train checkpoint stack (reference:
python/ray/train/_checkpoint.py:56 `Checkpoint`,
python/ray/train/v2/_internal/execution/checkpoint/checkpoint_manager.py
`CheckpointManager`, storage.py `StorageContext`), redesigned for JAX state:

- a checkpoint is a directory; training state is a pytree of (possibly
  sharded) jax.Arrays saved as one `rank_<k>.npz` per reporting process plus
  a JSON manifest — on restore every process reads its own shard file, so
  multi-host saves need only a shared filesystem path (local dir, NFS, or a
  mounted bucket: the `storage_path` abstraction of the reference).
- saves are ASYNC: device arrays are snapshotted to host memory synchronously
  (cheap, bounded by HBM→host bandwidth) and the file write happens on a
  background thread, double-buffered so at most one write is in flight.
- the manager retains the latest + top-K checkpoints by a metric, deleting
  the rest (reference: checkpoint_manager.py top-K semantics).
"""

from __future__ import annotations

import json
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

import numpy as np


# ---------------------------------------------------------------------------
# pytree <-> npz
# ---------------------------------------------------------------------------


def _flatten_with_paths(tree, prefix=""):
    """Flatten a nested dict/list/tuple pytree into {path: leaf}."""
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree):
            out.update(_flatten_with_paths(tree[k], f"{prefix}/{k}"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_with_paths(v, f"{prefix}/{i}"))
    else:
        out[prefix or "/"] = tree
    return out


def _unflatten_from_paths(flat: Dict[str, Any], skeleton):
    """Rebuild `skeleton`'s structure with leaves taken from `flat`."""

    def build(node, prefix):
        if isinstance(node, dict):
            return {k: build(node[k], f"{prefix}/{k}") for k in node}
        if isinstance(node, tuple):
            return tuple(
                build(v, f"{prefix}/{i}") for i, v in enumerate(node)
            )
        if isinstance(node, list):
            return [build(v, f"{prefix}/{i}") for i, v in enumerate(node)]
        return flat[prefix or "/"]

    return build(skeleton, "")


def snapshot_to_host(state) -> Dict[str, np.ndarray]:
    """Device→host snapshot of a pytree's addressable data (sync, fast)."""
    return snapshot_with_meta(state)[0]


def _shard_bounds(index, shape):
    return [
        [0 if s.start is None else int(s.start),
         dim if s.stop is None else int(s.stop)]
        for s, dim in zip(index, shape)
    ]


def snapshot_with_meta(state):
    """Like snapshot_to_host, plus per-leaf SHARD metadata for leaves where
    this process holds only slices of the global array (multi-process
    sharded training): {path: {"global_shape": [...], "shards": [{"key":
    npz-key, "index": [[lo, hi], ...]}, ...]}}. Every locally-addressable
    shard is saved (multi-chip hosts hold several); the metadata is what
    makes cross-world-size consolidating restore possible (reference:
    storage.py + the elastic restart path)."""
    import jax

    flat = _flatten_with_paths(state)
    out = {}
    meta: Dict[str, Any] = {}
    for path, leaf in flat.items():
        if isinstance(leaf, jax.Array):
            if leaf.is_fully_addressable:
                # single process holds everything: gather the full value
                out[path] = np.asarray(leaf)
                continue
            shards = leaf.addressable_shards
            bounds = [_shard_bounds(s.index, leaf.shape) for s in shards]
            full = [[0, d] for d in leaf.shape]
            if bounds and bounds[0] == full:
                # replicated across processes: any local copy is the value
                out[path] = np.asarray(shards[0].data)
                continue
            entries = []
            for i, (s, b) in enumerate(zip(shards, bounds)):
                key = path if i == 0 else f"{path}#shard{i}"
                out[key] = np.asarray(s.data)
                entries.append({"key": key, "index": b})
            meta[path] = {"global_shape": list(leaf.shape),
                          "shards": entries}
        elif isinstance(leaf, (np.ndarray, np.generic, int, float)):
            out[path] = np.asarray(leaf)
        else:
            out[path] = np.asarray(leaf)
    return out, meta


def _place_onto(skeleton, rebuilt):
    """Place restored host leaves onto the skeleton's shardings/types."""
    import jax

    def place(ref_leaf, new_leaf):
        if isinstance(ref_leaf, jax.Array):
            return jax.device_put(new_leaf, ref_leaf.sharding)
        if isinstance(ref_leaf, (int, float)):
            return type(ref_leaf)(new_leaf)
        return new_leaf

    return jax.tree.map(place, skeleton, rebuilt)


@dataclass
class Checkpoint:
    """A checkpoint directory under a StorageContext URI (reference:
    train/_checkpoint.py:56 + storage.py — `path` may be a plain local dir
    or any fsspec URI such as memory://... or gs://...)."""

    path: str
    metrics: Dict[str, Any] = field(default_factory=dict)

    def _storage(self):
        from ray_tpu.train._storage import get_storage

        return get_storage(self.path)

    def rank_file(self, rank: int) -> str:
        return self._storage().join(self.path, f"rank_{rank}.npz")

    @property
    def step(self) -> int:
        return int(self.metrics.get("step", -1))

    def load_state(self, skeleton, rank: int = 0):
        """Restore a pytree saved by `save_state` into skeleton's structure.

        Leaves that are jax.Arrays in `skeleton` are device_put with the
        skeleton's sharding (resharding on restore is free this way).

        Leaves this rank saved as multiple local shards (multi-chip hosts
        where the process holds several non-replicated regions) are merged
        back by region from the rank manifest and placed shard-by-shard;
        that only works when the skeleton expects the SAME local regions —
        a world-size or sharding change must go through load_consolidated.
        """
        import io

        import jax

        s = self._storage()
        data = s.read_bytes(self.rank_file(rank))
        with np.load(io.BytesIO(data)) as z:
            raw = {k: z[k] for k in z.files}
        try:
            shards_meta = s.read_json(
                s.join(self.path, f"manifest_{rank}.json")).get("shards", {})
        except FileNotFoundError:  # pre-metadata checkpoint
            shards_meta = {}
        flat = {k: v for k, v in raw.items()
                if "#shard" not in k and k not in shards_meta}
        # per-leaf {region bounds: saved shard array} — shards are served
        # directly, never merged into a global-shape buffer (a full-model
        # allocation per process would defeat the whole point of per-rank
        # sharded restore)
        partial: Dict[str, Dict[tuple, np.ndarray]] = {}
        for path, rec in shards_meta.items():
            partial[path] = {
                tuple(map(tuple, e["index"])): raw[e["key"]]
                for e in rec["shards"]
            }

        flat_skel = _flatten_with_paths(skeleton)
        placed: Dict[str, Any] = {}
        for path, ref_leaf in flat_skel.items():
            if isinstance(ref_leaf, jax.Array) and path in partial:
                by_region = partial[path]
                needed = {
                    tuple(map(tuple, _shard_bounds(idx, ref_leaf.shape)))
                    for idx in ref_leaf.sharding
                    .addressable_devices_indices_map(ref_leaf.shape).values()
                }
                if not needed <= set(by_region):
                    raise ValueError(
                        f"checkpoint leaf {path!r} was saved with different "
                        f"local shard regions than the restore sharding "
                        f"expects (world size or sharding changed) — use "
                        f"load_consolidated() instead of load_state()")
                placed[path] = jax.make_array_from_callback(
                    tuple(ref_leaf.shape), ref_leaf.sharding,
                    lambda idx, b=by_region, sh=ref_leaf.shape:
                        b[tuple(map(tuple, _shard_bounds(idx, sh)))])
                continue
            new_leaf = flat[path]
            if isinstance(ref_leaf, jax.Array):
                placed[path] = jax.device_put(new_leaf, ref_leaf.sharding)
            elif isinstance(ref_leaf, (int, float)):
                placed[path] = type(ref_leaf)(new_leaf)
            else:
                placed[path] = new_leaf
        return _unflatten_from_paths(placed, skeleton)

    def _rank_ids(self) -> List[int]:
        s = self._storage()
        return sorted(
            int(f[len("rank_"):-len(".npz")])
            for f in s.listdir(self.path)
            if f.startswith("rank_") and f.endswith(".npz"))

    def num_ranks(self) -> int:
        return len(self._rank_ids())

    def load_consolidated(self, skeleton):
        """Cross-world-size restore: merge EVERY rank's shard files into
        full arrays using the shard metadata the writers recorded, then
        place onto skeleton's shardings — a checkpoint saved at world size
        N restores at any world size M (the elastic restart path; VERDICT
        r3 weak #8 / next #8). Replicated leaves take rank 0's copy.
        Streams one rank file at a time: peak memory is one full model +
        one rank's shards, not world_size copies."""
        import io

        s = self._storage()
        ranks = self._rank_ids()
        if not ranks:
            raise FileNotFoundError(f"no rank shards in {self.path}")
        flat: Dict[str, np.ndarray] = {}
        for pos, r in enumerate(ranks):
            try:
                shards_meta = s.read_json(
                    s.join(self.path, f"manifest_{r}.json")).get("shards", {})
            except FileNotFoundError:  # pre-metadata checkpoint
                shards_meta = {}
            with np.load(io.BytesIO(
                    s.read_bytes(self.rank_file(r)))) as z:
                data = {k: z[k] for k in z.files}
            for path, rec in shards_meta.items():
                if path not in flat:
                    flat[path] = np.zeros(
                        rec["global_shape"],
                        data[rec["shards"][0]["key"]].dtype)
                for e in rec["shards"]:
                    region = tuple(slice(lo, hi) for lo, hi in e["index"])
                    flat[path][region] = data[e["key"]]
            if pos == 0:
                for k, v in data.items():
                    if "#shard" in k or k in shards_meta:
                        continue
                    flat.setdefault(k, v)
            del data
        rebuilt = _unflatten_from_paths(flat, skeleton)
        return _place_onto(skeleton, rebuilt)

    def to_wire(self) -> dict:
        return {"path": self.path, "metrics": self.metrics}

    @classmethod
    def from_wire(cls, w: dict) -> "Checkpoint":
        return cls(path=w["path"], metrics=dict(w.get("metrics") or {}))


class AsyncCheckpointWriter:
    """Double-buffered async writer: snapshot now, write in the background.

    At most one write in flight; a second save blocks until the first lands
    (backpressure instead of unbounded host-memory growth) — the same
    discipline as orbax's async checkpointer.
    """

    def __init__(self):
        self._pool = ThreadPoolExecutor(max_workers=1,
                                        thread_name_prefix="ckpt-write")
        self._inflight: Optional[Future] = None
        self._lock = threading.Lock()

    def save(self, state, path: str, rank: int = 0,
             manifest: Optional[dict] = None) -> Future:
        host, shard_meta = snapshot_with_meta(state)
        if shard_meta:
            manifest = dict(manifest or {})
            manifest["shards"] = shard_meta
        with self._lock:
            if self._inflight is not None:
                self._inflight.result()  # backpressure

            def write():
                import io

                from ray_tpu.train._storage import get_storage

                storage = get_storage(path)
                storage.makedirs(path)
                buf = io.BytesIO()
                np.savez(buf, **host)
                # tmp-name + rename publish: finalize counts rank_* files,
                # so the final name must never be visible mid-write (atomic
                # os.replace on local filesystems; object-store uploads are
                # atomic per object anyway)
                tmp = storage.join(path, f".rank_{rank}.tmp.npz")
                storage.write_bytes(tmp, buf.getvalue())
                # manifest FIRST: finalize promotes the dir as soon as all
                # rank_* files exist, and the (load-bearing) shard metadata
                # must already be inside when that happens
                if manifest is not None:
                    storage.write_json(
                        storage.join(path, f"manifest_{rank}.json"), manifest)
                storage.rename(tmp, storage.join(path, f"rank_{rank}.npz"))

            fut = self._pool.submit(write)
            self._inflight = fut
            return fut

    def wait(self):
        with self._lock:
            fut = self._inflight
        if fut is not None:
            fut.result()


def staging_dir_name(step: int, generation: int = 0) -> str:
    """THE staging-dir name format — writers (TrainWorker's staging_fn)
    and the finalizer/purger (CheckpointManager) must agree on it, or
    shards land in dirs finalize() never looks at and checkpoints silently
    stop finalizing."""
    return f".staging_checkpoint_g{generation:04d}_{step:09d}"


class CheckpointManager:
    """Tracks finalized checkpoints; retains latest + top-K by metric.

    Reference: train/v2/_internal/execution/checkpoint/checkpoint_manager.py.
    """

    def __init__(self, storage_path: str, run_name: str,
                 num_to_keep: int = 2,
                 metric: Optional[str] = None, mode: str = "min"):
        from ray_tpu.train._storage import get_storage

        self.storage = get_storage(storage_path)
        self.run_dir = self.storage.join(storage_path, run_name)
        self.storage.makedirs(self.run_dir)
        self.num_to_keep = max(1, num_to_keep)
        self.metric = metric
        self.mode = mode
        self.checkpoints: List[Checkpoint] = []
        self._load_existing()

    # -- paths ----------------------------------------------------------

    def staging_dir(self, step: int, generation: int = 0) -> str:
        """Staging dirs are scoped by gang GENERATION: a live resize
        purges only generations older than the committed one, so a
        joiner/survivor checkpoint write in flight at the commit can
        never race the purge of the previous layout's partial shards."""
        return self.storage.join(self.run_dir,
                                 staging_dir_name(step, generation))

    def final_dir(self, step: int) -> str:
        return self.storage.join(self.run_dir, f"checkpoint_{step:09d}")

    def _load_existing(self):
        """Recover the checkpoint list after a controller restart."""
        if not self.storage.isdir(self.run_dir):
            return
        for name in self.storage.listdir(self.run_dir):
            if not name.startswith("checkpoint_"):
                continue
            path = self.storage.join(self.run_dir, name)
            metrics = {}
            for f in self.storage.listdir(path):
                if f.startswith("manifest_"):
                    try:
                        metrics = self.storage.read_json(
                            self.storage.join(path, f)).get("metrics", {})
                        break
                    except (OSError, json.JSONDecodeError):
                        pass
            try:
                # rank manifests predate finalize and lack "step"; the
                # directory name is authoritative
                metrics.setdefault("step", int(name.rsplit("_", 1)[-1]))
            except ValueError:
                pass
            self.checkpoints.append(Checkpoint(path, metrics))

    # -- lifecycle ------------------------------------------------------

    def finalize(self, step: int, metrics: Dict[str, Any],
                 expected_ranks: int,
                 generation: int = 0) -> Optional[Checkpoint]:
        """Promote a staging dir once all ranks have written their shard.

        Idempotent per step: a step id can be REPORTED twice (a rank's
        local counter repeating across an elastic resize, or a restarted
        incarnation re-reporting its resume step) — the first promotion
        wins and the duplicate staging dir is dropped instead of crashing
        the controller on a rename-over-existing-dir."""
        staging = self.staging_dir(step, generation)
        final = self.final_dir(step)
        existing = next((c for c in self.checkpoints if c.path == final),
                        None)
        if not self.storage.isdir(staging):
            if self.storage.isdir(final):
                return existing or Checkpoint(final, dict(metrics))
            return None
        present = [f for f in self.storage.listdir(staging)
                   if f.startswith("rank_")]
        if len(present) < expected_ranks:
            return None
        if self.storage.isdir(final):
            # duplicate step: first promotion wins. Leave the staging dir
            # in place — ranks checkpoint with skew, and deleting it here
            # would race a slower rank's in-flight shard write (the purge
            # paths reap it once no writer can still target it).
            return existing or Checkpoint(final, dict(metrics))
        metrics = dict(metrics)
        metrics.setdefault("step", step)
        self.storage.rename(staging, final)
        ckpt = Checkpoint(final, metrics)
        self.checkpoints.append(ckpt)
        self._enforce_retention()
        return ckpt

    def _score(self, c: Checkpoint):
        if self.metric is None or self.metric not in c.metrics:
            return None
        v = float(c.metrics[self.metric])
        return -v if self.mode == "min" else v

    def _enforce_retention(self):
        if len(self.checkpoints) <= self.num_to_keep:
            return
        latest = self.checkpoints[-1]
        ranked = [c for c in self.checkpoints[:-1]]
        if self.metric is not None:
            ranked.sort(key=lambda c: (self._score(c) is None,
                                       -(self._score(c) or 0.0)))
        keep = {c.path for c in ranked[: self.num_to_keep - 1]}
        keep.add(latest.path)
        for c in list(self.checkpoints):
            if c.path not in keep:
                self.checkpoints.remove(c)
                self.storage.delete(c.path)

    def step_orphaned(self, step: int, generation: int = 0) -> bool:
        """Neither a staging dir nor a final dir exists for the step.
        Rank shard writes complete BEFORE the announcing report is queued
        (report() blocks on the writer future), so an orphaned step can
        only mean its staging dir was purged (resize commit / restart) —
        the pending entry will never finalize and should be dropped."""
        return (not self.storage.isdir(self.staging_dir(step, generation))
                and not self.storage.isdir(self.final_dir(step)))

    def purge_staging(self, below_generation: Optional[int] = None):
        """Drop partial staging dirs whose rank layout can no longer
        complete. With `below_generation`, only generations OLDER than it
        are purged — a live resize commit must never delete a dir the
        renumbered gang's writers are actively filling. Without it (a
        worker-group restart, where every writer is already dead), all
        staging dirs drop."""
        try:
            for name in self.storage.listdir(self.run_dir):
                if not name.startswith(".staging_checkpoint_"):
                    continue
                if below_generation is not None:
                    gen = 0
                    tail = name[len(".staging_checkpoint_"):]
                    if tail.startswith("g"):
                        try:
                            gen = int(tail[1:].split("_", 1)[0])
                        except ValueError:
                            pass
                    if gen >= below_generation:
                        continue
                self.storage.delete(self.storage.join(self.run_dir, name))
        except OSError:
            pass

    @property
    def latest(self) -> Optional[Checkpoint]:
        return self.checkpoints[-1] if self.checkpoints else None

    @property
    def best(self) -> Optional[Checkpoint]:
        if not self.checkpoints:
            return None
        scored = [(self._score(c), c) for c in self.checkpoints]
        with_metric = [(s, c) for s, c in scored if s is not None]
        if not with_metric:
            return self.latest
        return max(with_metric, key=lambda sc: sc[0])[1]
