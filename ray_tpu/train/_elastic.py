"""Elastic training: live gang resize instead of checkpoint-restore.

The drain/preemption plane makes planned node death a protocol; this module
makes the train stack *ride* it. When a slice drains with survivors still
holding >= ElasticScalingPolicy.min_workers, the controller does not tear
the gang down: surviving workers pause at a step boundary, the dead ranks'
state shards are re-distributed across the survivors through the object
plane (jax.Arrays stay HBM-resident via experimental/rdt.py — a shard that
keeps its holder never moves at all; only lost/overflow shards travel as
host-staged bytes), ranks and world_size are renumbered under a fresh
generation id, and training resumes. When capacity returns (node-table
"nodes" pubsub), the symmetric regrow spawns joiners that absorb shed
shards and a slice of the data-iterator assignment.

Three pieces live here:

- the pure re-shard planner (`plan_shards` / `plan_iterator` over the
  shared `rebalance` core): deterministic, retention-first assignment —
  every holder keeps what it already has up to a balanced quota, so the
  bytes that move are exactly the orphaned (dead-rank) shards plus the
  minimum overflow;
- `ElasticDataIterator`: per-rank epoch/batch/shard-assignment state with
  an explicit contract — across any shrink/regrow sequence, no sample is
  dropped or consumed twice within an epoch (remaining-sets are disjoint
  by construction and resize re-partitions exactly their union);
- `ElasticClient`: the worker-side half of the resize protocol
  (prepare -> park+publish -> commit/absorb -> resume | retire), driven by
  the controller through TrainWorker actor methods.
"""

from __future__ import annotations

import random
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional, Tuple


class ResizePlanError(RuntimeError):
    """The parked payloads cannot be re-planned live (e.g. ranks parked in
    different epochs); the controller falls back to checkpoint-restore."""


# ---------------------------------------------------------------------------
# pure planning
# ---------------------------------------------------------------------------


def rebalance(
    items_by_holder: Dict[int, List[Any]],
    rank_map: Dict[int, int],
    new_world: int,
) -> Dict[int, List[Tuple[Any, int]]]:
    """Retention-first balanced re-assignment of items across a new world.

    `items_by_holder` maps OLD rank -> items it holds; `rank_map` maps the
    surviving old ranks to their new ranks (dead/doomed old ranks are
    absent). Every new rank receives a balanced quota (total/new_world,
    +-1); a surviving holder keeps its own items up to its quota, so the
    only items that change hands are the orphans (held by non-surviving
    ranks) and the overflow above quota. Deterministic: items spill and
    fill in sorted order, ranks fill lowest-first.

    Returns new rank -> [(item, source_old_rank)].
    """
    if new_world <= 0:
        raise ResizePlanError("new world size must be positive")
    total = sum(len(v) for v in items_by_holder.values())
    quota = [total // new_world + (1 if i < total % new_world else 0)
             for i in range(new_world)]
    assigned: Dict[int, List[Tuple[Any, int]]] = {i: [] for i in range(new_world)}
    spill: List[Tuple[Any, int]] = []
    # pass 1: survivors keep their own, up to quota
    for old in sorted(items_by_holder):
        items = sorted(items_by_holder[old], key=_sort_key)
        new = rank_map.get(old)
        if new is None or new >= new_world:
            spill.extend((it, old) for it in items)  # orphaned
            continue
        keep = quota[new] - len(assigned[new])
        assigned[new].extend((it, old) for it in items[:keep])
        spill.extend((it, old) for it in items[keep:])  # overflow
    # pass 2: orphans + overflow fill the remaining quota, lowest rank first
    spill.sort(key=lambda p: _sort_key(p[0]))
    for nr in range(new_world):
        need = quota[nr] - len(assigned[nr])
        if need > 0:
            assigned[nr].extend(spill[:need])
            del spill[:need]
    if spill:  # can't happen: quotas sum to total
        raise ResizePlanError(f"rebalance left {len(spill)} unassigned items")
    return assigned


def _sort_key(item):
    if isinstance(item, (int, float)):
        return (0, item, "")
    return (1, 0, str(item))


def plan_shards(
    manifests: Dict[int, List[Any]],
    rank_map: Dict[int, int],
    new_world: int,
) -> Dict[int, List[Tuple[Any, int]]]:
    """Assign the union of all published state shards to the new world.

    `manifests` maps old rank -> the shard keys it holds (each key must be
    held by exactly one rank). Output maps new rank -> [(key, source old
    rank)]; a pair whose source maps to the same new rank is local — the
    worker already holds the shard and nothing moves."""
    seen: Dict[Any, int] = {}
    for old, keys in manifests.items():
        for k in keys:
            if k in seen:
                raise ResizePlanError(
                    f"shard key {k!r} held by both rank {seen[k]} and "
                    f"rank {old}")
            seen[k] = old
    return rebalance(manifests, rank_map, new_world)


def plan_iterator(
    states: Dict[int, Optional[dict]],
    rank_map: Dict[int, int],
    new_world: int,
) -> Dict[int, dict]:
    """Re-partition the pooled *remaining* samples of every parked rank's
    iterator across the new world. The per-epoch contract holds because
    the remaining sets are disjoint and their union is preserved exactly.

    All parked ranks must agree on (epoch, seed, num_samples, batch_size);
    a mismatch (a resize landing exactly on an epoch boundary) raises
    ResizePlanError and the controller falls back to checkpoint-restore
    rather than guessing at cross-epoch semantics."""
    live = {r: s for r, s in states.items() if s is not None}
    if not live:
        return {}
    base = next(iter(live.values()))
    for r, s in live.items():
        for key in ("epoch", "seed", "num_samples", "batch_size"):
            if s.get(key) != base.get(key):
                raise ResizePlanError(
                    f"iterator {key} mismatch at resize: rank {r} has "
                    f"{s.get(key)!r}, expected {base.get(key)!r}")
    assigned = rebalance(
        {r: list(s["samples"]) for r, s in live.items()},
        rank_map, new_world)
    global_base = sum(int(s.get("batches", 0)) for s in live.values()) + int(
        base.get("global_batch_base", 0))
    out: Dict[int, dict] = {}
    for nr in range(new_world):
        out[nr] = {
            "num_samples": base["num_samples"],
            "batch_size": base["batch_size"],
            "seed": base["seed"],
            "epoch": base["epoch"],
            "samples": [it for it, _src in assigned.get(nr, [])],
            "batches": 0,
            "global_batch_base": global_base,
        }
    return out


# ---------------------------------------------------------------------------
# data iterator
# ---------------------------------------------------------------------------


class ElasticDataIterator:
    """Deterministic per-rank sample iterator that survives gang resizes.

    Epoch `e` is a seeded permutation of range(num_samples) partitioned by
    stride across the world at `start_epoch` time; `next_batch()` consumes
    the local assignment in order and returns None once the local share of
    the epoch is exhausted (epoch advance is an explicit, coordinated call
    — auto-advance would let ranks drift across epoch boundaries and break
    the resize merge). `state()`/`from_state` are the handoff payload the
    elastic protocol moves."""

    def __init__(self, num_samples: int, batch_size: int, seed: int = 0,
                 rank: int = 0, world: int = 1):
        self.num_samples = int(num_samples)
        self.batch_size = int(batch_size)
        self.seed = int(seed)
        self.epoch = 0
        self.batches = 0            # local batches emitted this epoch
        self.global_batch_base = 0  # stamped by the resize plan
        self._remaining: List[int] = []
        self.start_epoch(0, rank=rank, world=world)

    @staticmethod
    def epoch_permutation(num_samples: int, seed: int, epoch: int) -> List[int]:
        rng = random.Random(seed * 1_000_003 + epoch)
        idx = list(range(num_samples))
        rng.shuffle(idx)
        return idx

    def start_epoch(self, epoch: int, rank: int, world: int) -> None:
        perm = self.epoch_permutation(self.num_samples, self.seed, epoch)
        self.epoch = int(epoch)
        self.batches = 0
        self._remaining = perm[rank::world]

    def next_batch(self) -> Optional[List[int]]:
        if not self._remaining:
            return None
        batch = self._remaining[: self.batch_size]
        del self._remaining[: len(batch)]
        self.batches += 1
        return batch

    @property
    def exhausted(self) -> bool:
        return not self._remaining

    @property
    def global_batch(self) -> int:
        """Monotone epoch-wide progress marker (exact while the world is
        stable; re-based from the pooled counts at each resize)."""
        return self.global_batch_base + self.batches

    def state(self) -> dict:
        return {
            "num_samples": self.num_samples,
            "batch_size": self.batch_size,
            "seed": self.seed,
            "epoch": self.epoch,
            "samples": list(self._remaining),
            "batches": self.batches,
            "global_batch_base": self.global_batch_base,
        }

    @classmethod
    def from_state(cls, state: dict) -> "ElasticDataIterator":
        it = cls.__new__(cls)
        it.num_samples = int(state["num_samples"])
        it.batch_size = int(state["batch_size"])
        it.seed = int(state["seed"])
        it.epoch = int(state["epoch"])
        it.batches = int(state.get("batches", 0))
        it.global_batch_base = int(state.get("global_batch_base", 0))
        it._remaining = list(state["samples"])
        return it


# ---------------------------------------------------------------------------
# worker-side protocol client
# ---------------------------------------------------------------------------


@dataclass
class ResizeOutcome:
    """What `ElasticClient.sync()` hands back to the train loop."""

    resized: bool = False
    retired: bool = False
    model: Any = None
    shards: Optional[Dict[Any, Any]] = None
    iterator: Optional[ElasticDataIterator] = None
    rank: int = 0
    world: int = 0
    generation: int = 0


class ElasticClient:
    """Worker-side half of the live-resize protocol.

    The TRAIN thread calls `init_or_join()` once and `sync()` every step;
    the ACTOR thread (TrainWorker methods, driven by the controller) calls
    prepare/status/commit/release/abort. A step's `sync()` is a single
    Event check when no resize is pending — the protocol costs nothing in
    steady state."""

    def __init__(self, ctx):
        self._ctx = ctx
        self._lock = threading.Lock()
        self._pending_gen: Optional[int] = None
        self._flagged = threading.Event()   # prepare() arrived
        self._parked = threading.Event()    # train thread published + waiting
        self._commit_event = threading.Event()
        self._commit: Optional[dict] = None
        self._published: Optional[dict] = None
        self._need_model = False
        self._join_spec: Optional[dict] = None
        self._done = True   # no resize in flight
        self._absorb_error: Optional[str] = None
        self.retired = False
        self.stats = {"resizes": 0, "shards_moved": 0, "joined": False}

    # -- actor-thread API (controller-driven) ---------------------------

    def prepare(self, generation: int, need_model: bool = False) -> bool:
        with self._lock:
            if self.retired:
                return False
            self._pending_gen = int(generation)
            # only the rank whose model will seed joiners pays the full
            # model staging at park (a shrink consumes no model at all)
            self._need_model = bool(need_model)
            self._commit = None
            self._commit_event.clear()
            self._parked.clear()
            self._done = False
            self._flagged.set()
        return True

    def status(self) -> dict:
        with self._lock:
            out = {
                "parked": self._parked.is_set(),
                "done": self._done,
                "failed": self._absorb_error,
                "retired": self.retired,
                "generation": self._pending_gen,
            }
            if self._parked.is_set() and self._published is not None:
                out.update(self._published)
        return out

    def commit(self, spec: dict) -> bool:
        """Deliver the controller's decision to the parked train thread."""
        with self._lock:
            if self.retired:
                return False
            if not self._parked.is_set():
                # not parked (never saw prepare's flag, or already aborted
                # locally on park timeout): only an abort is deliverable
                if spec.get("abort"):
                    self._flagged.clear()
                    self._pending_gen = None
                    self._done = True
                    return True
                return False
            self._commit = dict(spec)
            self._commit_event.set()
        return True

    def abort(self) -> bool:
        return self.commit({"abort": True})

    def release(self) -> bool:
        """Retire a doomed rank: its train thread unparks and returns."""
        return self.commit({"retire": True})

    def done(self) -> bool:
        with self._lock:
            return self._done

    # -- train-thread API ------------------------------------------------

    def init_or_join(
        self,
        init_model: Optional[Callable[[], Any]] = None,
        init_shards: Optional[Callable[[List[Any]], Dict[Any, Any]]] = None,
        shard_keys: Optional[List[Any]] = None,
        iterator: Optional[dict] = None,
    ) -> Tuple[Any, Dict[Any, Any], Optional[ElasticDataIterator]]:
        """First call of an elastic train fn: generation-0 workers build
        fresh state (their stride of `shard_keys`, a fresh iterator);
        workers joining a live run at generation N absorb the handoff
        payload the resize plan assigned them instead."""
        import ray_tpu

        spec = self._join_spec
        if spec is not None:
            self._join_spec = None
            try:
                model = (ray_tpu.get(spec["model_ref"], timeout=120)
                         if spec.get("model_ref") is not None
                         else (init_model() if init_model else None))
                shards: Dict[Any, Any] = {}
                for key, ref in spec.get("shards", []):
                    shards[key] = ray_tpu.get(ref, timeout=120)
                    self.stats["shards_moved"] += 1
            except BaseException as e:  # noqa: BLE001 — join absorb failed
                with self._lock:
                    self._absorb_error = repr(e)
                raise
            it = (ElasticDataIterator.from_state(spec["iter"])
                  if spec.get("iter") is not None else None)
            self.stats["joined"] = True
            with self._lock:
                self._done = True
            return model, shards, it
        model = init_model() if init_model else None
        keys = list(shard_keys or [])
        mine = keys[self._ctx.rank::max(1, self._ctx.world_size)]
        shards = init_shards(mine) if init_shards else {k: None for k in mine}
        it = (ElasticDataIterator(rank=self._ctx.rank,
                                  world=self._ctx.world_size, **iterator)
              if iterator is not None else None)
        return model, shards, it

    def sync(self, model: Any = None, shards: Optional[Dict[Any, Any]] = None,
             iterator: Optional[ElasticDataIterator] = None,
             park_timeout_s: float = 600.0) -> ResizeOutcome:
        """Per-step resize point. Fast path: one Event check. When a
        resize is pending: publish this rank's payload into the object
        plane, park until the controller commits, then absorb the plan
        (fetch only the shards assigned here that are not already local)
        and resume under the new (rank, world, generation)."""
        if not self._flagged.is_set():
            return ResizeOutcome(resized=False)
        import ray_tpu

        with self._lock:
            gen = self._pending_gen
            need_model = self._need_model
        if gen is None:
            self._flagged.clear()
            return ResizeOutcome(resized=False)
        shards = shards or {}
        # publish OUTSIDE the lock: staging a large model/shard set can
        # take long, and the actor thread's status()/commit() polls (the
        # controller's 30s RPC timeouts) must not block behind it. One
        # plane object per shard so absorption moves exactly the assigned
        # shards, nothing else; jax.Arrays inside stay HBM-resident (rdt),
        # the put stages host bytes for any cross-process consumer.
        published = {
            "manifest": sorted(shards, key=_sort_key),
            "shard_refs": {k: ray_tpu.put(v) for k, v in shards.items()},
            "model_ref": (ray_tpu.put(model)
                          if model is not None and need_model else None),
            "iter": iterator.state() if iterator is not None else None,
        }
        with self._lock:
            if self._pending_gen != gen:  # aborted while staging
                self._flagged.clear()
                return ResizeOutcome(resized=False)
            self._published = published
            self._parked.set()
        try:
            # slice the park wait so a controller shutdown (stop_event)
            # unparks the train thread instead of orphaning it for the
            # whole timeout; the controller otherwise always resolves a
            # park with commit/abort/release
            deadline = time.monotonic() + park_timeout_s
            committed = False
            while time.monotonic() < deadline:
                if self._commit_event.wait(timeout=0.2):
                    committed = True
                    break
                stop = getattr(self._ctx, "stop_event", None)
                if stop is not None and stop.is_set():
                    break
            with self._lock:
                spec = (self._commit or {"abort": True}) if committed \
                    else {"abort": True}
                self._commit = None
                self._commit_event.clear()
                self._parked.clear()
                self._flagged.clear()
                self._pending_gen = None
                # drop the published refs: every absorber holds its own
                # borrow / fetched copy by now (the controller sequences
                # release after all resize_done acks)
                self._published = None
            if spec.get("retire"):
                self.retired = True
                return ResizeOutcome(retired=True)
            if spec.get("abort"):
                return ResizeOutcome(resized=False)
            try:
                new_shards: Dict[Any, Any] = {}
                for entry in spec.get("shards", []):
                    key, ref = entry[0], entry[1]
                    if key in shards:
                        new_shards[key] = shards[key]  # local: nothing moves
                    else:
                        new_shards[key] = ray_tpu.get(ref, timeout=120)
                        self.stats["shards_moved"] += 1
                new_model = model
                if spec.get("model_ref") is not None:
                    new_model = ray_tpu.get(spec["model_ref"], timeout=120)
            except BaseException as e:  # noqa: BLE001 — absorb failed:
                # mark it BEFORE re-raising so the controller's
                # resize_done sweep sees a failure, not a clean "done",
                # and routes through the planned post-commit teardown
                # instead of charging the failure budget
                with self._lock:
                    self._absorb_error = repr(e)
                raise
            new_it = iterator
            if spec.get("iter") is not None and iterator is not None:
                new_it = ElasticDataIterator.from_state(spec["iter"])
            ctx = self._ctx
            ctx.rank = int(spec["rank"])
            ctx.world_size = int(spec["world"])
            ctx.generation = int(spec.get("generation", ctx.generation + 1))
            self.stats["resizes"] += 1
            return ResizeOutcome(
                resized=True, model=new_model, shards=new_shards,
                iterator=new_it, rank=ctx.rank, world=ctx.world_size,
                generation=ctx.generation)
        finally:
            with self._lock:
                self._done = True


__all__ = [
    "ElasticClient",
    "ElasticDataIterator",
    "ResizeOutcome",
    "ResizePlanError",
    "plan_iterator",
    "plan_shards",
    "rebalance",
]
