"""Actor-plane pipeline parallelism — stage actors + 1F1B microbatch
schedule over the object store.

This is the reference-shaped PP path (reference:
python/ray/dag/compiled_dag_node.py:813 — compiled actor DAGs exist to
drive PP through preallocated channels;
python/ray/experimental/channel/torch_tensor_accelerator_channel.py:1).
Each pipeline stage is an actor owning a contiguous slice of layers (+ the
embedding on the first stage, norm + LM head on the last), with jitted
forward/backward closures. Activations and gradients hand off through the
shared-memory object plane (host-staged v1; on one host the transfer is
zero-copy shm). The driver submits ops in per-stage 1F1B order; because
actor queues execute strictly in submission order and argument refs gate
delivery, the classic one-forward-one-backward interleave — bounding live
residuals per stage at (S - stage) instead of M — emerges from ordinary
task ordering, no channel protocol needed.

The in-jit SPMD pipeline (ray_tpu/parallel/pipeline.py, "pp" mesh axis +
ppermute) is the TPU-native fast path; this actor version covers the
reference's cross-process shape — stages can live in different processes,
hosts, or failure domains, and compose with the scheduler (placement
groups pin stages to nodes/slices).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu


def _slice_layers(layers: Dict[str, Any], lo: int, hi: int) -> Dict[str, Any]:
    return {k: v[lo:hi] for k, v in layers.items()}


@ray_tpu.remote
class PipelineStage:
    """One pipeline stage: a contiguous block of decoder layers.

    Stages initialize the FULL parameter tree from the same seed and keep
    only their slice — bit-identical to a single-stage run's init, which is
    what makes the loss-parity test exact (optimizer updates are
    elementwise, so per-slice AdamW == sliced full-tree AdamW).
    """

    def __init__(self, cfg, stage_id: int, n_stages: int, seed: int = 0,
                 learning_rate: float = 3e-4):
        import jax
        import jax.numpy as jnp
        import optax

        from ray_tpu.models.llama import init_params, rms_norm, rope_tables, _layer

        self.cfg = cfg
        self.sid = stage_id
        self.S = n_stages
        self.first = stage_id == 0
        self.last = stage_id == n_stages - 1
        L = cfg.n_layers
        assert L % n_stages == 0
        per = L // n_stages
        full = init_params(cfg, jax.random.key(seed))
        params: Dict[str, Any] = {
            "layers": _slice_layers(full["layers"], stage_id * per,
                                    (stage_id + 1) * per),
        }
        if self.first:
            params["tok_emb"] = full["tok_emb"]
        if self.last:
            params["norm"] = full["norm"]
            params["lm_head"] = full["lm_head"]
        self.params = params
        self.tx = optax.adamw(learning_rate)
        self.opt_state = self.tx.init(self.params)
        self._residuals: Dict[int, Any] = {}  # mb_id -> vjp closure
        self._grad_acc = None
        self._n_acc = 0
        dt = cfg.dtype

        def stage_fwd(params, x, tokens):
            """x: activations from the previous stage ((mb, s, d)) or None
            for the first stage (embeds `tokens` itself). Returns activations
            or, on the last stage, the microbatch's masked mean NLL."""
            if self.first:
                h = params["tok_emb"].astype(dt)[tokens]
            else:
                h = x.astype(dt)
            positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
            cos, sin = rope_tables(cfg, positions)
            def body(carry, lp):
                return _layer(cfg, None, carry, lp, cos, sin), None
            h, _ = jax.lax.scan(body, h, params["layers"])
            if not self.last:
                return h
            h = rms_norm(h, params["norm"], cfg.norm_eps)
            logits = (h @ params["lm_head"].astype(dt)).astype(jnp.float32)
            logp = jax.nn.log_softmax(logits, axis=-1)
            tgt = tokens[:, 1:]
            nll = -jnp.take_along_axis(
                logp[:, :-1], tgt[..., None], axis=-1)[..., 0]
            return nll.mean()

        def stage_bwd(params, x, tokens, dy):
            """Rematerialized backward: recompute the forward under vjp and
            pull gradients (per-stage activation remat — the standard PP
            memory/compute trade)."""
            _, vjp = jax.vjp(lambda p, xx: stage_fwd(p, xx, tokens), params, x)
            return vjp(dy)

        self._jax = jax
        self._jnp = jnp
        # jit both halves: un-jitted vjp retraces on EVERY microbatch and a
        # multi-second op can outlast the executor's ordering-gap timeout
        self._jfwd = jax.jit(stage_fwd)
        self._jbwd = jax.jit(stage_bwd)

    # -- schedule ops ---------------------------------------------------

    def forward(self, mb_id: int, x, tokens):
        """Run this stage's forward for microbatch `mb_id`, saving the
        (input, tokens) residuals for the rematerialized backward. Returns
        activations (or the scalar loss on the last stage)."""
        jax = self._jax
        x = None if x is None else jax.device_put(np.asarray(x))
        tokens = jax.device_put(np.asarray(tokens))
        out = self._jfwd(self.params, x, tokens)
        self._residuals[mb_id] = (x, tokens)
        if self.last:
            return float(out)
        return np.asarray(out)

    def backward(self, mb_id: int, dy=None):
        """Backward for microbatch `mb_id`; `dy` is the activation gradient
        from the next stage (None on the last stage — the loss seeds it).
        Accumulates parameter grads; returns dx for the previous stage (or
        None on the first)."""
        jax = self._jax
        jnp = self._jnp
        x, tokens = self._residuals.pop(mb_id)
        if self.last:
            seed = jnp.float32(1.0)
        else:
            seed = jax.device_put(np.asarray(dy)).astype(self.cfg.dtype)
        dparams, dx = self._jbwd(self.params, x, tokens, seed)
        if self._grad_acc is None:
            self._grad_acc = dparams
        else:
            self._grad_acc = jax.tree.map(
                lambda a, b: a + b, self._grad_acc, dparams)
        self._n_acc += 1
        if self.first:
            return None
        return np.asarray(dx)

    def apply_gradients(self):
        """Average accumulated microbatch grads and take one AdamW step."""
        import optax

        jax = self._jax
        assert self._grad_acc is not None and not self._residuals, (
            "apply_gradients before all backwards completed")
        grads = jax.tree.map(lambda g: g / self._n_acc, self._grad_acc)
        updates, self.opt_state = self.tx.update(
            grads, self.opt_state, self.params)
        self.params = optax.apply_updates(self.params, updates)
        self._grad_acc = None
        self._n_acc = 0
        return True


def _one_f_one_b_order(S: int, M: int, sid: int) -> List[tuple]:
    """Per-stage op order implementing 1F1B: warmup of (S - sid) forwards,
    then alternate backward/forward, then drain backwards."""
    warmup = min(M, S - sid)
    ops: List[tuple] = [("F", m) for m in range(warmup)]
    nf, nb = warmup, 0
    while nb < M:
        ops.append(("B", nb))
        nb += 1
        if nf < M:
            ops.append(("F", nf))
            nf += 1
    return ops


class ActorPipeline:
    """Driver-side handle: S stage actors + the 1F1B step schedule."""

    def __init__(self, cfg, n_stages: int, n_microbatches: int,
                 learning_rate: float = 3e-4, seed: int = 0,
                 stage_options: Optional[List[dict]] = None):
        self.S = n_stages
        self.M = n_microbatches
        self.stages = []
        for s in range(n_stages):
            klass = PipelineStage
            if stage_options and stage_options[s]:
                klass = PipelineStage.options(**stage_options[s])
            self.stages.append(klass.remote(
                cfg, s, n_stages, seed=seed, learning_rate=learning_rate))

    def train_step(self, tokens: np.ndarray, timeout: float = 300.0) -> float:
        """One synchronous optimizer step over `tokens` (B, seq); B % M == 0.
        Returns the mean microbatch loss."""
        B = tokens.shape[0]
        assert B % self.M == 0
        mbs = tokens.reshape(self.M, B // self.M, -1)
        S, M = self.S, self.M

        fwd_out: Dict[tuple, Any] = {}   # (sid, mb) -> activation/loss ref
        bwd_out: Dict[tuple, Any] = {}   # (sid, mb) -> dx ref
        # submit in per-stage 1F1B order; refs gate cross-stage dependencies
        # and actor queues serialize per-stage execution in this exact order
        pending: Dict[int, List[tuple]] = {
            s: _one_f_one_b_order(S, M, s) for s in range(S)}
        done: Dict[int, int] = {s: 0 for s in range(S)}
        while any(done[s] < len(pending[s]) for s in range(S)):
            progressed = False
            for s in range(S):
                while done[s] < len(pending[s]):
                    op, m = pending[s][done[s]]
                    if op == "F":
                        x = None if s == 0 else fwd_out.get((s - 1, m))
                        if s > 0 and x is None:
                            break  # predecessor forward not yet submitted
                        fwd_out[(s, m)] = self.stages[s].forward.remote(
                            m, x, mbs[m])
                    else:
                        dy = None if s == S - 1 else bwd_out.get((s + 1, m))
                        if s < S - 1 and dy is None:
                            break  # successor backward not yet submitted
                        bwd_out[(s, m)] = self.stages[s].backward.remote(m, dy)
                    done[s] += 1
                    progressed = True
            assert progressed, "1F1B schedule wedged (cyclic dependency?)"
        losses = ray_tpu.get(
            [fwd_out[(S - 1, m)] for m in range(M)], timeout=timeout)
        ray_tpu.get(
            [st.apply_gradients.remote() for st in self.stages],
            timeout=timeout)
        return float(np.mean(losses))

    def shutdown(self):
        for st in self.stages:
            try:
                ray_tpu.kill(st)
            except Exception:  # noqa: BLE001 — already dead
                pass


class CompiledActorPipeline:
    """1F1B pipeline driven through the COMPILED graph path: the whole
    per-step schedule (S stages × M microbatches of forward/backward +
    the optimizer application) is compiled ONCE into per-actor executor
    loops connected by preallocated shm channels — zero task submissions
    per train step (reference: compiled_dag_node.py:813, whose purpose is
    exactly this PP drive; VERDICT r3 next #2).

    The DAG is authored in per-stage 1F1B order, which the compiled plan
    preserves per actor, so the memory profile matches ActorPipeline."""

    def __init__(self, cfg, n_stages: int, n_microbatches: int,
                 learning_rate: float = 3e-4, seed: int = 0,
                 slot_size: int = 8 << 20,
                 stage_options: Optional[List[dict]] = None):
        from ray_tpu.dag import InputNode, MultiOutputNode

        self.S = S = n_stages
        self.M = M = n_microbatches
        self.stages = []
        for s in range(n_stages):
            klass = PipelineStage
            if stage_options and stage_options[s]:
                # e.g. label_selector pinning stages to nodes: cross-node
                # activation/grad edges then ride RemoteChannel
                klass = PipelineStage.options(**stage_options[s])
            self.stages.append(klass.remote(
                cfg, s, n_stages, seed=seed, learning_rate=learning_rate))
        fwd: Dict[tuple, Any] = {}
        bwd: Dict[tuple, Any] = {}
        with InputNode() as inp:
            pending = {s: _one_f_one_b_order(S, M, s) for s in range(S)}
            done = {s: 0 for s in range(S)}
            while any(done[s] < len(pending[s]) for s in range(S)):
                progressed = False
                for s in range(S):
                    while done[s] < len(pending[s]):
                        op, m = pending[s][done[s]]
                        if op == "F":
                            if s > 0 and (s - 1, m) not in fwd:
                                break
                            x = None if s == 0 else fwd[(s - 1, m)]
                            fwd[(s, m)] = self.stages[s].forward.bind(
                                m, x, inp[m])
                        else:
                            if s < S - 1 and (s + 1, m) not in bwd:
                                break
                            dy = None if s == S - 1 else bwd[(s + 1, m)]
                            bwd[(s, m)] = self.stages[s].backward.bind(m, dy)
                        done[s] += 1
                        progressed = True
                assert progressed, "1F1B authoring wedged"
            applies = [st.apply_gradients.bind() for st in self.stages]
            # stage-0 backwards are sinks (their dx is None): they must be
            # targets or the compile-time DFS would drop the whole backward
            # chain from the plan
            dag = MultiOutputNode(
                [fwd[(S - 1, m)] for m in range(M)]
                + [bwd[(0, m)] for m in range(M)] + applies)
        self._compiled = dag.experimental_compile(
            max_in_flight=2, slot_size=slot_size)

    def train_step(self, tokens: np.ndarray, timeout: float = 300.0) -> float:
        B = tokens.shape[0]
        assert B % self.M == 0
        mbs = tokens.reshape(self.M, B // self.M, -1)
        out = self._compiled.execute(
            {m: mbs[m] for m in range(self.M)}).get(timeout=timeout)
        return float(np.mean(out[:self.M]))

    def shutdown(self):
        try:
            self._compiled.teardown()
        except Exception:  # noqa: BLE001 — loops may be dead
            pass
        for st in self.stages:
            try:
                ray_tpu.kill(st)
            except Exception:  # noqa: BLE001 — already dead
                pass
