"""Worker group: the gang of training worker actors + synchronization actor.

Reference: python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:88 (create/poll/shutdown lifecycle over a placement group)
and checkpoint/sync_actor.py (barrier/broadcast among workers).

TPU-first redesign: the group is placed either on a STRICT_SPREAD placement
group of per-worker bundles (CPU / one-process-per-host) or on TPU slices via
ray_tpu.tpu.slice.SlicePlacementGroup; rank-0's node becomes the
jax.distributed coordinator, and the MEGASCALE/coordinator env vars are
injected exactly as the reference's JaxConfig does
(reference: python/ray/train/v2/jax/config.py:60-121).
"""

from __future__ import annotations

import queue
import socket
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train import _context as ctx_mod


@ray_tpu.remote
class SyncActor:
    """Barrier + rank-0 broadcast rendezvous (reference: sync_actor.py)."""

    def __init__(self):
        self._counts: Dict[str, int] = {}
        self._gen: Dict[str, int] = {}
        self._kv: Dict[str, Any] = {}

    async def barrier(self, name: str, world_size: int):
        import asyncio

        self._counts[name] = self._counts.get(name, 0) + 1
        gen = self._gen.get(name, 0)
        if self._counts[name] >= world_size:
            self._counts[name] = 0
            self._gen[name] = gen + 1
            return True
        while self._gen.get(name, 0) == gen:
            await asyncio.sleep(0.01)
        return True

    async def put(self, key: str, value: Any):
        self._kv[key] = value
        return True

    async def wait_for(self, key: str, poll_s: float = 0.01):
        import asyncio

        while key not in self._kv:
            await asyncio.sleep(poll_s)
        return self._kv[key]


@ray_tpu.remote
class TrainWorker:
    """One training process. Runs the user's train fn on a thread with a
    TrainContext installed; buffers reports for the controller's polls."""

    def __init__(self, rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_path: str,
                 run_dir: str):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.run_dir = run_dir
        self._thread: Optional[threading.Thread] = None
        self._ctx: Optional[ctx_mod.TrainContext] = None
        self._error: Optional[str] = None
        self._done = False

    def node_ip(self) -> str:
        return socket.gethostbyname(socket.gethostname())

    def start(self, train_fn_pickled: bytes, config: Optional[dict],
              latest_checkpoint: Optional[dict],
              sync_actor, env_vars: Optional[Dict[str, str]] = None) -> bool:
        import os

        import cloudpickle

        # cloudpickle: the user's train fn is typically a closure/local def,
        # beyond plain pickle (same treatment as exported remote functions)
        train_fn = cloudpickle.loads(train_fn_pickled)
        if env_vars:
            os.environ.update(env_vars)
        staging_fn = (
            lambda step: f"{self.run_dir}/.staging_checkpoint_{step:09d}"
        )
        ctx = ctx_mod.TrainContext(
            rank=self.rank, world_size=self.world_size,
            local_rank=self.local_rank, node_rank=self.node_rank,
            run_name=self.run_name, storage_path=self.storage_path,
            staging_dir_fn=staging_fn,
            latest_checkpoint=(
                Checkpoint.from_wire(latest_checkpoint)
                if latest_checkpoint else None
            ),
        )
        ctx._sync_client = sync_actor
        self._ctx = ctx

        def run():
            ctx_mod.set_context(ctx)
            try:
                if config is not None:
                    train_fn(config)
                else:
                    train_fn()
            except BaseException:  # noqa: BLE001 — reported to controller
                self._error = traceback.format_exc()
            finally:
                self._done = True
                ctx_mod.set_context(None)

        self._thread = threading.Thread(target=run, name="train-fn", daemon=True)
        self._thread.start()
        return True

    def poll(self) -> dict:
        """Drain buffered reports; include liveness/error state."""
        reports = []
        if self._ctx is not None:
            while True:
                try:
                    reports.append(self._ctx.report_queue.get_nowait())
                except queue.Empty:
                    break
        return {"reports": reports, "done": self._done, "error": self._error}

    def stop(self) -> bool:
        if self._ctx is not None:
            self._ctx.stop_event.set()
        return True

    def flush_checkpoints(self) -> bool:
        """Block until any in-flight async checkpoint write lands."""
        if self._ctx is not None:
            self._ctx._writer.wait()
        return True


@dataclass
class WorkerStatus:
    alive: bool
    done: bool = False
    error: Optional[str] = None
    reports: List[dict] = field(default_factory=list)
    # hex id of the node that hosted the worker, resolved for dead workers
    # so the controller can ask "was THAT node draining?" instead of
    # treating any drain anywhere in the cluster as the cause
    node_id: Optional[str] = None


class WorkerGroup:
    """Creates, polls, and tears down the gang of TrainWorker actors."""

    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 run_name: str, storage_path: str, run_dir: str,
                 use_tpu_slices: bool = False, topology: str = "",
                 accelerator_type: str = ""):
        self.num_workers = num_workers
        self.resources_per_worker = dict(resources_per_worker)
        self.run_name = run_name
        self.storage_path = storage_path
        self.run_dir = run_dir
        self.use_tpu_slices = use_tpu_slices
        self.topology = topology
        self.accelerator_type = accelerator_type
        self.workers: List[Any] = []
        self.sync_actor = None
        self._pg = None
        self._slice_pg = None

    # -- lifecycle ------------------------------------------------------

    def create(self, latest_checkpoint: Optional[Checkpoint] = None):
        from ray_tpu.util.placement_group import placement_group

        # unique name per ATTEMPT: a retry after a failed creation must not
        # collide with (and bind to) the previous attempt's still-dying
        # named actor — that surfaced as "actor failed to start:
        # ray_tpu.kill" under full-suite load. Discovery is by handle (the
        # workers receive it in start()); the name is only for debugging.
        import os as _os

        self.sync_actor = SyncActor.options(
            name=f"{self.run_name}-sync-{_os.urandom(4).hex()}",
            namespace="_train",
        ).remote()

        if self.use_tpu_slices:
            from ray_tpu.tpu.slice import slice_placement_group

            self._slice_pg = slice_placement_group(
                pod_type=self.accelerator_type, num_slices=1,
                topology=self.topology,
            )
            self._slice_pg.ready()
            pg = self._slice_pg.placement_group
        else:
            pg = placement_group(
                [dict(self.resources_per_worker)
                 for _ in range(self.num_workers)],
                strategy="SPREAD",
            )
            if not pg.ready(timeout=120):
                raise TimeoutError("worker-group placement group not ready")
        self._pg = pg

        self.workers = [
            TrainWorker.options(
                resources=self.resources_per_worker,
                placement_group=pg, placement_group_bundle_index=i,
            ).remote(
                rank=i, world_size=self.num_workers, local_rank=0,
                node_rank=i, run_name=self.run_name,
                storage_path=self.storage_path, run_dir=self.run_dir,
            )
            for i in range(self.num_workers)
        ]
        # rank-0's host becomes the jax.distributed coordinator
        ips = ray_tpu.get([w.node_ip.remote() for w in self.workers],
                          timeout=120)
        coordinator = f"{ips[0]}:{_pick_port(self.run_name)}"
        env_base = {
            "RT_TRAIN_COORDINATOR": coordinator,
            "RT_TRAIN_WORLD_SIZE": str(self.num_workers),
        }
        self._env_base = env_base
        self._latest = latest_checkpoint
        return self

    def start_training(self, train_fn: Callable, config: Optional[dict]):
        import cloudpickle

        fn_bytes = cloudpickle.dumps(train_fn)
        wire_ckpt = self._latest.to_wire() if self._latest else None
        starts = []
        for i, w in enumerate(self.workers):
            env = dict(self._env_base)
            env["RT_TRAIN_RANK"] = str(i)
            starts.append(w.start.remote(
                fn_bytes, config, wire_ckpt, self.sync_actor, env))
        ray_tpu.get(starts, timeout=120)

    def poll(self) -> List[WorkerStatus]:
        out: List[WorkerStatus] = []
        refs = [w.poll.remote() for w in self.workers]
        for i, ref in enumerate(refs):
            try:
                r = ray_tpu.get(ref, timeout=60)
                out.append(WorkerStatus(alive=True, done=r["done"],
                                        error=r["error"], reports=r["reports"]))
            except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError,
                    ray_tpu.GetTimeoutError) as e:
                out.append(WorkerStatus(alive=False, error=str(e),
                                        node_id=self._worker_node(i)))
        return out

    def _worker_node(self, idx: int) -> Optional[str]:
        """Last node that hosted worker `idx` (the actor record keeps its
        node_id after death)."""
        try:
            from ray_tpu._private.core_worker import get_core_worker

            cw = get_core_worker()
            info = cw.run_sync(cw.control.call(
                "get_actor_info",
                {"actor_id": self.workers[idx]._actor_id.binary()}),
                10)["actor"]
            nid = info.get("node_id")
            return nid.hex() if nid else None
        except Exception:  # noqa: BLE001 — control store unreachable
            return None

    def flush_checkpoints(self):
        try:
            ray_tpu.get(
                [w.flush_checkpoints.remote() for w in self.workers],
                timeout=300,
            )
        except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError):
            pass

    def shutdown(self):
        for w in self.workers:
            try:
                w.stop.remote()
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.2)
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        if self.sync_actor is not None:
            try:
                ray_tpu.kill(self.sync_actor)
            except Exception:  # noqa: BLE001
                pass
        if self._slice_pg is not None:
            try:
                self._slice_pg.remove()
            except Exception:  # noqa: BLE001
                pass
        elif self._pg is not None:
            try:
                from ray_tpu.util.placement_group import remove_placement_group

                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []


def _pick_port(seed: str) -> int:
    return 20000 + (hash(seed) % 20000)
