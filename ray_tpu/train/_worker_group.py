"""Worker group: the gang of training worker actors + synchronization actor.

Reference: python/ray/train/v2/_internal/execution/worker_group/
worker_group.py:88 (create/poll/shutdown lifecycle over a placement group)
and checkpoint/sync_actor.py (barrier/broadcast among workers).

TPU-first redesign: the group is placed either on a STRICT_SPREAD placement
group of per-worker bundles (CPU / one-process-per-host) or on TPU slices via
ray_tpu.tpu.slice.SlicePlacementGroup; rank-0's node becomes the
jax.distributed coordinator, and the MEGASCALE/coordinator env vars are
injected exactly as the reference's JaxConfig does
(reference: python/ray/train/v2/jax/config.py:60-121).

Elastic extension: the group is GENERATION-aware. A live resize (see
train/_elastic.py) renumbers ranks in place — surviving actors are reused,
never recreated — under a monotonically increasing generation id. All
SyncActor barriers and rendezvous keys are scoped by that generation, so a
straggler from generation N can neither satisfy nor poison generation
N+1's barriers: its calls fail fast with a stale-generation error.
"""

from __future__ import annotations

import logging
import queue
import socket
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint
from ray_tpu.train import _context as ctx_mod
from ray_tpu.train import _elastic

logger = logging.getLogger(__name__)


@ray_tpu.remote
class SyncActor:
    """Barrier + rank-0 broadcast rendezvous (reference: sync_actor.py),
    scoped by gang generation: `advance_generation` (called by the
    controller when a live resize commits) invalidates every in-flight
    wait from older generations — parked waiters wake and raise instead
    of satisfying a barrier the resized gang will never complete."""

    def __init__(self):
        self._counts: Dict[tuple, int] = {}
        self._rounds: Dict[tuple, int] = {}
        self._kv: Dict[tuple, Any] = {}
        self._generation = 0

    def _check_gen(self, generation: int):
        if generation < self._generation:
            raise RuntimeError(
                f"stale gang generation {generation} (current: "
                f"{self._generation}) — this worker was resized out or "
                f"has not absorbed the resize yet")

    async def _await_gen(self, generation: int):
        """Stale generations fail fast; FUTURE generations wait — a joiner
        starts at generation N+1 and may reach a barrier before the
        controller's advance_generation commit lands (the commit always
        follows: joiners only exist because a resize is in flight)."""
        import asyncio

        self._check_gen(generation)
        while generation > self._generation:
            await asyncio.sleep(0.01)
        self._check_gen(generation)

    async def barrier(self, name: str, world_size: int, generation: int = 0):
        import asyncio

        await self._await_gen(generation)
        key = (generation, name)
        self._counts[key] = self._counts.get(key, 0) + 1
        rnd = self._rounds.get(key, 0)
        if self._counts[key] >= world_size:
            self._counts[key] = 0
            self._rounds[key] = rnd + 1
            return True
        while self._rounds.get(key, 0) == rnd:
            self._check_gen(generation)  # a resize landed mid-wait
            await asyncio.sleep(0.01)
        return True

    async def put(self, key: str, value: Any, generation: int = 0):
        await self._await_gen(generation)
        self._kv[(generation, key)] = value
        return True

    async def wait_for(self, key: str, poll_s: float = 0.01,
                       generation: int = 0):
        import asyncio

        await self._await_gen(generation)
        while (generation, key) not in self._kv:
            self._check_gen(generation)
            await asyncio.sleep(poll_s)
        return self._kv[(generation, key)]

    async def advance_generation(self, generation: int):
        """Commit point of a live resize: bump the generation and drop
        stale barrier rounds/kv so generation-N stragglers fail fast
        (their wait loops observe the bump and raise)."""
        if generation <= self._generation:
            return False
        self._generation = generation
        for d in (self._counts, self._rounds, self._kv):
            for k in [k for k in d if k[0] < generation]:
                del d[k]
        return True

    async def generation(self) -> int:
        return self._generation


@ray_tpu.remote
class TrainWorker:
    """One training process. Runs the user's train fn on a thread with a
    TrainContext installed; buffers reports for the controller's polls.
    The elastic resize protocol (prepare/status/commit/release) is driven
    through actor methods while the train thread runs — parking happens
    cooperatively at the train fn's next `elastic.sync()` call."""

    def __init__(self, rank: int, world_size: int, local_rank: int,
                 node_rank: int, run_name: str, storage_path: str,
                 run_dir: str):
        self.rank = rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.node_rank = node_rank
        self.run_name = run_name
        self.storage_path = storage_path
        self.run_dir = run_dir
        self._thread: Optional[threading.Thread] = None
        self._ctx: Optional[ctx_mod.TrainContext] = None
        self._error: Optional[str] = None
        self._done = False

    def node_ip(self) -> str:
        return socket.gethostbyname(socket.gethostname())

    def host_node_id(self) -> str:
        """Hex id of the node daemon that spawned this worker process —
        ground truth for the controller's drain blast-radius mapping (the
        actor-table record can lag placement)."""
        import os

        return os.environ.get("RT_NODE_ID", "")

    def start(self, train_fn_pickled: bytes, config: Optional[dict],
              latest_checkpoint: Optional[dict],
              sync_actor, env_vars: Optional[Dict[str, str]] = None,
              elastic: bool = False, generation: int = 0,
              elastic_join: Optional[dict] = None) -> bool:
        import os

        import cloudpickle

        # cloudpickle: the user's train fn is typically a closure/local def,
        # beyond plain pickle (same treatment as exported remote functions)
        train_fn = cloudpickle.loads(train_fn_pickled)
        if env_vars:
            os.environ.update(env_vars)
        # generation-scoped at WRITE time (ctx.generation moves with each
        # committed resize), so a resize purge of older generations can
        # never race these writes
        from ray_tpu.train._checkpoint import staging_dir_name

        staging_fn = (
            lambda step: f"{self.run_dir}/"
                         f"{staging_dir_name(step, ctx.generation)}"
        )
        ctx = ctx_mod.TrainContext(
            rank=self.rank, world_size=self.world_size,
            local_rank=self.local_rank, node_rank=self.node_rank,
            run_name=self.run_name, storage_path=self.storage_path,
            staging_dir_fn=staging_fn,
            latest_checkpoint=(
                Checkpoint.from_wire(latest_checkpoint)
                if latest_checkpoint else None
            ),
        )
        ctx._sync_client = sync_actor
        ctx.generation = generation
        if elastic or elastic_join is not None:
            ctx.elastic = _elastic.ElasticClient(ctx)
            if elastic_join is not None:
                ctx.elastic._join_spec = dict(elastic_join)
                with ctx.elastic._lock:
                    ctx.elastic._done = False
        self._ctx = ctx

        def run():
            ctx_mod.set_context(ctx)
            try:
                if config is not None:
                    train_fn(config)
                else:
                    train_fn()
            except BaseException:  # noqa: BLE001 — reported to controller
                self._error = traceback.format_exc()
            finally:
                self._done = True
                ctx_mod.set_context(None)

        self._thread = threading.Thread(target=run, name="train-fn", daemon=True)
        self._thread.start()
        return True

    def poll(self) -> dict:
        """Drain buffered reports; include liveness/error state."""
        reports = []
        if self._ctx is not None:
            while True:
                try:
                    reports.append(self._ctx.report_queue.get_nowait())
                except queue.Empty:
                    break
        return {"reports": reports, "done": self._done, "error": self._error}

    def stop(self) -> bool:
        if self._ctx is not None:
            self._ctx.stop_event.set()
        return True

    def flush_checkpoints(self) -> bool:
        """Block until any in-flight async checkpoint write lands."""
        if self._ctx is not None:
            self._ctx._writer.wait()
        return True

    # -- elastic resize protocol (controller-driven) --------------------

    def _elastic_client(self):
        if self._ctx is None or self._ctx.elastic is None:
            return None
        return self._ctx.elastic

    def prepare_resize(self, generation: int, need_model: bool = False) -> bool:
        client = self._elastic_client()
        if client is None:
            return False
        return client.prepare(generation, need_model)

    def resize_status(self) -> dict:
        client = self._elastic_client()
        out = client.status() if client is not None else {"parked": False,
                                                          "done": True}
        out["training_done"] = self._done
        out["rank"] = self._ctx.rank if self._ctx else self.rank
        return out

    def commit_resize(self, spec: dict) -> bool:
        client = self._elastic_client()
        return client.commit(spec) if client is not None else False

    def abort_resize(self) -> bool:
        client = self._elastic_client()
        return client.abort() if client is not None else True

    def release_resize(self) -> bool:
        client = self._elastic_client()
        return client.release() if client is not None else False

    def resize_done(self) -> bool:
        client = self._elastic_client()
        return client.done() if client is not None else True

    def elastic_stats(self) -> dict:
        client = self._elastic_client()
        return dict(client.stats) if client is not None else {}


@dataclass
class WorkerStatus:
    alive: bool
    done: bool = False
    error: Optional[str] = None
    reports: List[dict] = field(default_factory=list)
    # hex id of the node that hosted the worker, resolved for dead workers
    # so the controller can ask "was THAT node draining?" instead of
    # treating any drain anywhere in the cluster as the cause
    node_id: Optional[str] = None


class WorkerGroup:
    """Creates, polls, resizes, and tears down the gang of TrainWorker
    actors. `live_resize` reuses surviving actors in place — the teardown/
    recreate path is the fallback, not the norm."""

    def __init__(self, num_workers: int, resources_per_worker: Dict[str, float],
                 run_name: str, storage_path: str, run_dir: str,
                 use_tpu_slices: bool = False, topology: str = "",
                 accelerator_type: str = "", elastic: bool = False):
        self.num_workers = num_workers
        self.resources_per_worker = dict(resources_per_worker)
        self.run_name = run_name
        self.storage_path = storage_path
        self.run_dir = run_dir
        self.use_tpu_slices = use_tpu_slices
        self.topology = topology
        self.accelerator_type = accelerator_type
        self.elastic = elastic
        self.workers: List[Any] = []        # index == current rank
        self.worker_nodes: List[Optional[str]] = []
        self.generation = 0      # last COMMITTED generation
        # every attempt burns a fresh generation number, committed or not:
        # an aborted attempt's killed joiner may have left barrier/kv calls
        # parked in the SyncActor at its generation — reusing the number
        # would let that residue poison the retry (phantom barrier counts,
        # stale rendezvous values). advance_generation purges strictly
        # older keys only.
        self._attempt_gen = 0
        # final reports drained from ranks retired by a live resize — the
        # next poll() hands them to the controller; killing a doomed actor
        # must not lose the (reported) samples it consumed before parking
        self._stashed_reports: List[dict] = []
        self.sync_actor = None
        self._pg = None
        self._slice_pg = None
        self._fn_bytes: Optional[bytes] = None
        self._config: Optional[dict] = None

    # -- lifecycle ------------------------------------------------------

    def create(self, latest_checkpoint: Optional[Checkpoint] = None):
        from ray_tpu.util.placement_group import placement_group

        # unique name per ATTEMPT: a retry after a failed creation must not
        # collide with (and bind to) the previous attempt's still-dying
        # named actor — that surfaced as "actor failed to start:
        # ray_tpu.kill" under full-suite load. Discovery is by handle (the
        # workers receive it in start()); the name is only for debugging.
        import os as _os

        placement = self._sync_actor_placement()
        self.sync_actor = SyncActor.options(
            name=f"{self.run_name}-sync-{_os.urandom(4).hex()}",
            namespace="_train",
            **placement,
        ).remote()
        if placement:
            # the anti-spot selector was chosen from a SNAPSHOT: if the
            # last non-spot node left between the check and placement, the
            # selector is unmatchable and the actor queues infeasible
            # forever. Probe readiness; on expiry RE-CHECK feasibility —
            # only a genuinely all-spot cluster falls back to
            # unconstrained placement (a merely slow scheduler must not
            # silently trade away the anti-spot protection).
            try:
                ray_tpu.get(self.sync_actor.generation.remote(), timeout=20)
            except (ray_tpu.GetTimeoutError, ray_tpu.ActorDiedError,
                    ray_tpu.ActorUnavailableError):
                if self._sync_actor_placement():
                    logger.warning(
                        "anti-spot SyncActor slow to place but non-spot "
                        "capacity still exists — keeping the constraint")
                else:
                    logger.warning(
                        "anti-spot SyncActor placement infeasible "
                        "(non-spot capacity gone) — falling back to "
                        "unconstrained placement")
                    try:
                        ray_tpu.kill(self.sync_actor)
                    except Exception:  # noqa: BLE001
                        pass
                    self.sync_actor = SyncActor.options(
                        name=f"{self.run_name}-sync-{_os.urandom(4).hex()}",
                        namespace="_train",
                    ).remote()

        if self.use_tpu_slices:
            from ray_tpu.tpu.slice import slice_placement_group

            self._slice_pg = slice_placement_group(
                pod_type=self.accelerator_type, num_slices=1,
                topology=self.topology,
            )
            self._slice_pg.ready()
            pg = self._slice_pg.placement_group
        elif self.elastic:
            # no gang placement group: a PG fate-shares every bundle with
            # every bundle's node — one drained node would take the whole
            # (healthy) gang down with "placement group returned" exactly
            # when the live resize wants the survivors untouched. Elastic
            # workers schedule individually (drain_cooperative below keeps
            # the control store's drain migration off them too: the
            # controller owns their planned-removal handling).
            pg = None
        else:
            pg = placement_group(
                [dict(self.resources_per_worker)
                 for _ in range(self.num_workers)],
                strategy="SPREAD",
            )
            if not pg.ready(timeout=120):
                raise TimeoutError("worker-group placement group not ready")
        self._pg = pg

        self.workers = [
            self._worker_options(pg=pg, bundle_index=i).remote(
                rank=i, world_size=self.num_workers, local_rank=0,
                node_rank=i, run_name=self.run_name,
                storage_path=self.storage_path, run_dir=self.run_dir,
            )
            for i in range(self.num_workers)
        ]
        # rank-0's host becomes the jax.distributed coordinator
        ips = ray_tpu.get([w.node_ip.remote() for w in self.workers],
                          timeout=120)
        coordinator = f"{ips[0]}:{_pick_port(self.run_name)}"
        env_base = {
            "RT_TRAIN_COORDINATOR": coordinator,
            "RT_TRAIN_WORLD_SIZE": str(self.num_workers),
        }
        self._env_base = env_base
        self._latest = latest_checkpoint
        self._resolve_worker_nodes()
        return self

    @staticmethod
    def _sync_actor_placement() -> Dict[str, Any]:
        """Pin the rendezvous/barrier actor OFF spot/preemptible capacity
        (nodes labeled spot=true / preemptible=true): every elastic resize
        rendezvouses through the SyncActor, so losing it to a reclaimed
        spot node mid-resize turns a planned shrink into a full
        checkpoint-restore. Anti-affinity via the "!value" label selector;
        falls back to unconstrained placement when every usable node
        carries the marker (an all-spot cluster must still train).
        Implementation shared with the other coordination singletons in
        `_private/spot.py`."""
        from ray_tpu._private.spot import anti_spot_placement

        return anti_spot_placement("the rendezvous SyncActor")

    def _worker_options(self, pg=None, bundle_index: int = -1):
        opts: Dict[str, Any] = {"resources": self.resources_per_worker}
        if pg is not None:
            opts["placement_group"] = pg
            opts["placement_group_bundle_index"] = bundle_index
        if self.elastic:
            opts["drain_cooperative"] = True
        return TrainWorker.options(**opts)

    def start_training(self, train_fn: Callable, config: Optional[dict]):
        import cloudpickle

        self._fn_bytes = cloudpickle.dumps(train_fn)
        self._config = config
        wire_ckpt = self._latest.to_wire() if self._latest else None
        starts = []
        for i, w in enumerate(self.workers):
            env = dict(self._env_base)
            env["RT_TRAIN_RANK"] = str(i)
            starts.append(w.start.remote(
                self._fn_bytes, config, wire_ckpt, self.sync_actor, env,
                self.elastic, self.generation))
        ray_tpu.get(starts, timeout=120)

    def poll(self) -> List[WorkerStatus]:
        out: List[WorkerStatus] = []
        if self._stashed_reports:
            out.append(WorkerStatus(alive=True, done=True,
                                    reports=self._stashed_reports))
            self._stashed_reports = []
        refs = [w.poll.remote() for w in self.workers]
        for i, ref in enumerate(refs):
            try:
                r = ray_tpu.get(ref, timeout=60)
                out.append(WorkerStatus(alive=True, done=r["done"],
                                        error=r["error"], reports=r["reports"],
                                        node_id=(self.worker_nodes[i]
                                                 if i < len(self.worker_nodes)
                                                 else None)))
            except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError,
                    ray_tpu.GetTimeoutError) as e:
                logger.info("worker rank %d (gen %d, node %s) poll failed: %s",
                            i, self.generation,
                            (self.worker_nodes[i] or "?")[:12]
                            if i < len(self.worker_nodes) else "?", e)
                out.append(WorkerStatus(alive=False, error=str(e),
                                        node_id=self._worker_node(i)))
        return out

    def _worker_node(self, idx: int) -> Optional[str]:
        """Last node that hosted worker `idx` (the actor record keeps its
        node_id after death)."""
        try:
            from ray_tpu._private.core_worker import get_core_worker

            cw = get_core_worker()
            info = cw.run_sync(cw.control.call(
                "get_actor_info",
                {"actor_id": self.workers[idx]._actor_id.binary()}),
                10)["actor"]
            nid = info.get("node_id")
            return nid.hex() if nid else None
        except Exception:  # noqa: BLE001 — control store unreachable
            return None

    def _resolve_worker_nodes(self):
        """Map each worker to its hosting node (drain notices name nodes;
        the controller needs worker-level blast radius). Asks each LIVE
        worker for its own RT_NODE_ID — the actor-table record can lag
        placement, and a wrong mapping here would shrink away the healthy
        half of the gang."""
        nodes: List[Optional[str]] = []
        try:
            resolved = ray_tpu.get(
                [w.host_node_id.remote() for w in self.workers], timeout=60)
            nodes = [r or None for r in resolved]
        except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError,
                ray_tpu.GetTimeoutError):
            nodes = [self._worker_node(i) for i in range(len(self.workers))]
        self.worker_nodes = nodes

    def flush_checkpoints(self):
        try:
            ray_tpu.get(
                [w.flush_checkpoints.remote() for w in self.workers],
                timeout=300,
            )
        except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError):
            pass

    # -- live resize ----------------------------------------------------

    def live_resize(self, keep: List[int], add: int = 0,
                    park_timeout_s: float = 20.0) -> str:
        """Resize the gang in place: survivors (current ranks in `keep`)
        are renumbered 0..len(keep)-1 and reused; `add` joiners are
        spawned at the tail ranks. Returns:

        - "ok"      — resize committed; the group now has the new shape
        - "aborted" — nothing changed (park timed out, plan infeasible,
                      training already finishing); safe to continue
        - "failed"  — the resize died after the commit point (a survivor
                      or joiner was lost mid-absorption); the gang is in
                      an undefined shape and must go through teardown

        Protocol (see train/_elastic.py): prepare -> all workers park at
        a step boundary and publish their shard/iterator payload into the
        object plane -> plan (retention-first, only lost/overflow shards
        assigned across processes) -> joiners spawn+absorb -> generation
        advances -> survivors commit+absorb -> doomed ranks released.
        Everything that can fail cleanly happens before the commit point.
        """
        keep = sorted(keep)
        new_world = len(keep) + add
        if not self.elastic or new_world <= 0:
            return "aborted"
        self._attempt_gen += 1
        gen = self._attempt_gen
        keep_set = set(keep)
        doomed = [i for i in range(len(self.workers)) if i not in keep_set]
        logger.info(
            "live resize gen %d: %d -> %d workers (keep=%s, +%d joiners)",
            gen, len(self.workers), new_world, keep, add)

        for i, w in enumerate(self.workers):
            # only the lowest surviving rank stages its model — it seeds
            # joiners; nothing consumes a model on a pure shrink
            w.prepare_resize.remote(
                gen, bool(add > 0 and keep and i == keep[0]))

        # 1. wait for every current worker to park (abort early if one
        #    finishes training or dies — both make the resize moot)
        statuses = self._await_parked(park_timeout_s)
        if statuses is None:
            self._abort_resize()
            return "aborted"

        # 1b. drain the doomed ranks' final reports while they are parked
        #     (nothing new arrives past the park): killing them after
        #     release must not lose the samples they consumed+reported
        if doomed:
            try:
                finals = ray_tpu.get(
                    [self.workers[i].poll.remote() for i in doomed],
                    timeout=30)
                for r in finals:
                    self._stashed_reports.extend(r.get("reports") or [])
            except Exception as e:  # noqa: BLE001 — a doomed worker died
                logger.warning("doomed-rank report drain failed: %s", e)
                self._abort_resize()
                return "aborted"

        # 2. plan: shards + iterator over the published payloads
        rank_map = {old: new for new, old in enumerate(keep)}
        try:
            shard_plan = _elastic.plan_shards(
                {i: list(st.get("manifest") or []) for i, st in
                 statuses.items()},
                rank_map, new_world)
            iter_plan = _elastic.plan_iterator(
                {i: st.get("iter") for i, st in statuses.items()},
                rank_map, new_world)
        except _elastic.ResizePlanError as e:
            logger.warning("live resize plan infeasible: %s", e)
            self._abort_resize()
            return "aborted"
        ref_of = {i: st.get("shard_refs") or {} for i, st in statuses.items()}
        # the lowest surviving rank's published model seeds joiners
        model_src = statuses[keep[0]].get("model_ref") if keep else None

        def spec_for(new_rank: int, joiner: bool) -> dict:
            shards = []
            for key, src in shard_plan.get(new_rank, []):
                local = (not joiner) and rank_map.get(src) == new_rank
                shards.append([key, None if local else ref_of[src].get(key)])
            return {
                "generation": gen, "rank": new_rank, "world": new_world,
                "shards": shards, "iter": iter_plan.get(new_rank),
                "model_ref": model_src if joiner else None,
            }

        # 3. joiners spawn and absorb BEFORE the commit point: a joiner
        #    that fails to start aborts the resize with survivors still
        #    parked and nothing renumbered. All starts are issued together
        #    — the gang is paused for the SLOWEST joiner, not the sum.
        joiners: List[Any] = []
        try:
            starts = []
            for j in range(add):
                nr = len(keep) + j
                w = self._worker_options().remote(
                    rank=nr, world_size=new_world, local_rank=0,
                    node_rank=nr, run_name=self.run_name,
                    storage_path=self.storage_path, run_dir=self.run_dir,
                )
                env = dict(self._env_base)
                env["RT_TRAIN_RANK"] = str(nr)
                env["RT_TRAIN_WORLD_SIZE"] = str(new_world)
                starts.append(w.start.remote(
                    self._fn_bytes, self._config, None, self.sync_actor,
                    env, True, gen, spec_for(nr, joiner=True)))
                joiners.append(w)
            if starts:
                ray_tpu.get(starts, timeout=120)
            if joiners and not self._await_done(joiners, park_timeout_s):
                raise TimeoutError("joiners never absorbed the handoff")
            # re-validate right before the point of no return: a survivor
            # whose park wait expired during a slow joiner spawn silently
            # resumed — committing would renumber a gang that is already
            # running at the old shape
            sts = ray_tpu.get(
                [self.workers[i].resize_status.remote() for i in keep],
                timeout=30)
            if not all(st.get("parked") for st in sts):
                raise TimeoutError("a survivor unparked before commit")
        except Exception as e:  # noqa: BLE001 — pre-commit: clean abort
            logger.warning("live resize aborted before commit: %s", e)
            self._kill_workers(joiners)
            self._abort_resize()
            return "aborted"

        # ---- commit point ------------------------------------------------
        # 4. the generation advances (stale-gen barrier calls now fail
        #    fast), then survivors renumber and absorb
        try:
            ray_tpu.get(self.sync_actor.advance_generation.remote(gen),
                        timeout=30)
            survivors = [self.workers[i] for i in keep]
            acks = [w.commit_resize.remote(spec_for(nr, joiner=False))
                    for nr, w in enumerate(survivors)]
            if not all(ray_tpu.get(acks, timeout=60)):
                raise RuntimeError("a survivor rejected the resize commit")
            if not self._await_done(survivors, max(park_timeout_s, 60.0)):
                raise TimeoutError("survivors never finished absorbing")
        except Exception as e:  # noqa: BLE001 — post-commit: poisoned
            logger.error("live resize failed after commit: %s", e)
            # the joiners are not yet in self.workers: reap them here or
            # they outlive the teardown, squat on gang resources, and
            # keep writing shard files into the run's staging dirs
            self._kill_workers(joiners)
            return "failed"

        # 5. release the doomed ranks so their train fns return cleanly
        #    inside the drain window: await the release ack (the commit is
        #    delivered to the parked thread) and then give the train fn a
        #    beat to unwind its finally blocks — an immediate kill races
        #    the un-awaited release through the control plane and cuts
        #    user cleanup off. Bounded tightly: the node is dying anyway.
        doomed_workers = [self.workers[i] for i in doomed]
        try:
            ray_tpu.get([w.release_resize.remote() for w in doomed_workers],
                        timeout=10)
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                polls = ray_tpu.get([w.poll.remote() for w in doomed_workers],
                                    timeout=10)
                for p in polls:
                    # anything reported during unwind still reaches the
                    # controller (the pre-park payload was stashed earlier)
                    self._stashed_reports.extend(p.get("reports") or [])
                if all(p.get("done") for p in polls):
                    break
                time.sleep(0.05)
        except Exception:  # noqa: BLE001 — a doomed worker died mid-release
            pass
        self._kill_workers(doomed_workers)

        self.workers = [self.workers[i] for i in keep] + joiners
        self.num_workers = new_world
        self.generation = gen
        self._env_base["RT_TRAIN_WORLD_SIZE"] = str(new_world)
        self._resolve_worker_nodes()
        logger.info("live resize gen %d committed: world=%d", gen, new_world)
        return "ok"

    def _await_parked(self, timeout_s: float) -> Optional[Dict[int, dict]]:
        """Poll resize_status until every worker is parked with a payload.
        None => abort (timeout, a death, or training finishing)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                sts = ray_tpu.get(
                    [w.resize_status.remote() for w in self.workers],
                    timeout=30)
            except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError,
                    ray_tpu.GetTimeoutError) as e:
                logger.warning("worker lost while parking for resize: %s", e)
                return None
            if any(st.get("training_done") for st in sts):
                return None  # the run is ending; let it end
            if all(st.get("parked") for st in sts):
                return dict(enumerate(sts))
            time.sleep(0.05)
        logger.warning("live resize park timed out after %.1fs", timeout_s)
        return None

    def _await_done(self, workers: List[Any], timeout_s: float) -> bool:
        """True only when every worker finished its absorb CLEANLY — a
        worker whose absorb raised reports failed (done alone would read
        as success and let the resize destroy the unabsorbed shards'
        last copies)."""
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            try:
                sts = ray_tpu.get(
                    [w.resize_status.remote() for w in workers], timeout=30)
            except (ray_tpu.ActorDiedError, ray_tpu.ActorUnavailableError,
                    ray_tpu.GetTimeoutError):
                return False
            failed = [st.get("failed") for st in sts if st.get("failed")]
            if failed:
                logger.warning("resize absorb failed: %s", failed[0])
                return False
            if all(st.get("done") for st in sts):
                return True
            time.sleep(0.05)
        return False

    @staticmethod
    def _kill_workers(workers: List[Any]):
        for w in workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass

    def _abort_resize(self):
        for w in self.workers:
            try:
                w.abort_resize.remote()
            except Exception:  # noqa: BLE001
                pass

    def shutdown(self):
        for w in self.workers:
            try:
                w.stop.remote()
            except Exception:  # noqa: BLE001
                pass
        time.sleep(0.2)
        for w in self.workers:
            try:
                ray_tpu.kill(w)
            except Exception:  # noqa: BLE001
                pass
        if self.sync_actor is not None:
            try:
                ray_tpu.kill(self.sync_actor)
            except Exception:  # noqa: BLE001
                pass
        if self._slice_pg is not None:
            try:
                self._slice_pg.remove()
            except Exception:  # noqa: BLE001
                pass
        elif self._pg is not None:
            try:
                from ray_tpu.util.placement_group import remove_placement_group

                remove_placement_group(self._pg)
            except Exception:  # noqa: BLE001
                pass
        self.workers = []


def _pick_port(seed: str) -> int:
    return 20000 + (hash(seed) % 20000)
