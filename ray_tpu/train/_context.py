"""Worker-side training context: rank info, report(), barrier, checkpoint.

Reference surface: ray.train.get_context() / ray.train.report
(python/ray/train/v2/_internal/execution/context.py, train_loop_utils).
The context is installed by the TrainWorker actor before the user's
train_loop_per_worker runs on its thread.
"""

from __future__ import annotations

import os
import queue
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

from ray_tpu.train._checkpoint import AsyncCheckpointWriter, Checkpoint

_local = threading.local()


@dataclass
class TrainContext:
    rank: int
    world_size: int
    local_rank: int
    node_rank: int
    run_name: str
    storage_path: str
    staging_dir_fn: Any  # step -> staging dir path
    latest_checkpoint: Optional[Checkpoint] = None
    report_queue: "queue.Queue[dict]" = field(default_factory=queue.Queue)
    stop_event: threading.Event = field(default_factory=threading.Event)
    _writer: AsyncCheckpointWriter = field(default_factory=AsyncCheckpointWriter)
    _sync_client: Any = None  # SyncActor handle, set by the worker
    # live-resize state: the gang generation this worker currently belongs
    # to (rank/world_size above are REWRITTEN by a committed resize) and
    # the worker-side protocol client (None for non-elastic runs)
    generation: int = 0
    elastic: Any = None

    # -- public API (mirrors ray.train.*) -------------------------------

    def get_world_size(self) -> int:
        return self.world_size

    def get_world_rank(self) -> int:
        return self.rank

    def get_generation(self) -> int:
        """Gang generation: bumped by every committed live resize."""
        return self.generation

    def get_local_rank(self) -> int:
        return self.local_rank

    def get_node_rank(self) -> int:
        return self.node_rank

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self.latest_checkpoint

    def should_stop(self) -> bool:
        """Cooperative stop signal (controller shutdown / preemption)."""
        return self.stop_event.is_set()

    def report(self, metrics: Dict[str, Any],
               checkpoint_state: Optional[Any] = None) -> None:
        """Report metrics (and optionally save a checkpoint shard).

        `checkpoint_state` is a pytree of jax/numpy arrays; it is snapshotted
        to host synchronously and written asynchronously to the run's staging
        directory for the reported step. The controller finalizes the
        checkpoint once every rank's shard has landed.
        """
        entry: Dict[str, Any] = {"metrics": dict(metrics), "rank": self.rank,
                                 "generation": self.generation}
        if checkpoint_state is not None:
            step = int(metrics.get("step", 0))
            staging = self.staging_dir_fn(step)
            fut = self._writer.save(
                checkpoint_state, staging, rank=self.rank,
                manifest={"metrics": dict(metrics), "rank": self.rank,
                          "world_size": self.world_size,
                          "generation": self.generation},
            )
            fut.result()  # surface write errors at the report site
            entry["checkpoint_step"] = step
        self.report_queue.put(entry)

    def barrier(self, name: str = "default", timeout: float = 300.0) -> None:
        """Block until every worker in the group reaches this barrier.

        Barriers are scoped by the gang generation: a straggler from
        generation N can never satisfy (or poison) generation N+1's
        barriers — its call fails fast with a stale-generation error."""
        if self._sync_client is None:
            return
        import ray_tpu

        ray_tpu.get(
            self._sync_client.barrier.remote(
                name, self.world_size, self.generation),
            timeout=timeout,
        )

    def broadcast_from_rank_zero(self, name: str, value: Any = None,
                                 timeout: float = 300.0) -> Any:
        """Rank 0 contributes `value`; every rank returns it. Rendezvous
        keys are generation-scoped like barriers."""
        if self._sync_client is None:
            return value
        import ray_tpu

        if self.rank == 0:
            ray_tpu.get(
                self._sync_client.put.remote(name, value, self.generation),
                timeout=timeout)
        return ray_tpu.get(
            self._sync_client.wait_for.remote(name, 0.01, self.generation),
            timeout=timeout)


def get_context() -> TrainContext:
    ctx = getattr(_local, "ctx", None)
    if ctx is None:
        raise RuntimeError(
            "ray_tpu.train.get_context() called outside a train worker"
        )
    return ctx


def set_context(ctx: Optional[TrainContext]) -> None:
    _local.ctx = ctx


def report(metrics: Dict[str, Any],
           checkpoint_state: Optional[Any] = None) -> None:
    get_context().report(metrics, checkpoint_state=checkpoint_state)
