"""Scaling and failure policies for the Train controller.

Reference: python/ray/train/v2/_internal/execution/scaling_policy/{fixed,
elastic}.py and failure_handling/ — the controller consults the scaling
policy for a target worker-group size and the failure policy for whether a
failure is retryable.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Union

from ray_tpu._private import protocol as pb


@dataclass
class ScalingDecision:
    num_workers: int
    reason: str = ""


def usable_cluster_resources(
    nodes: List[dict],
    death_fresh_window_s: float = 120.0,
    now: Optional[float] = None,
) -> Dict[str, float]:
    """Capacity a worker group can actually be (re)placed on.

    A raw `cluster_resources()` sum over-counts during a planned removal:
    DRAINING nodes still appear in the node table (and a node that just
    received a drain notice may briefly still read ALIVE), so a post-drain
    re-create would target a width the shrunken cluster can't hold and
    immediately resize again. Subtract every node that is DRAINING, is
    carrying a drain reason, or has a fresh expected-death record before
    computing the fit."""
    now = time.time() if now is None else now
    total: Dict[str, float] = {}
    for n in nodes:
        if n.get("state") != "ALIVE":
            continue  # DEAD and DRAINING nodes host nothing new
        if pb.is_sim_node(n.get("labels")):
            continue  # scale-harness nodes can't host real workers
        if n.get("drain_reason"):
            continue  # notice landed, state transition racing
        death = n.get("death")
        if (death and death.get("expected")
                and now - death.get("ts", 0.0) < death_fresh_window_s):
            continue  # going away: a record beat the state field
        for k, v in (n.get("resources") or {}).items():
            total[k] = total.get(k, 0.0) + v
    return total


class ScalingPolicy:
    def target_size(self, cluster_cpus, resources_per_worker) -> ScalingDecision:
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    def target_size(self, cluster_cpus, resources_per_worker):
        return ScalingDecision(self.num_workers, "fixed")


class ElasticScalingPolicy(ScalingPolicy):
    """Size the group to what the cluster can currently hold, within
    [min_workers, max_workers] (reference: scaling_policy/elastic.py).

    `cluster_resources` may be a full {resource: amount} dict (preferred:
    the fit respects every requested resource shape, e.g. custom "spot" or
    "TPU" markers, not just CPU) or a bare CPU count for compatibility.
    Feed it `usable_cluster_resources(...)` — sizing against a raw
    cluster sum counts DRAINING nodes and targets a width the cluster
    can't actually hold."""

    def __init__(self, min_workers: int, max_workers: int):
        assert 1 <= min_workers <= max_workers
        self.min_workers = min_workers
        self.max_workers = max_workers

    def target_size(self, cluster_resources: Union[float, Dict[str, float]],
                    resources_per_worker):
        if not isinstance(cluster_resources, dict):
            cluster_resources = {"CPU": float(cluster_resources)}
        per = {k: float(v) for k, v in (resources_per_worker or {}).items()
               if float(v) > 0}
        if not per:
            per = {"CPU": 1.0}
        fit = min(
            int(cluster_resources.get(k, 0.0) // v) for k, v in per.items()
        )
        n = max(self.min_workers, min(self.max_workers, fit))
        return ScalingDecision(n, f"elastic fit={fit}")


@dataclass
class FailurePolicy:
    """Retry budget for worker-group failures (reference: FailureConfig)."""

    max_failures: int = 0  # -1 = unlimited

    def decide(self, failure_count: int) -> bool:
        """True = retry (recreate the group), False = raise."""
        if self.max_failures == -1:
            return True
        return failure_count <= self.max_failures
