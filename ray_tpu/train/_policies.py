"""Scaling and failure policies for the Train controller.

Reference: python/ray/train/v2/_internal/execution/scaling_policy/{fixed,
elastic}.py and failure_handling/ — the controller consults the scaling
policy for a target worker-group size and the failure policy for whether a
failure is retryable.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class ScalingDecision:
    num_workers: int
    reason: str = ""


class ScalingPolicy:
    def target_size(self, cluster_cpus: float,
                    resources_per_worker: dict) -> ScalingDecision:
        raise NotImplementedError


class FixedScalingPolicy(ScalingPolicy):
    def __init__(self, num_workers: int):
        self.num_workers = num_workers

    def target_size(self, cluster_cpus, resources_per_worker):
        return ScalingDecision(self.num_workers, "fixed")


class ElasticScalingPolicy(ScalingPolicy):
    """Size the group to what the cluster can currently hold, within
    [min_workers, max_workers] (reference: scaling_policy/elastic.py)."""

    def __init__(self, min_workers: int, max_workers: int):
        assert 1 <= min_workers <= max_workers
        self.min_workers = min_workers
        self.max_workers = max_workers

    def target_size(self, cluster_cpus, resources_per_worker):
        per = max(float(resources_per_worker.get("CPU", 1.0)), 1e-9)
        fit = int(cluster_cpus // per)
        n = max(self.min_workers, min(self.max_workers, fit))
        return ScalingDecision(n, f"elastic fit={fit}")


@dataclass
class FailurePolicy:
    """Retry budget for worker-group failures (reference: FailureConfig)."""

    max_failures: int = 0  # -1 = unlimited

    def decide(self, failure_count: int) -> bool:
        """True = retry (recreate the group), False = raise."""
        if self.max_failures == -1:
            return True
        return failure_count <= self.max_failures
