"""ray_tpu.train — distributed training on TPU gangs.

Capability parity with Ray Train v2 (reference: python/ray/train/v2/):
controller + worker group + scaling/failure policies + checkpoint manager +
report/barrier, with a JaxTrainer as the TPU-native flagship entry point.
"""

from ray_tpu.train._checkpoint import (
    AsyncCheckpointWriter,
    Checkpoint,
    CheckpointManager,
)
from ray_tpu.train._context import TrainContext, get_context, report
from ray_tpu.train._controller import TrainController, TrainResult
from ray_tpu.train._elastic import (
    ElasticClient,
    ElasticDataIterator,
    ResizeOutcome,
)
from ray_tpu.train._policies import (
    ElasticScalingPolicy,
    FailurePolicy,
    FixedScalingPolicy,
    usable_cluster_resources,
)
from ray_tpu.train._worker_group import SyncActor, TrainWorker, WorkerGroup
from ray_tpu.train.trainer import (
    CheckpointConfig,
    DataParallelTrainer,
    FailureConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
    TrainingFailedError,
    setup_jax_distributed,
)

__all__ = [
    "AsyncCheckpointWriter",
    "Checkpoint",
    "CheckpointConfig",
    "CheckpointManager",
    "DataParallelTrainer",
    "ElasticClient",
    "ElasticDataIterator",
    "ElasticScalingPolicy",
    "ResizeOutcome",
    "FailureConfig",
    "FailurePolicy",
    "FixedScalingPolicy",
    "JaxTrainer",
    "RunConfig",
    "ScalingConfig",
    "SyncActor",
    "TrainContext",
    "TrainController",
    "TrainResult",
    "TrainWorker",
    "TrainingFailedError",
    "WorkerGroup",
    "get_context",
    "report",
    "usable_cluster_resources",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("train")
del _rlu
