"""Train controller: the run loop driving worker groups to completion.

Reference: python/ray/train/v2/_internal/execution/controller/controller.py:105
— the controller owns the worker-group lifecycle: consult the scaling policy,
create the group, poll it, finalize checkpoints as rank shards land, and on
failure consult the failure policy, tear down, and re-create (resuming from
the latest finalized checkpoint).

Redesigned driver-side (a plain object run by Trainer.fit) rather than as a
detached actor: the TPU framework's north-star path is a single driver owning
a slice gang, and driver-failure isolation can be layered on by running fit()
itself inside an actor.

Elastic extension: for ElasticScalingPolicy runs the controller adds a
RESIZING state between RUNNING and the teardown path. A planned removal
(drain/preemption notice, observed on the node table and the "nodes"
pubsub) with enough survivors triggers a LIVE SHRINK — the gang pauses at
a step boundary, the doomed ranks' state shards re-shard across survivors
through the object plane, ranks renumber under a new generation, and
training resumes without ever tearing down. When the autoscaler restores
capacity, the symmetric REGROW spawns joiners that absorb shed shards.
Teardown + checkpoint-restore remains the fallback for everything
unplanned (and for train fns that never reach an elastic sync point).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu.train._checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train._policies import (
    ElasticScalingPolicy,
    FailurePolicy,
    ScalingPolicy,
    usable_cluster_resources,
)
from ray_tpu.train._worker_group import WorkerGroup, WorkerStatus

logger = logging.getLogger(__name__)


@dataclass
class TrainResult:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    best_checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_config: Optional[dict],
        scaling_policy: ScalingPolicy,
        failure_policy: FailurePolicy,
        resources_per_worker: Dict[str, float],
        run_name: str,
        storage_path: str,
        checkpoint_manager: CheckpointManager,
        use_tpu_slices: bool = False,
        topology: str = "",
        accelerator_type: str = "",
        poll_interval_s: float = 0.2,
    ):
        self.train_fn = train_fn
        self.train_config = train_config
        self.scaling_policy = scaling_policy
        self.failure_policy = failure_policy
        self.resources_per_worker = resources_per_worker
        self.run_name = run_name
        self.storage_path = storage_path
        self.ckpt = checkpoint_manager
        self.use_tpu_slices = use_tpu_slices
        self.topology = topology
        self.accelerator_type = accelerator_type
        self.poll_interval_s = poll_interval_s
        self.failure_count = 0
        # planned-removal rejoins (drain/preemption): checkpoint-then-rejoin,
        # never charged against the failure policy's budget — a preempted
        # node is the dominant production "failure" and must be a non-event.
        # Bounded separately so a drain loop can't retry forever.
        self.drain_rejoins = 0
        self.max_drain_rejoins = int(
            GLOBAL_CONFIG.get("train_max_drain_rejoins"))
        # live resizes (elastic): shrink/regrow without teardown; bounded by
        # the same knob as drain rejoins (both are planned-removal budget)
        self.resizes = 0
        self.shrinks = 0
        self.regrows = 0
        self.state = "CREATED"
        self._group: Optional[WorkerGroup] = None
        # checkpoint steps reported but not yet finalized, keyed by
        # (gang generation, step) — staging dirs are generation-scoped so
        # a resize can purge the old layout without racing live writers
        self._pending_ckpt: Dict[tuple, Dict[str, Any]] = {}
        # resize trigger plumbing: the "nodes" pubsub listener flips the
        # dirty flag so a drain notice is acted on within one poll tick;
        # the periodic node-table read is the floor under notice loss
        self._nodes_dirty = threading.Event()
        self._next_node_check = 0.0
        self._no_resize_until = 0.0
        self._next_regrow = 0.0

    @staticmethod
    def _is_planned_removal(cause: Optional[str]) -> bool:
        """A worker lost to a graceful drain or preemption notice — the
        structured death reasons name the drain (\"node draining
        (preemption)\", \"drained (autoscaler)\") — is a planned rejoin,
        not a crash."""
        if not cause:
            return False
        c = cause.lower()
        return "drain" in c or "preempt" in c

    @staticmethod
    def _drain_in_progress(node_ids=None, terminal_only=False) -> bool:
        """Notice-driven planned-failure detection: a worker can die with a
        generic connection error before the structured death cause
        propagates, so also consult the node table — a DRAINING node, or a
        fresh expected-termination record, means the loss was planned.
        When the dead workers' nodes are known, only THOSE nodes count: an
        unrelated idle-node drain elsewhere in the cluster must not mask a
        genuine crash (which would silently bypass the failure budget).
        With terminal_only, a reversible (no-deadline) drain doesn't count
        either — only deadline-carrying drains kill workers, so callers
        with no node scoping available (group creation) use this to keep a
        routine idle-drain from masking a genuinely bad config."""
        wanted = {n for n in (node_ids or []) if n}
        fresh_s = float(GLOBAL_CONFIG.get("train_expected_death_fresh_s"))
        try:
            for n in ray_tpu.nodes():
                if wanted and n.get("node_id") not in wanted:
                    continue
                if n.get("state") in ("DRAINING", "PREEMPTING") and (
                        not terminal_only or n.get("drain_deadline")):
                    return True
                death = n.get("death")
                if (death and death.get("expected")
                        and time.time() - death.get("ts", 0.0) < fresh_s):
                    return True
        except Exception:  # noqa: BLE001 — control store unreachable
            return False
        return False

    # -- helpers --------------------------------------------------------

    def _elastic_live(self) -> bool:
        # slice gangs are excluded: a TPU slice placement group fate-shares
        # every bundle with every bundle's host, so an in-place resize
        # would be undone the moment the drained host's bundle releases
        # (and a joiner would land off-slice with no MEGASCALE peering) —
        # slice-topology reshape goes through checkpoint-restore until
        # jax.distributed re-init is wired (ROADMAP item 4 follow-up)
        return (isinstance(self.scaling_policy, ElasticScalingPolicy)
                and bool(GLOBAL_CONFIG.get("train_live_resize"))
                and not self.use_tpu_slices)

    def _nodes(self) -> List[dict]:
        try:
            return ray_tpu.nodes()
        except Exception:  # noqa: BLE001 — control store unreachable
            return []

    def _usable_resources(self) -> Dict[str, float]:
        """Capacity the group can actually target: DRAINING nodes and
        fresh expected-death records are excluded, so a post-drain
        (re)create never sizes for a width the shrunken cluster can't
        hold (and immediately resizes again)."""
        res = usable_cluster_resources(
            self._nodes(),
            float(GLOBAL_CONFIG.get("train_expected_death_fresh_s")))
        return res or {"CPU": 1.0}

    def _make_group(self) -> WorkerGroup:
        decision = self.scaling_policy.target_size(
            self._usable_resources(), self.resources_per_worker
        )
        logger.info("worker group size %d (%s)", decision.num_workers,
                    decision.reason)
        group = WorkerGroup(
            num_workers=decision.num_workers,
            resources_per_worker=self.resources_per_worker,
            run_name=self.run_name,
            storage_path=self.storage_path,
            run_dir=self.ckpt.run_dir,
            use_tpu_slices=self.use_tpu_slices,
            topology=self.topology,
            accelerator_type=self.accelerator_type,
            elastic=self._elastic_live(),
        )
        try:
            group.create(latest_checkpoint=self.ckpt.latest)
            group.start_training(self.train_fn, self.train_config)
        except BaseException:
            # a half-created group leaks its named sync actor, workers, and
            # placement group — the retry then collides on the name / starves
            # on resources and fails with a confusing actor-kill cause
            try:
                group.shutdown()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            raise
        return group

    def _ingest_reports(self, statuses: List[WorkerStatus],
                        result: TrainResult, world_size: int):
        """Collect metrics; finalize checkpoints once all rank shards landed."""
        for st in statuses:
            for rep in st.reports:
                result.metrics_history.append(rep["metrics"])
                if rep["metrics"]:
                    result.metrics = rep["metrics"]
                if "checkpoint_step" in rep:
                    key = (int(rep.get("generation", 0)),
                           rep["checkpoint_step"])
                    self._pending_ckpt[key] = rep["metrics"]
        for gen, step in sorted(self._pending_ckpt):
            ckpt = self.ckpt.finalize(
                step, self._pending_ckpt[(gen, step)],
                expected_ranks=world_size, generation=gen,
            )
            if ckpt is not None:
                del self._pending_ckpt[(gen, step)]
                result.checkpoint = ckpt
                logger.info("checkpoint finalized: %s", ckpt.path)
            elif self.ckpt.step_orphaned(step, gen):
                # reports queued before a resize commit can land AFTER the
                # purge (stashed doomed-rank reports, survivors' buffered
                # polls) and resurrect a step whose staging dir is gone —
                # shard writes complete before the report is queued, so
                # "neither staging nor final exists" can only mean purged
                del self._pending_ckpt[(gen, step)]

    # -- live resize triggers -------------------------------------------

    def _resize_trigger(self, group: WorkerGroup):
        """Decide whether the gang should resize NOW. Returns
        ("shrink", keep_indices, 0), ("grow", keep_indices, add) or None.

        Shrink: a worker sits on a node that is DRAINING with a deadline
        (preemption/autoscaler/manual removal) and enough workers survive
        to stay >= min_workers. The check runs BEFORE any worker dies —
        the whole point is to use the drain window to move shards while
        their holders are still alive.

        Grow: usable capacity (DRAINING and freshly-dead-expected nodes
        excluded) fits more workers than the gang currently has, bounded
        by the policy and rate-limited by the regrow cooldown."""
        if not self._elastic_live() or not group.elastic:
            return None
        now = time.monotonic()
        if now < self._no_resize_until:
            return None
        if not self._nodes_dirty.is_set() and now < self._next_node_check:
            return None
        self._nodes_dirty.clear()
        self._next_node_check = now + float(
            GLOBAL_CONFIG.get("train_node_watch_period_s"))
        nodes = self._nodes()
        if not nodes:
            return None
        by_id = {n["node_id"]: n for n in nodes}
        doomed = []
        for i, nid in enumerate(group.worker_nodes):
            rec = by_id.get(nid) if nid else None
            # PREEMPTING counts: a reclaim notice carries its deadline
            # before any drain starts — shrinking during the notice window
            # moves shards while their holders are certainly alive, and
            # the regrow lands on the autoscaler's pre-provisioned
            # replacement instead of waiting out a node boot
            if (rec is not None
                    and rec.get("state") in ("DRAINING", "PREEMPTING")
                    and rec.get("drain_deadline")):
                doomed.append(i)
        if doomed:
            keep = [i for i in range(len(group.workers)) if i not in doomed]
            if keep and len(keep) >= self.scaling_policy.min_workers:
                return ("shrink", keep, 0)
            return None  # below the floor: teardown path will handle it
        if now < self._next_regrow:
            return None
        fresh_s = float(GLOBAL_CONFIG.get("train_expected_death_fresh_s"))
        decision = self.scaling_policy.target_size(
            usable_cluster_resources(nodes, fresh_s),
            self.resources_per_worker)
        self._report_train_demand(decision.num_workers)
        add = decision.num_workers - group.num_workers
        if add > 0:
            self._next_regrow = now + float(
                GLOBAL_CONFIG.get("train_regrow_cooldown_s"))
            return ("grow", list(range(len(group.workers))), add)
        return None

    def _report_train_demand(self, target_now: int):
        """Elastic-train autoscaler hook: when the policy's max exceeds
        what usable capacity can host, push the missing width into the
        control store's demand aggregate (report_demand, TTL'd) so the
        demand-driven autoscaler provisions toward the run's ceiling
        instead of waiting for lease pileups. Empty shapes withdraw the
        entry once capacity catches up."""
        ceiling = int(getattr(self.scaling_policy, "max_workers", 0) or 0)
        missing = max(0, ceiling - target_now)
        try:
            from ray_tpu._private.core_worker import get_core_worker

            cw = get_core_worker()
            ttl = 3.0 * float(
                GLOBAL_CONFIG.get("train_node_watch_period_s"))
            cw.run_sync(cw.control.call("report_demand", {
                "key": f"elastic_train:{self.run_name}",
                "shapes": [dict(self.resources_per_worker)] * missing,
                "ttl_s": ttl,
            }), 5)
        except Exception:  # noqa: BLE001 — demand hints must never
            pass           # perturb training

    def _try_live_resize(self, group: WorkerGroup, trigger) -> str:
        kind, keep, add = trigger
        if self.drain_rejoins + self.resizes >= self.max_drain_rejoins:
            logger.warning(
                "live %s skipped: planned-removal budget exhausted "
                "(%d rejoins + %d resizes)", kind, self.drain_rejoins,
                self.resizes)
            # same cooldown as a failed attempt: a still-DRAINING node
            # would otherwise re-trigger (and re-log) this every watch tick
            self._no_resize_until = time.monotonic() + float(
                GLOBAL_CONFIG.get("train_resize_park_timeout_s"))
            return "aborted"
        self.state = "RESIZING"
        try:
            verdict = group.live_resize(
                keep, add,
                park_timeout_s=float(
                    GLOBAL_CONFIG.get("train_resize_park_timeout_s")))
        finally:
            self.state = "RUNNING"
        if verdict == "ok":
            self.resizes += 1
            if kind == "shrink":
                self.shrinks += 1
            else:
                self.regrows += 1
            # in-flight staging shards were written under the OLD rank
            # layout; the resized gang re-checkpoints from its live state.
            # Generation-targeted: writers of the committed generation may
            # already be filling THEIR staging dirs (joiners train during
            # the survivor-commit window) and must not be raced.
            self._pending_ckpt.clear()
            self.ckpt.purge_staging(below_generation=group.generation)
            logger.info(
                "live %s committed: world=%d generation=%d", kind,
                group.num_workers, group.generation)
        else:
            # don't hammer prepare/park against a gang that can't resize
            # (non-elastic train fn, plan infeasible): one attempt per
            # park window
            self._no_resize_until = time.monotonic() + float(
                GLOBAL_CONFIG.get("train_resize_park_timeout_s"))
        return verdict

    # -- run loop -------------------------------------------------------

    def run(self) -> TrainResult:
        result = TrainResult()
        listener = None
        cw = None
        if self._elastic_live():
            try:
                from ray_tpu._private.core_worker import get_core_worker

                cw = get_core_worker()

                def _notice(message, flag=self._nodes_dirty):
                    flag.set()

                cw.add_node_listener(_notice)
                listener = _notice
            except Exception:  # noqa: BLE001 — polling floor still works
                cw = None
        try:
            return self._run(result)
        finally:
            self.state = "DONE"
            if cw is not None and listener is not None:
                try:
                    cw.remove_node_listener(listener)
                except Exception:  # noqa: BLE001
                    pass

    def _run(self, result: TrainResult) -> TrainResult:
        while True:
            self.state = "SCHEDULING"
            try:
                self._group = self._make_group()
            except Exception as e:  # noqa: BLE001 — group creation failed
                if (self._drain_in_progress(terminal_only=True)
                        and self.drain_rejoins < self.max_drain_rejoins):
                    # creation raced a terminal drain (workers died on the
                    # leaving node mid-start): retry without spending the
                    # budget — reversible idle-drains kill nothing and must
                    # not mask a genuinely bad config
                    self.drain_rejoins += 1
                    time.sleep(0.5)
                    continue
                self.failure_count += 1
                if not self.failure_policy.decide(self.failure_count):
                    result.error = f"worker group creation failed: {e}"
                    return result
                time.sleep(min(2.0 ** self.failure_count * 0.2, 10.0))
                continue

            group = self._group
            failed = False
            planned = False
            self.state = "RUNNING"
            try:
                while True:
                    statuses = group.poll()
                    self._ingest_reports(statuses, result, group.num_workers)
                    dead = [s for s in statuses if not s.alive]
                    errored = [s for s in statuses if s.error and s.alive]
                    if dead or errored:
                        failed = True
                        cause = (dead or errored)[0].error
                        # only a LOST worker can be drain-caused: an
                        # application error in a live worker must charge the
                        # failure budget even while some node is draining
                        planned = bool(dead) and (
                            self._is_planned_removal(cause)
                            or self._drain_in_progress(
                                [s.node_id for s in dead]))
                        if planned:
                            logger.info(
                                "worker lost to planned node removal "
                                "(drain/preemption); rejoining from the "
                                "latest checkpoint: %s", cause)
                        else:
                            logger.warning("worker failure: %s", cause)
                        result.error = cause
                        break
                    if all(s.done for s in statuses):
                        # final drain: async checkpoint writes + last reports
                        group.flush_checkpoints()
                        self._ingest_reports(group.poll(), result,
                                             group.num_workers)
                        break
                    trigger = self._resize_trigger(group)
                    if trigger is not None:
                        verdict = self._try_live_resize(group, trigger)
                        if verdict == "ok":
                            continue  # resized in place; keep polling
                        if verdict == "failed":
                            # post-commit loss: the gang shape is undefined
                            # — planned teardown, resume from checkpoint
                            failed = True
                            planned = True
                            result.error = (
                                "live resize failed after commit; "
                                "falling back to checkpoint-restore")
                            break
                        # aborted: continue at the old width; if the drain
                        # kills workers anyway the normal path handles it
                    time.sleep(self.poll_interval_s)
            finally:
                group.shutdown()
                self._group = None

            if not failed:
                result.error = None
                result.best_checkpoint = self.ckpt.best
                result.checkpoint = self.ckpt.latest
                return result

            # drop partial staging shards from the failed incarnation: a
            # differently-sized restart would otherwise mix incarnations
            self._pending_ckpt.clear()
            self.ckpt.purge_staging()
            if planned:
                # drain-triggered rejoin: resume from the drain-window
                # checkpoint without spending the failure budget (bounded
                # separately so a pathological drain loop still terminates)
                self.drain_rejoins += 1
                if self.drain_rejoins > self.max_drain_rejoins:
                    result.error = (
                        f"too many drain rejoins ({self.drain_rejoins}); "
                        f"last cause: {result.error}")
                    return result
                logger.info(
                    "rejoining worker group after planned removal "
                    "(rejoin %d, failure budget untouched), resuming from %s",
                    self.drain_rejoins,
                    self.ckpt.latest.path if self.ckpt.latest else "scratch",
                )
                continue
            self.failure_count += 1
            if not self.failure_policy.decide(self.failure_count):
                return result
            logger.info(
                "restarting worker group (failure %d), resuming from %s",
                self.failure_count,
                self.ckpt.latest.path if self.ckpt.latest else "scratch",
            )
