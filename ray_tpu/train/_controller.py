"""Train controller: the run loop driving worker groups to completion.

Reference: python/ray/train/v2/_internal/execution/controller/controller.py:105
— the controller owns the worker-group lifecycle: consult the scaling policy,
create the group, poll it, finalize checkpoints as rank shards land, and on
failure consult the failure policy, tear down, and re-create (resuming from
the latest finalized checkpoint).

Redesigned driver-side (a plain object run by Trainer.fit) rather than as a
detached actor: the TPU framework's north-star path is a single driver owning
a slice gang, and driver-failure isolation can be layered on by running fit()
itself inside an actor.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train._policies import FailurePolicy, ScalingPolicy
from ray_tpu.train._worker_group import WorkerGroup, WorkerStatus

logger = logging.getLogger(__name__)


@dataclass
class TrainResult:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    best_checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_config: Optional[dict],
        scaling_policy: ScalingPolicy,
        failure_policy: FailurePolicy,
        resources_per_worker: Dict[str, float],
        run_name: str,
        storage_path: str,
        checkpoint_manager: CheckpointManager,
        use_tpu_slices: bool = False,
        topology: str = "",
        accelerator_type: str = "",
        poll_interval_s: float = 0.2,
    ):
        self.train_fn = train_fn
        self.train_config = train_config
        self.scaling_policy = scaling_policy
        self.failure_policy = failure_policy
        self.resources_per_worker = resources_per_worker
        self.run_name = run_name
        self.storage_path = storage_path
        self.ckpt = checkpoint_manager
        self.use_tpu_slices = use_tpu_slices
        self.topology = topology
        self.accelerator_type = accelerator_type
        self.poll_interval_s = poll_interval_s
        self.failure_count = 0
        self._group: Optional[WorkerGroup] = None
        # checkpoint steps reported but not yet finalized (async rank shards
        # may land after the report that announced them)
        self._pending_ckpt: Dict[int, Dict[str, Any]] = {}

    # -- helpers --------------------------------------------------------

    def _cluster_cpus(self) -> float:
        try:
            return float(ray_tpu.cluster_resources().get("CPU", 1.0))
        except Exception:  # noqa: BLE001
            return 1.0

    def _make_group(self) -> WorkerGroup:
        decision = self.scaling_policy.target_size(
            self._cluster_cpus(), self.resources_per_worker
        )
        logger.info("worker group size %d (%s)", decision.num_workers,
                    decision.reason)
        group = WorkerGroup(
            num_workers=decision.num_workers,
            resources_per_worker=self.resources_per_worker,
            run_name=self.run_name,
            storage_path=self.storage_path,
            run_dir=self.ckpt.run_dir,
            use_tpu_slices=self.use_tpu_slices,
            topology=self.topology,
            accelerator_type=self.accelerator_type,
        )
        try:
            group.create(latest_checkpoint=self.ckpt.latest)
            group.start_training(self.train_fn, self.train_config)
        except BaseException:
            # a half-created group leaks its named sync actor, workers, and
            # placement group — the retry then collides on the name / starves
            # on resources and fails with a confusing actor-kill cause
            try:
                group.shutdown()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            raise
        return group

    def _ingest_reports(self, statuses: List[WorkerStatus],
                        result: TrainResult, world_size: int):
        """Collect metrics; finalize checkpoints once all rank shards landed."""
        for st in statuses:
            for rep in st.reports:
                result.metrics_history.append(rep["metrics"])
                if rep["metrics"]:
                    result.metrics = rep["metrics"]
                if "checkpoint_step" in rep:
                    self._pending_ckpt[rep["checkpoint_step"]] = rep["metrics"]
        for step in sorted(self._pending_ckpt):
            ckpt = self.ckpt.finalize(
                step, self._pending_ckpt[step], expected_ranks=world_size
            )
            if ckpt is not None:
                del self._pending_ckpt[step]
                result.checkpoint = ckpt
                logger.info("checkpoint finalized: %s", ckpt.path)

    # -- run loop -------------------------------------------------------

    def run(self) -> TrainResult:
        result = TrainResult()
        while True:
            try:
                self._group = self._make_group()
            except Exception as e:  # noqa: BLE001 — group creation failed
                self.failure_count += 1
                if not self.failure_policy.decide(self.failure_count):
                    result.error = f"worker group creation failed: {e}"
                    return result
                time.sleep(min(2.0 ** self.failure_count * 0.2, 10.0))
                continue

            group = self._group
            world = group.num_workers
            failed = False
            try:
                while True:
                    statuses = group.poll()
                    self._ingest_reports(statuses, result, world)
                    dead = [s for s in statuses if not s.alive]
                    errored = [s for s in statuses if s.error and s.alive]
                    if dead or errored:
                        failed = True
                        cause = (dead or errored)[0].error
                        logger.warning("worker failure: %s", cause)
                        result.error = cause
                        break
                    if all(s.done for s in statuses):
                        # final drain: async checkpoint writes + last reports
                        group.flush_checkpoints()
                        self._ingest_reports(group.poll(), result, world)
                        break
                    time.sleep(self.poll_interval_s)
            finally:
                group.shutdown()
                self._group = None

            if not failed:
                result.error = None
                result.best_checkpoint = self.ckpt.best
                result.checkpoint = self.ckpt.latest
                return result

            # drop partial staging shards from the failed incarnation: a
            # differently-sized restart would otherwise mix incarnations
            self._pending_ckpt.clear()
            self._purge_staging()
            self.failure_count += 1
            if not self.failure_policy.decide(self.failure_count):
                return result
            logger.info(
                "restarting worker group (failure %d), resuming from %s",
                self.failure_count,
                self.ckpt.latest.path if self.ckpt.latest else "scratch",
            )

    def _purge_staging(self):
        import shutil

        try:
            for name in os.listdir(self.ckpt.run_dir):
                if name.startswith(".staging_checkpoint_"):
                    shutil.rmtree(os.path.join(self.ckpt.run_dir, name),
                                  ignore_errors=True)
        except OSError:
            pass
