"""Train controller: the run loop driving worker groups to completion.

Reference: python/ray/train/v2/_internal/execution/controller/controller.py:105
— the controller owns the worker-group lifecycle: consult the scaling policy,
create the group, poll it, finalize checkpoints as rank shards land, and on
failure consult the failure policy, tear down, and re-create (resuming from
the latest finalized checkpoint).

Redesigned driver-side (a plain object run by Trainer.fit) rather than as a
detached actor: the TPU framework's north-star path is a single driver owning
a slice gang, and driver-failure isolation can be layered on by running fit()
itself inside an actor.
"""

from __future__ import annotations

import logging
import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import ray_tpu
from ray_tpu.train._checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train._policies import FailurePolicy, ScalingPolicy
from ray_tpu.train._worker_group import WorkerGroup, WorkerStatus

logger = logging.getLogger(__name__)


@dataclass
class TrainResult:
    metrics: Dict[str, Any] = field(default_factory=dict)
    checkpoint: Optional[Checkpoint] = None
    best_checkpoint: Optional[Checkpoint] = None
    error: Optional[str] = None
    metrics_history: List[Dict[str, Any]] = field(default_factory=list)


class TrainController:
    def __init__(
        self,
        train_fn: Callable,
        train_config: Optional[dict],
        scaling_policy: ScalingPolicy,
        failure_policy: FailurePolicy,
        resources_per_worker: Dict[str, float],
        run_name: str,
        storage_path: str,
        checkpoint_manager: CheckpointManager,
        use_tpu_slices: bool = False,
        topology: str = "",
        accelerator_type: str = "",
        poll_interval_s: float = 0.2,
    ):
        self.train_fn = train_fn
        self.train_config = train_config
        self.scaling_policy = scaling_policy
        self.failure_policy = failure_policy
        self.resources_per_worker = resources_per_worker
        self.run_name = run_name
        self.storage_path = storage_path
        self.ckpt = checkpoint_manager
        self.use_tpu_slices = use_tpu_slices
        self.topology = topology
        self.accelerator_type = accelerator_type
        self.poll_interval_s = poll_interval_s
        self.failure_count = 0
        # planned-removal rejoins (drain/preemption): checkpoint-then-rejoin,
        # never charged against the failure policy's budget — a preempted
        # node is the dominant production "failure" and must be a non-event.
        # Bounded separately so a drain loop can't retry forever.
        self.drain_rejoins = 0
        self.max_drain_rejoins = 16
        self._group: Optional[WorkerGroup] = None
        # checkpoint steps reported but not yet finalized (async rank shards
        # may land after the report that announced them)
        self._pending_ckpt: Dict[int, Dict[str, Any]] = {}

    @staticmethod
    def _is_planned_removal(cause: Optional[str]) -> bool:
        """A worker lost to a graceful drain or preemption notice — the
        structured death reasons name the drain (\"node draining
        (preemption)\", \"drained (autoscaler)\") — is a planned rejoin,
        not a crash."""
        if not cause:
            return False
        c = cause.lower()
        return "drain" in c or "preempt" in c

    @staticmethod
    def _drain_in_progress(node_ids=None, terminal_only=False) -> bool:
        """Notice-driven planned-failure detection: a worker can die with a
        generic connection error before the structured death cause
        propagates, so also consult the node table — a DRAINING node, or a
        fresh expected-termination record, means the loss was planned.
        When the dead workers' nodes are known, only THOSE nodes count: an
        unrelated idle-node drain elsewhere in the cluster must not mask a
        genuine crash (which would silently bypass the failure budget).
        With terminal_only, a reversible (no-deadline) drain doesn't count
        either — only deadline-carrying drains kill workers, so callers
        with no node scoping available (group creation) use this to keep a
        routine idle-drain from masking a genuinely bad config."""
        wanted = {n for n in (node_ids or []) if n}
        try:
            for n in ray_tpu.nodes():
                if wanted and n.get("node_id") not in wanted:
                    continue
                if n.get("state") == "DRAINING" and (
                        not terminal_only or n.get("drain_deadline")):
                    return True
                death = n.get("death")
                if (death and death.get("expected")
                        and time.time() - death.get("ts", 0.0) < 120.0):
                    return True
        except Exception:  # noqa: BLE001 — control store unreachable
            return False
        return False

    # -- helpers --------------------------------------------------------

    def _cluster_cpus(self) -> float:
        try:
            return float(ray_tpu.cluster_resources().get("CPU", 1.0))
        except Exception:  # noqa: BLE001
            return 1.0

    def _make_group(self) -> WorkerGroup:
        decision = self.scaling_policy.target_size(
            self._cluster_cpus(), self.resources_per_worker
        )
        logger.info("worker group size %d (%s)", decision.num_workers,
                    decision.reason)
        group = WorkerGroup(
            num_workers=decision.num_workers,
            resources_per_worker=self.resources_per_worker,
            run_name=self.run_name,
            storage_path=self.storage_path,
            run_dir=self.ckpt.run_dir,
            use_tpu_slices=self.use_tpu_slices,
            topology=self.topology,
            accelerator_type=self.accelerator_type,
        )
        try:
            group.create(latest_checkpoint=self.ckpt.latest)
            group.start_training(self.train_fn, self.train_config)
        except BaseException:
            # a half-created group leaks its named sync actor, workers, and
            # placement group — the retry then collides on the name / starves
            # on resources and fails with a confusing actor-kill cause
            try:
                group.shutdown()
            except Exception:  # noqa: BLE001 — best-effort cleanup
                pass
            raise
        return group

    def _ingest_reports(self, statuses: List[WorkerStatus],
                        result: TrainResult, world_size: int):
        """Collect metrics; finalize checkpoints once all rank shards landed."""
        for st in statuses:
            for rep in st.reports:
                result.metrics_history.append(rep["metrics"])
                if rep["metrics"]:
                    result.metrics = rep["metrics"]
                if "checkpoint_step" in rep:
                    self._pending_ckpt[rep["checkpoint_step"]] = rep["metrics"]
        for step in sorted(self._pending_ckpt):
            ckpt = self.ckpt.finalize(
                step, self._pending_ckpt[step], expected_ranks=world_size
            )
            if ckpt is not None:
                del self._pending_ckpt[step]
                result.checkpoint = ckpt
                logger.info("checkpoint finalized: %s", ckpt.path)

    # -- run loop -------------------------------------------------------

    def run(self) -> TrainResult:
        result = TrainResult()
        while True:
            try:
                self._group = self._make_group()
            except Exception as e:  # noqa: BLE001 — group creation failed
                if (self._drain_in_progress(terminal_only=True)
                        and self.drain_rejoins < self.max_drain_rejoins):
                    # creation raced a terminal drain (workers died on the
                    # leaving node mid-start): retry without spending the
                    # budget — reversible idle-drains kill nothing and must
                    # not mask a genuinely bad config
                    self.drain_rejoins += 1
                    time.sleep(0.5)
                    continue
                self.failure_count += 1
                if not self.failure_policy.decide(self.failure_count):
                    result.error = f"worker group creation failed: {e}"
                    return result
                time.sleep(min(2.0 ** self.failure_count * 0.2, 10.0))
                continue

            group = self._group
            world = group.num_workers
            failed = False
            planned = False
            try:
                while True:
                    statuses = group.poll()
                    self._ingest_reports(statuses, result, world)
                    dead = [s for s in statuses if not s.alive]
                    errored = [s for s in statuses if s.error and s.alive]
                    if dead or errored:
                        failed = True
                        cause = (dead or errored)[0].error
                        # only a LOST worker can be drain-caused: an
                        # application error in a live worker must charge the
                        # failure budget even while some node is draining
                        planned = bool(dead) and (
                            self._is_planned_removal(cause)
                            or self._drain_in_progress(
                                [s.node_id for s in dead]))
                        if planned:
                            logger.info(
                                "worker lost to planned node removal "
                                "(drain/preemption); rejoining from the "
                                "latest checkpoint: %s", cause)
                        else:
                            logger.warning("worker failure: %s", cause)
                        result.error = cause
                        break
                    if all(s.done for s in statuses):
                        # final drain: async checkpoint writes + last reports
                        group.flush_checkpoints()
                        self._ingest_reports(group.poll(), result, world)
                        break
                    time.sleep(self.poll_interval_s)
            finally:
                group.shutdown()
                self._group = None

            if not failed:
                result.error = None
                result.best_checkpoint = self.ckpt.best
                result.checkpoint = self.ckpt.latest
                return result

            # drop partial staging shards from the failed incarnation: a
            # differently-sized restart would otherwise mix incarnations
            self._pending_ckpt.clear()
            self._purge_staging()
            if planned:
                # drain-triggered rejoin: resume from the drain-window
                # checkpoint without spending the failure budget (bounded
                # separately so a pathological drain loop still terminates)
                self.drain_rejoins += 1
                if self.drain_rejoins > self.max_drain_rejoins:
                    result.error = (
                        f"too many drain rejoins ({self.drain_rejoins}); "
                        f"last cause: {result.error}")
                    return result
                logger.info(
                    "rejoining worker group after planned removal "
                    "(rejoin %d, failure budget untouched), resuming from %s",
                    self.drain_rejoins,
                    self.ckpt.latest.path if self.ckpt.latest else "scratch",
                )
                continue
            self.failure_count += 1
            if not self.failure_policy.decide(self.failure_count):
                return result
            logger.info(
                "restarting worker group (failure %d), resuming from %s",
                self.failure_count,
                self.ckpt.latest.path if self.ckpt.latest else "scratch",
            )

    def _purge_staging(self):
        import shutil

        try:
            for name in os.listdir(self.ckpt.run_dir):
                if name.startswith(".staging_checkpoint_"):
                    shutil.rmtree(os.path.join(self.ckpt.run_dir, name),
                                  ignore_errors=True)
        except OSError:
            pass
