"""StorageContext: one URI-addressed filesystem plane for checkpoints,
runtime-env packages, and Tune trial state.

Reference: python/ray/train/v2/_internal/execution/storage.py (fsspec/
pyarrow-backed StorageContext behind `storage_path` — local dirs, NFS,
s3://, gs://). Here fsspec carries every scheme it knows (file, memory, s3,
gs, ...); plain paths resolve to the local filesystem with identical
semantics to the previous os/shutil code, including atomic finalize
(rename) where the backend supports it.
"""

from __future__ import annotations

import json
import os
import posixpath
from typing import Any, List


class StorageContext:
    """Filesystem operations rooted at a URI."""

    def __init__(self, uri: str):
        import fsspec

        self.uri = uri
        self.fs, self.root = fsspec.core.url_to_fs(uri)
        self._local = type(self.fs).__name__ == "LocalFileSystem"

    # -- paths ----------------------------------------------------------

    def join(self, *parts: str) -> str:
        if self._local:
            return os.path.join(*parts)
        return posixpath.join(*parts)

    def basename(self, path: str) -> str:
        return posixpath.basename(path.rstrip("/"))

    # -- directory ops ---------------------------------------------------

    def makedirs(self, path: str) -> None:
        self.fs.makedirs(path, exist_ok=True)

    def exists(self, path: str) -> bool:
        return self.fs.exists(path)

    def isdir(self, path: str) -> bool:
        return self.fs.isdir(path)

    def listdir(self, path: str) -> List[str]:
        if not self.fs.isdir(path):
            return []
        return sorted(self.basename(p) for p in self.fs.ls(path, detail=False))

    def delete(self, path: str) -> None:
        try:
            self.fs.rm(path, recursive=True)
        except FileNotFoundError:
            pass

    def rename(self, src: str, dst: str) -> None:
        """Atomic on local filesystems; move (copy+delete) elsewhere —
        finalize protocols must tolerate either."""
        if self._local:
            os.replace(src, dst)
            return
        self.fs.mv(src, dst, recursive=True)

    # -- file ops ---------------------------------------------------------

    def open(self, path: str, mode: str = "rb"):
        if "w" in mode or "a" in mode:
            parent = posixpath.dirname(path) if not self._local \
                else os.path.dirname(path)
            if parent:
                self.makedirs(parent)
        return self.fs.open(path, mode)

    def write_bytes(self, path: str, data: bytes) -> None:
        with self.open(path, "wb") as f:
            f.write(data)

    def read_bytes(self, path: str) -> bytes:
        with self.open(path, "rb") as f:
            return f.read()

    def write_json(self, path: str, obj: Any) -> None:
        def coerce(o):
            try:
                return float(o)  # numpy/jax scalars from user metrics
            except (TypeError, ValueError):
                return str(o)

        self.write_bytes(path, json.dumps(obj, default=coerce).encode())

    def read_json(self, path: str) -> Any:
        return json.loads(self.read_bytes(path).decode())

    def download_dir(self, src: str, local_dir: str) -> None:
        """Recursively copy a storage directory to the local filesystem."""
        os.makedirs(local_dir, exist_ok=True)
        # fs.find returns protocol-stripped paths; strip the base the same
        # way or file:// sources produce ../-laden relative paths
        base = self.fs._strip_protocol(src.rstrip("/"))
        for path in self.fs.find(base):
            rel = os.path.relpath(path, base)
            out = os.path.join(local_dir, rel)
            os.makedirs(os.path.dirname(out), exist_ok=True)
            with open(out, "wb") as f:
                f.write(self.read_bytes(path))


def get_storage(uri_or_path: str) -> StorageContext:
    # no cache: construction is cheap and fsspec already caches filesystem
    # instances per protocol (a per-path cache would grow unbounded across
    # a training run's per-checkpoint paths)
    return StorageContext(uri_or_path)
