"""Public Train API: configs + DataParallelTrainer + JaxTrainer.

Reference surface: ray.train.ScalingConfig/RunConfig/FailureConfig/
CheckpointConfig (python/ray/train/), DataParallelTrainer
(train/v2/api/data_parallel_trainer.py:66) and the TPU-specific JaxTrainer
(train/v2/jax/jax_trainer.py:20, config.py:40-121).

TPU-first redesign: JaxTrainer's workers form a JAX SPMD gang — rank 0's host
is the jax.distributed coordinator (rendezvous address broadcast through the
worker group exactly like JaxConfig's `_setup_jax_distributed_environment`),
topology-aware placement reserves whole TPU slices, and MEGASCALE env vars
carry cross-slice (DCN) coordination.
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional

from ray_tpu.train._checkpoint import CheckpointManager
from ray_tpu.train._controller import TrainController, TrainResult
from ray_tpu.train._policies import (
    ElasticScalingPolicy,
    FailurePolicy,
    FixedScalingPolicy,
)


@dataclass
class ScalingConfig:
    """Reference: ray.train.ScalingConfig (+ TPU fields of v2/jax/config.py).

    Setting `elastic_min_workers` makes the run ELASTIC: the group sizes
    to current usable capacity within [elastic_min_workers, num_workers],
    and — when the train fn drives `ctx.elastic.sync()` each step — a
    planned node removal (drain/preemption) with enough survivors resizes
    the live gang instead of tearing it down, re-expanding when capacity
    returns (see train/_elastic.py; knob: `train_live_resize`)."""

    num_workers: int = 1
    resources_per_worker: Dict[str, float] = field(default_factory=dict)
    use_tpu: bool = False
    topology: str = ""  # e.g. "4x4" — reserves whole slices when use_tpu
    accelerator_type: str = ""  # e.g. "v5e"
    elastic_min_workers: Optional[int] = None  # set → elastic scaling

    def policy(self):
        if self.elastic_min_workers is not None:
            return ElasticScalingPolicy(self.elastic_min_workers,
                                        self.num_workers)
        return FixedScalingPolicy(self.num_workers)

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker)
        if not res:
            res = {"CPU": 1.0}
        return res


@dataclass
class FailureConfig:
    """Reference: ray.train.FailureConfig."""

    max_failures: int = 0


@dataclass
class CheckpointConfig:
    """Reference: ray.train.CheckpointConfig."""

    num_to_keep: int = 2
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "min"


@dataclass
class RunConfig:
    """Reference: ray.train.RunConfig."""

    name: str = ""
    storage_path: str = ""
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)

    def resolved_name(self) -> str:
        return self.name or f"train-run-{int(time.time())}"

    def resolved_storage(self) -> str:
        return self.storage_path or os.path.join(
            os.path.expanduser("~"), "ray_tpu_results"
        )


class DataParallelTrainer:
    """Runs `train_loop_per_worker` on N gang-scheduled workers.

    Reference: train/v2/api/data_parallel_trainer.py:66. fit() drives the
    controller loop synchronously and returns a TrainResult.
    """

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[dict] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()

    def _controller(self) -> TrainController:
        run_name = self.run_config.resolved_name()
        storage = self.run_config.resolved_storage()
        cc = self.run_config.checkpoint_config
        manager = CheckpointManager(
            storage, run_name,
            num_to_keep=cc.num_to_keep,
            metric=cc.checkpoint_score_attribute,
            mode=cc.checkpoint_score_order,
        )
        return TrainController(
            train_fn=self.train_loop_per_worker,
            train_config=self.train_loop_config,
            scaling_policy=self.scaling_config.policy(),
            failure_policy=FailurePolicy(
                self.run_config.failure_config.max_failures
            ),
            resources_per_worker=self.scaling_config.worker_resources(),
            run_name=run_name,
            storage_path=storage,
            checkpoint_manager=manager,
            use_tpu_slices=bool(
                self.scaling_config.use_tpu and self.scaling_config.topology
            ),
            topology=self.scaling_config.topology,
            accelerator_type=self.scaling_config.accelerator_type,
        )

    def fit(self) -> TrainResult:
        result = self._controller().run()
        if result.error is not None:
            raise TrainingFailedError(result.error)
        return result


class TrainingFailedError(RuntimeError):
    """Training exhausted its failure budget (reference: TrainingFailedError)."""


class JaxTrainer(DataParallelTrainer):
    """SPMD JAX training over a TPU gang (reference: v2/jax/jax_trainer.py:20).

    The train loop runs once per host process; call
    `ray_tpu.train.setup_jax_distributed()` first thing inside it to join the
    global mesh (coordinator address + rank/world size are injected by the
    worker group, mirroring _setup_jax_distributed_environment
    (reference: v2/jax/config.py:60-121)).
    """

    def __init__(self, train_loop_per_worker: Callable, **kwargs):
        scaling = kwargs.get("scaling_config") or ScalingConfig()
        if scaling.use_tpu and not scaling.resources_per_worker:
            # one worker process per TPU host, owning all its chips
            scaling.resources_per_worker = {"TPU": 4.0}
        kwargs["scaling_config"] = scaling
        super().__init__(train_loop_per_worker, **kwargs)


def setup_jax_distributed(local_device_count: Optional[int] = None) -> None:
    """Join the run's global JAX mesh from inside a train worker.

    Uses the coordinator/rank env vars injected by the worker group
    (RT_TRAIN_COORDINATOR / RT_TRAIN_RANK / RT_TRAIN_WORLD_SIZE — the same
    contract as MEGASCALE/jax.distributed in the reference). No-op for a
    single-worker run.
    """
    import jax

    world = int(os.environ.get("RT_TRAIN_WORLD_SIZE", "1"))
    if world <= 1:
        return
    coord = os.environ["RT_TRAIN_COORDINATOR"]
    rank = int(os.environ["RT_TRAIN_RANK"])
    kwargs = {}
    if local_device_count is not None:
        kwargs["local_device_ids"] = list(range(local_device_count))
    jax.distributed.initialize(
        coordinator_address=coord, num_processes=world, process_id=rank,
        **kwargs,
    )
