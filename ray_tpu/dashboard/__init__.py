"""Dashboard: HTTP JSON API + overview page for cluster state.

Reference surface: python/ray/dashboard/ (DashboardHead head.py:49 serving
/api/... routes + the React frontend; per-node agents feed it). Here one
detached async actor serves the API straight from the control store's
tables — nodes, actors, jobs, tasks, placement groups, Prometheus metrics
— plus a single-file HTML overview.
"""

from __future__ import annotations

import asyncio
import json
import time
from typing import Any, Dict

import ray_tpu


def _read_text(path: str) -> str:
    """Whole-file read for asyncio.to_thread (the handler itself must not
    touch disk on the event loop)."""
    with open(path, encoding="utf-8") as f:
        return f.read()


DASHBOARD_NAME = "dashboard"
DASH_NAMESPACE = "_dashboard"

_PAGE = """<!doctype html>
<html><head><title>ray_tpu dashboard</title>
<style>
 body { font-family: monospace; margin: 2em; background: #111; color: #eee; }
 h1 { color: #7fdbca; } h2 { color: #82aaff; margin-top: 1.5em; }
 table { border-collapse: collapse; }
 td, th { border: 1px solid #444; padding: 4px 10px; text-align: left; }
 th { background: #222; }
</style></head>
<body>
<h1>ray_tpu dashboard</h1>
<div id="content">loading…</div>
<script>
async function refresh() {
  let [nodes, actors, jobs, tasks] = await Promise.all(
    ["nodes?limit=1000", "actors", "jobs", "task_summary"].map(
      p => fetch("/api/" + p).then(r => r.json())));
  nodes = nodes.nodes || nodes;
  jobs = jobs.jobs || jobs;
  const esc = (s) => String(s).replace(/[&<>"']/g,
    ch => ({"&":"&amp;","<":"&lt;",">":"&gt;",'"':"&quot;","'":"&#39;"}[ch]));
  const table = (rows) => {
    if (!rows.length) return "<i>(none)</i>";
    const cols = Object.keys(rows[0]);
    return "<table><tr>" + cols.map(c => `<th>${esc(c)}</th>`).join("") +
      "</tr>" + rows.map(r => "<tr>" + cols.map(
        c => `<td>${esc(JSON.stringify(r[c]))}</td>`).join("") +
      "</tr>").join("") + "</table>";
  };
  document.getElementById("content").innerHTML =
    "<h2>Nodes</h2>" + table(nodes) +
    "<h2>Actors</h2>" + table(actors) +
    "<h2>Jobs</h2>" + table(jobs) +
    "<h2>Tasks</h2><pre>" + JSON.stringify(tasks, null, 1) + "</pre>";
}
refresh(); setInterval(refresh, 3000);
</script></body></html>
"""


@ray_tpu.remote
class DashboardActor:
    """Async actor hosting the aiohttp app (same pattern as the serve
    ingress proxy — the server starts lazily on the core event loop)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8265):
        self.host = host
        self.port = port
        self._started = None
        self._runner = None
        # aggregated node table maintained from the control store's delta
        # cursor: each poll transfers only the mutations since the last one
        # instead of serializing the full 1000-node table per request
        self._nodes_cache: dict = {}
        self._nodes_cursor = -1

    async def _node_table(self) -> list:
        """The aggregated node table, refreshed via get_nodes_delta. Falls
        back to a full read when delta sync is off or the cursor expired."""
        reply = await self._control(
            "get_nodes_delta", {"cursor": self._nodes_cursor})
        if reply.get("full") or "updates" not in reply:
            self._nodes_cache = {
                n["node_id"]: n for n in reply.get("nodes", [])
            }
        else:
            for n in reply["updates"]:
                self._nodes_cache[n["node_id"]] = n
            if len(self._nodes_cache) > 4096:
                # deltas only ever ADD rows; under heavy churn re-anchor on
                # a full read so the store's dead-node retention (which
                # prunes) bounds this cache too
                self._nodes_cursor = -1
                return await self._node_table()
        self._nodes_cursor = reply.get("version", self._nodes_cursor)
        return list(self._nodes_cache.values())

    async def _control(self, method: str, payload: dict = None):
        from ray_tpu._private.core_worker import get_core_worker

        cw = get_core_worker()
        return await cw.control.call(method, payload or {})

    async def _start(self):
        from aiohttp import web

        app = web.Application()
        app.router.add_get("/", self._index)
        app.router.add_get("/api/nodes", self._nodes)
        app.router.add_get("/api/actors", self._actors)
        app.router.add_get("/api/jobs", self._jobs)
        app.router.add_get("/api/driver_jobs", self._driver_jobs)
        app.router.add_get("/api/tasks", self._tasks)
        app.router.add_get("/api/task_summary", self._task_summary)
        app.router.add_get("/api/placement_groups", self._pgs)
        app.router.add_get("/api/cluster_load", self._cluster_load)
        app.router.add_get("/api/events", self._events)
        app.router.add_get("/api/node_stats", self._node_stats)
        app.router.add_get("/api/workers", self._workers)
        app.router.add_get("/api/profile", self._profile)
        app.router.add_get("/api/jax_profile", self._jax_profile)
        app.router.add_get("/api/flight_recorder", self._flight_recorder)
        app.router.add_get("/metrics", self._metrics)
        self._runner = web.AppRunner(app)
        await self._runner.setup()
        site = web.TCPSite(self._runner, self.host, self.port)
        await site.start()

    async def ready(self) -> str:
        if self._started is None:
            self._started = asyncio.ensure_future(self._start())
        try:
            await self._started
        except BaseException:
            # a transient bind failure must not brick the detached actor:
            # the next ready() retries the startup
            self._started = None
            raise
        return f"http://{self.host}:{self.port}"

    async def _index(self, request):
        from aiohttp import web

        import os

        page = getattr(self, "_index_page", None)
        if page is None:
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "static", "index.html")
            try:
                page = await asyncio.to_thread(_read_text, path)
            except OSError:  # packaged without assets: minimal inline fallback
                page = _PAGE
            self._index_page = page  # static asset: read once, serve cached
        return web.Response(text=page, content_type="text/html")

    async def _resolve_node(self, node_hex: str) -> dict:
        """Find a LIVE node by full id or unique prefix (>= 8 chars)."""
        nodes = await self._node_table()
        matches = [
            n for n in nodes
            if n["node_id"].hex() == node_hex
            or (len(node_hex) >= 8 and n["node_id"].hex().startswith(node_hex))
        ]
        if not matches:
            raise ValueError(f"unknown node {node_hex}")
        if len(matches) > 1:
            raise ValueError(f"ambiguous node prefix {node_hex}")
        if matches[0]["state"] == "DEAD":
            raise ValueError(f"node {node_hex} is dead")
        return matches[0]

    async def _daemon_call(self, node_hex: str, method: str, payload: dict):
        """RPC a specific node's daemon (resolved through the control
        store's node table)."""
        from ray_tpu.runtime.rpc import RpcClient

        n = await self._resolve_node(node_hex)
        client = RpcClient(n["address"], name="dash->daemon", retries=1)
        await client.connect()
        try:
            return await client.call(method, payload, timeout=60)
        finally:
            await client.close()

    async def _node_stats(self, request):
        """Per-node psutil/store stats sampled by daemons into the control
        store (reference: dashboard reporter agents)."""
        from aiohttp import web

        reply = await self._control("get_node_stats")
        return web.json_response(reply["stats"])

    async def _workers(self, request):
        """?node=<hex>: live workers on that node."""
        from aiohttp import web

        from ray_tpu.runtime.rpc import RpcError

        node = request.query.get("node", "")
        try:
            reply = await self._daemon_call(node, "list_workers", {})
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=404)
        except (RpcError, ConnectionError, OSError) as e:
            return web.json_response(
                {"error": f"daemon unreachable: {e}"}, status=502)
        return web.json_response(reply["workers"])

    async def _profile(self, request):
        """?node=<hex>&worker=<hex>[&kind=threads|tasks]: on-demand stack
        sample of a live worker (reference: the dashboard's py-spy
        profiling endpoint, reporter/profile_manager.py:60-102)."""
        from aiohttp import web

        from ray_tpu.runtime.rpc import RpcError

        node = request.query.get("node", "")
        worker = request.query.get("worker", "")
        kind = request.query.get("kind", "threads")
        try:
            bytes.fromhex(worker)
        except ValueError:
            return web.json_response(
                {"error": f"bad worker id {worker!r}"}, status=400)
        try:
            reply = await self._daemon_call(
                node, "profile_worker", {"worker_id": worker, "kind": kind})
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=404)
        except (RpcError, ConnectionError, OSError) as e:
            return web.json_response(
                {"error": f"daemon unreachable: {e}"}, status=502)
        status = 200 if reply.get("ok") else 400
        return web.json_response(reply, status=status)

    async def _jax_profile(self, request):
        """?node=<hex>&duration=2[&logdir=...]: capture a JAX/XPlane trace
        on that node via a pinned task (reference: the dashboard's JAX
        profiler capture, reporter/jax_profile_manager.py:11). The trace
        dir is created ON THE TARGET node (default: its temp dir)."""
        from aiohttp import web

        node = request.query.get("node", "")
        try:
            duration = float(request.query.get("duration", "2"))
        except ValueError:
            return web.json_response(
                {"error": "duration must be a number"}, status=400)
        logdir = request.query.get("logdir")
        try:
            n = await self._resolve_node(node)
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=404)
        from ray_tpu._private.core_worker import get_core_worker
        from ray_tpu.tpu.profiler import node_capture_task

        cw = get_core_worker()
        ref = node_capture_task(n["node_id"].hex()).remote(logdir, duration)
        out_dir, files = await cw.get_async(ref, timeout=duration + 120)
        return web.json_response(
            {"node": n["node_id"].hex(), "logdir": out_dir, "files": files})

    async def _nodes(self, request):
        """Paginated node listing served from the delta-maintained
        aggregate (`?offset=&limit=`, default limit 100): a poll against a
        1000-node cluster transfers one page + the table's recent deltas,
        never the full table per request."""
        from aiohttp import web

        from ray_tpu._private.protocol import NodeInfo

        try:
            offset = max(0, int(request.query.get("offset", 0)))
            limit = max(1, min(1000, int(request.query.get("limit", 100))))
        except ValueError:
            return web.json_response({"error": "bad offset/limit"},
                                     status=400)
        nodes = await self._node_table()
        # live first, then draining, then dead — stable within groups so
        # pages don't shuffle between polls
        order = {"ALIVE": 0, "DRAINING": 1}
        nodes.sort(key=lambda n: (order.get(n["state"], 2),
                                  n["node_id"]))
        page = nodes[offset:offset + limit]
        return web.json_response({
            "total": len(nodes),
            "offset": offset,
            "limit": limit,
            "nodes": [
                {
                    # FULL hex: these ids feed /api/workers, /api/profile
                    # and /api/jax_profile, which resolve nodes by exact id
                    "node_id": NodeInfo.from_wire(n).node_id.hex(),
                    "state": n["state"],
                    "address": n["address"],
                    "resources": NodeInfo.from_wire(n).resources.to_dict(),
                }
                for n in page
            ],
        })

    async def _actors(self, request):
        from aiohttp import web

        reply = await self._control("list_actors")
        return web.json_response([
            {
                "actor_id": a["actor_id"].hex()[:12],
                "state": a["state"],
                "name": a.get("name", ""),
                "restarts": a.get("num_restarts", 0),
            }
            for a in reply["actors"]
        ])

    async def _jobs(self, request):
        """Paginated SUBMITTED-job listing from the durable job table
        (`?offset=&limit=&tenant=&status=`, default limit 100) — the job
        plane's table, not the internal driver-job registry (that one
        lives at /api/driver_jobs)."""
        from aiohttp import web

        try:
            offset = max(0, int(request.query.get("offset", 0)))
            limit = max(1, min(1000, int(request.query.get("limit", 100))))
        except ValueError:
            return web.json_response({"error": "bad offset/limit"},
                                     status=400)
        payload = {"offset": offset, "limit": limit}
        if request.query.get("tenant"):
            payload["tenant"] = request.query["tenant"]
        if request.query.get("status"):
            payload["status"] = request.query["status"]
        reply = await self._control("job_list", payload)
        return web.json_response({
            "total": reply.get("total", 0),
            "offset": offset,
            "limit": limit,
            "jobs": [
                {
                    "submission_id": j["submission_id"],
                    "status": j.get("status", ""),
                    "tenant": j.get("tenant", ""),
                    "entrypoint": j.get("entrypoint", ""),
                    "message": j.get("message", ""),
                    "submit_time": j.get("submit_time"),
                    "start_time": j.get("start_time"),
                    "end_time": j.get("end_time"),
                }
                for j in reply.get("jobs", [])
            ],
        })

    async def _driver_jobs(self, request):
        """Internal driver-job registry (one row per attached driver)."""
        from aiohttp import web

        reply = await self._control("get_all_jobs")
        return web.json_response([
            {
                "job_id": j["job_id"].hex(),
                "finished": j.get("finished", False),
                "age_s": round(time.time() - j.get("start_time", time.time())),
            }
            for j in reply["jobs"]
        ])

    async def _tasks(self, request):
        from aiohttp import web

        reply = await self._control("list_task_events", {"limit": 200})
        return web.json_response([
            {
                "task_id": ev["task_id"].hex()[:12],
                "name": ev["name"],
                "state": ev["event"],
                "duration_s": round(ev.get("duration_s", 0), 4),
            }
            for ev in reply["events"]
        ])

    async def _task_summary(self, request):
        from aiohttp import web

        reply = await self._control("list_task_events", {"limit": 0})
        counts: Dict[str, int] = {}
        latest: Dict[bytes, str] = {}
        for ev in reply["events"]:
            if ev.get("event") == "SPAN":
                continue  # trace annotations, not task state
            latest[ev["task_id"]] = ev["event"]
        for st in latest.values():
            counts[st] = counts.get(st, 0) + 1
        return web.json_response(counts)

    async def _pgs(self, request):
        from aiohttp import web

        reply = await self._control("list_placement_groups")
        return web.json_response([
            {
                "pg_id": pg["pg_id"].hex()[:12],
                "state": pg["state"],
                "bundles": len(pg.get("bundles", [])),
            }
            for pg in reply["pgs"]
        ])

    async def _events(self, request):
        """Structured cluster event stream (reference: the aggregator
        agent's export feed). Query params: source, type, limit."""
        from aiohttp import web as _web

        try:
            payload = {"limit": int(request.query.get("limit", 1000))}
        except ValueError:
            return _web.json_response({"error": "limit must be an int"},
                                      status=400)
        for key in ("source", "type"):
            if request.query.get(key):
                payload[key] = request.query[key]
        from aiohttp import web

        reply = await self._control("list_events", payload)
        return web.json_response(reply["events"])

    async def _cluster_load(self, request):
        from aiohttp import web

        return web.json_response(await self._control("get_cluster_load"))

    async def _flight_recorder(self, request):
        """?node=<hex>: that node's flight-recorder rings (daemon + its
        workers, collected daemon-side); without ?node, the control store's
        ring — the on-demand post-mortem pull (see
        ray_tpu.util.state.dump_flight_recorder for the cluster-wide CLI
        form)."""
        from aiohttp import web

        from ray_tpu.runtime.rpc import RpcError

        node = request.query.get("node", "")
        if not node:
            reply = await self._control("dump_flight_recorder")
            return web.json_response({"control_store": reply})
        try:
            reply = await self._daemon_call(
                node, "collect_flight_recorders", {})
        except ValueError as e:
            return web.json_response({"error": str(e)}, status=404)
        except (RpcError, ConnectionError, OSError) as e:
            return web.json_response(
                {"error": f"daemon unreachable: {e}"}, status=502)
        return web.json_response(reply)

    async def _metrics(self, request):
        """User metrics + built-in system series (rt_node_*, rt_tasks_*,
        rt_actors_*) in one Prometheus exposition — the scrape target the
        bundled Grafana dashboard reads (reference: dashboard/modules/
        metrics/ ships Prometheus+Grafana configs the same way)."""
        from aiohttp import web

        text = await render_metrics_text(self._control)
        return web.Response(text=text, content_type="text/plain")

    async def stop(self) -> bool:
        if self._runner is not None:
            await self._runner.cleanup()
        return True


async def render_metrics_text(control) -> str:
    """The /metrics scrape body, given an async `control(method, payload)`
    callable. Module-level (not actor state) so the outage/malformed-data
    resilience is directly testable: a dead control store or a malformed
    worker snapshot must degrade the scrape, never 500 it."""
    from ray_tpu.util.metrics import render_prometheus

    try:
        reply = await control("get_metrics")
        workers = reply["workers"]
    except Exception:  # noqa: BLE001 — store outage: system series may
        # still answer from a recovering store below; user metrics resume
        # on the next scrape
        workers = {}
    lines = [render_prometheus(workers).rstrip()]

    # system series are best-effort: a transient control-store error on
    # any of them must not 500 the scrape and drop the user metrics
    async def _system_series():
        out = []
        try:
            stats = (await control("get_node_stats"))["stats"]
        except Exception:  # noqa: BLE001
            stats = {}
        gauges = {"cpu_percent": "rt_node_cpu_percent",
                  "mem_percent": "rt_node_mem_percent",
                  "store_bytes": "rt_node_store_bytes"}
        for skey, mname in gauges.items():
            rows = [(n, s[skey]) for n, s in stats.items() if skey in s]
            if not rows:
                continue
            out.append(f"# TYPE {mname} gauge")
            for node, val in sorted(rows):
                out.append(f'{mname}{{node="{node[:12]}"}} {val}')

        nodes = (await control("get_all_nodes"))["nodes"]
        alive = sum(1 for n in nodes if n["state"] == "ALIVE")
        out.append("# TYPE rt_nodes_alive gauge")
        out.append(f"rt_nodes_alive {alive}")

        actors = (await control("list_actors"))["actors"]
        acounts: Dict[str, int] = {}
        for a in actors:
            acounts[str(a["state"])] = acounts.get(str(a["state"]), 0) + 1
        out.append("# TYPE rt_actors_total gauge")
        for st, n in sorted(acounts.items()):
            out.append(f'rt_actors_total{{state="{st}"}} {n}')

        evs = await control("list_task_events", {"limit": 0})
        latest: Dict[bytes, str] = {}
        for ev in evs["events"]:
            if ev.get("event") == "SPAN":
                continue  # trace annotations, not task state
            latest[ev["task_id"]] = ev["event"]
        tcounts: Dict[str, int] = {}
        for st in latest.values():
            tcounts[st] = tcounts.get(st, 0) + 1
        out.append("# TYPE rt_tasks_total gauge")
        for st, n in sorted(tcounts.items()):
            out.append(f'rt_tasks_total{{state="{st}"}} {n}')
        # task-event loss accounting (store-side view; the per-process
        # counter rides the user-metric plane as
        # rt_task_events_dropped_total)
        out.append("# TYPE rt_task_events_store_dropped_total counter")
        out.append(
            f"rt_task_events_store_dropped_total {evs.get('dropped', 0)}")
        return out

    try:
        lines.extend(await _system_series())
    except Exception:  # noqa: BLE001 — user metrics still render
        pass

    return "\n".join(lines) + "\n"


def start_dashboard(host: str = "127.0.0.1", port: int = 8265) -> str:
    """Start (or reuse) the dashboard; returns its URL (reference: the
    dashboard head process started by `ray start --head`)."""
    try:
        actor = ray_tpu.get_actor(DASHBOARD_NAME, namespace=DASH_NAMESPACE)
    except ValueError:
        actor = DashboardActor.options(
            name=DASHBOARD_NAME, namespace=DASH_NAMESPACE,
            lifetime="detached", max_concurrency=64,
        ).remote(host=host, port=port)
    return ray_tpu.get(actor.ready.remote(), timeout=60)


def stop_dashboard():
    try:
        actor = ray_tpu.get_actor(DASHBOARD_NAME, namespace=DASH_NAMESPACE)
    except ValueError:
        return
    try:
        ray_tpu.get(actor.stop.remote(), timeout=30)
    except Exception:  # noqa: BLE001
        pass
    ray_tpu.kill(actor)


__all__ = ["DashboardActor", "render_metrics_text", "start_dashboard",
           "stop_dashboard"]
