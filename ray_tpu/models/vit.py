"""Vision Transformer — the image-model family, TPU-first.

Covers the reference's vision workloads (image-classification training is
Ray Train's headline GPU benchmark, doc/source/train/benchmarks.rst:31-47,
and Ray Data's image pipelines feed it) with a native model instead of a
delegated torchvision one.

TPU-first choices mirror models/llama.py:
- patchify is a reshape + ONE matmul (a conv with stride=kernel is exactly
  that; the matmul form rides the MXU with no im2col),
- encoder layers are stacked and run under `lax.scan` (one compiled layer),
- attention reuses the Pallas flash kernel non-causally (bidirectional),
- every parameter carries a PartitionSpec (megatron tp + fsdp), activations
  constrained to the dp/fsdp batch axes — DP/FSDP/TP come from GSPMD.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Dict

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.mesh import BATCH_AXES, constrain


@dataclass(frozen=True)
class ViTConfig:
    image_size: int = 224
    patch_size: int = 16
    channels: int = 3
    dim: int = 768
    n_layers: int = 12
    n_heads: int = 12
    mlp_dim: int = 3072
    num_classes: int = 1000
    norm_eps: float = 1e-6
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    attention_impl: str = "flash"  # "flash" (pallas) | "xla"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_patches(self) -> int:
        return (self.image_size // self.patch_size) ** 2

    @property
    def patch_dim(self) -> int:
        return self.channels * self.patch_size * self.patch_size

    @classmethod
    def base(cls, **kw) -> "ViTConfig":  # ViT-B/16
        return cls(**kw)

    @classmethod
    def large(cls, **kw) -> "ViTConfig":  # ViT-L/16
        return cls(dim=1024, n_layers=24, n_heads=16, mlp_dim=4096, **kw)

    @classmethod
    def tiny(cls, **kw) -> "ViTConfig":
        return cls(image_size=32, patch_size=8, dim=64, n_layers=2,
                   n_heads=4, mlp_dim=128, num_classes=10, **kw)

    def num_params(self) -> int:
        per_layer = (
            4 * self.dim * self.dim          # wq wk wv wo
            + 2 * self.dim * self.mlp_dim    # w1 w2
            + self.mlp_dim + self.dim        # biases
            + 4 * self.dim                   # 2 LN scale+bias
        )
        return (
            self.patch_dim * self.dim + self.dim       # patch embed + bias
            + self.num_patches * self.dim              # pos emb
            + self.n_layers * per_layer
            + 2 * self.dim                             # final LN
            + self.dim * self.num_classes + self.num_classes
        )


def param_specs(cfg: ViTConfig) -> Dict[str, Any]:
    """Megatron layout: qkv/w1 column-parallel (tp on the output dim),
    wo/w2 row-parallel; fsdp shards the other dim (ZeRO-3 via GSPMD)."""
    return {
        "patch_emb": P("fsdp", "tp"),
        "patch_bias": P(None),
        "pos_emb": P(None, "fsdp"),
        "layers": {
            "ln1_scale": P(None, None), "ln1_bias": P(None, None),
            "ln2_scale": P(None, None), "ln2_bias": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "w1": P(None, "fsdp", "tp"),
            "b1": P(None, "tp"),
            "w2": P(None, "tp", "fsdp"),
            "b2": P(None, "fsdp"),
        },
        "norm_scale": P(None), "norm_bias": P(None),
        "head": P("fsdp", "tp"),
        "head_bias": P("tp"),
    }


def init_params(cfg: ViTConfig, key: jax.Array) -> Dict[str, Any]:
    ks = iter(jax.random.split(key, 8))
    pd = cfg.param_dtype
    L, D, M = cfg.n_layers, cfg.dim, cfg.mlp_dim

    def dense(k, shape, fan_in):
        return (jax.random.normal(k, shape, pd) / jnp.sqrt(fan_in)).astype(pd)

    return {
        "patch_emb": dense(next(ks), (cfg.patch_dim, D), cfg.patch_dim),
        "patch_bias": jnp.zeros((D,), pd),
        "pos_emb": 0.02 * jax.random.normal(
            next(ks), (cfg.num_patches, D), pd),
        "layers": {
            "ln1_scale": jnp.ones((L, D), pd),
            "ln1_bias": jnp.zeros((L, D), pd),
            "ln2_scale": jnp.ones((L, D), pd),
            "ln2_bias": jnp.zeros((L, D), pd),
            "wq": dense(next(ks), (L, D, D), D),
            "wk": dense(next(ks), (L, D, D), D),
            "wv": dense(next(ks), (L, D, D), D),
            "wo": dense(next(ks), (L, D, D), D),
            "w1": dense(next(ks), (L, D, M), D),
            "b1": jnp.zeros((L, M), pd),
            "w2": dense(next(ks), (L, M, D), M),
            "b2": jnp.zeros((L, D), pd),
        },
        "norm_scale": jnp.ones((D,), pd),
        "norm_bias": jnp.zeros((D,), pd),
        "head": jnp.zeros((D, cfg.num_classes), pd),
        "head_bias": jnp.zeros((cfg.num_classes,), pd),
    }


def layer_norm(x, scale, bias, eps):
    x32 = x.astype(jnp.float32)
    mean = x32.mean(-1, keepdims=True)
    var = x32.var(-1, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    return (out * scale + bias).astype(x.dtype)


def _attention(cfg: ViTConfig, q, k, v):
    """Bidirectional attention, (b, s, h, hd) layout."""
    if cfg.attention_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=False)
    scale = 1.0 / jnp.sqrt(cfg.head_dim).astype(q.dtype)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _layer(cfg: ViTConfig, mesh, h, lp):
    dt = cfg.dtype
    b, s, d = h.shape
    x = layer_norm(h, lp["ln1_scale"], lp["ln1_bias"], cfg.norm_eps)
    q = (x @ lp["wq"].astype(dt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    k = (x @ lp["wk"].astype(dt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    v = (x @ lp["wv"].astype(dt)).reshape(b, s, cfg.n_heads, cfg.head_dim)
    o = _attention(cfg, q, k, v).reshape(b, s, d)
    h = h + o @ lp["wo"].astype(dt)
    x = layer_norm(h, lp["ln2_scale"], lp["ln2_bias"], cfg.norm_eps)
    x = jax.nn.gelu(x @ lp["w1"].astype(dt) + lp["b1"].astype(dt))
    h = h + (x @ lp["w2"].astype(dt) + lp["b2"].astype(dt))
    if mesh is not None:
        h = constrain(h, mesh, P(BATCH_AXES, None, None))
    return h


def patchify(cfg: ViTConfig, images: jax.Array) -> jax.Array:
    """(b, H, W, C) -> (b, num_patches, patch_dim) via pure reshapes."""
    b = images.shape[0]
    p, n = cfg.patch_size, cfg.image_size // cfg.patch_size
    x = images.reshape(b, n, p, n, p, cfg.channels)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, n * n, cfg.patch_dim)


def forward(cfg: ViTConfig, params: Dict[str, Any], images: jax.Array,
            mesh=None) -> jax.Array:
    """Logits (b, num_classes); mean-pooled encoder output."""
    dt = cfg.dtype
    h = patchify(cfg, images).astype(dt) @ params["patch_emb"].astype(dt)
    h = h + params["patch_bias"].astype(dt) + params["pos_emb"].astype(dt)
    if mesh is not None:
        h = constrain(h, mesh, P(BATCH_AXES, None, None))

    def body(carry, lp):
        return _layer(cfg, mesh, carry, lp), None

    h, _ = jax.lax.scan(body, h, params["layers"])
    h = layer_norm(h, params["norm_scale"], params["norm_bias"], cfg.norm_eps)
    pooled = h.mean(axis=1)
    logits = pooled.astype(jnp.float32) @ params["head"].astype(jnp.float32)
    return logits + params["head_bias"].astype(jnp.float32)


def make_train_step(cfg: ViTConfig, mesh: Mesh, learning_rate: float = 1e-3,
                    remat=False):
    """(init_state, shard_state, train_step, data_sharding) — same contract
    as models.llama.make_train_step; cross-entropy on integer labels."""
    import optax

    from ray_tpu.parallel.mesh import data_spec, logical_to_sharding

    tx = optax.adamw(learning_rate)
    param_shardings = logical_to_sharding(param_specs(cfg), mesh)
    layer = partial(_layer, cfg, mesh)
    if remat:
        layer = jax.checkpoint(layer)

    def compute_loss(params, images, labels):
        dt = cfg.dtype
        h = patchify(cfg, images).astype(dt) @ params["patch_emb"].astype(dt)
        h = h + params["patch_bias"].astype(dt) + params["pos_emb"].astype(dt)
        h = constrain(h, mesh, P(BATCH_AXES, None, None))

        def body(carry, lp):
            return layer(carry, lp), None

        h, _ = jax.lax.scan(body, h, params["layers"])
        h = layer_norm(h, params["norm_scale"], params["norm_bias"],
                       cfg.norm_eps)
        pooled = h.mean(axis=1)
        logits = (pooled.astype(jnp.float32) @ params["head"].astype(jnp.float32)
                  + params["head_bias"].astype(jnp.float32))
        logp = jax.nn.log_softmax(logits)
        nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
        return nll.mean()

    def init_state(key):
        params = init_params(cfg, key)
        return params, tx.init(params)

    def train_step(state, images, labels):
        params, opt_state = state
        loss, grads = jax.value_and_grad(compute_loss)(params, images, labels)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    data_sharding = jax.sharding.NamedSharding(
        mesh, P(BATCH_AXES, None, None, None))
    label_sharding = jax.sharding.NamedSharding(mesh, P(BATCH_AXES))

    def shard_state(state):
        from ray_tpu.parallel.mesh import shard_train_state

        params, opt_state = state
        return shard_train_state(params, opt_state, param_shardings, mesh)

    jitted = jax.jit(train_step, donate_argnums=(0,))
    return init_state, shard_state, jitted, (data_sharding, label_sharding)


__all__ = [
    "ViTConfig",
    "forward",
    "init_params",
    "make_train_step",
    "param_specs",
    "patchify",
]
