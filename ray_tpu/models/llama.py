"""Llama-family decoder — the flagship model, TPU-first.

Pure-functional JAX (params are a pytree; layers are STACKED and executed with
`lax.scan` so XLA compiles one layer once regardless of depth — compile time
stays flat as models grow). bfloat16 activations/matmuls feed the MXU; RoPE,
GQA, RMSNorm, SwiGLU match Llama-2/3 semantics.

Parallelism is declared, not hand-written: every parameter carries a
PartitionSpec (megatron tp on the contracting 'parallel' dim, fsdp on the
other — ZeRO-3 semantics emerge from GSPMD all-gather/reduce-scatter), and
activations are constrained to ((«dp","fsdp»), "sp", None). Sequence
parallelism can route attention through ring attention
(ray_tpu.parallel.ring_attention) instead of GSPMD's KV all-gather.

Capability reference: the models Ray serves/trains via vLLM & TorchTrainer
(e.g. python/ray/llm/ engines; BASELINE.json configs 3/5 — Llama-2-7B LoRA,
Llama-3-8B serving); the framework itself has no native model zoo — this one
does, by design.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ray_tpu.parallel.mesh import BATCH_AXES, MeshSpec, constrain


@dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_dim: int = 11008
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    max_seq_len: int = 4096
    dtype: Any = jnp.bfloat16  # activation/compute dtype
    param_dtype: Any = jnp.float32
    # attention implementation: "xla" (GSPMD), "ring" (ppermute SP),
    # "flash" (pallas kernel on TPU)
    attention_impl: str = "xla"

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @classmethod
    def llama2_7b(cls, **kw) -> "LlamaConfig":
        return cls(dim=4096, n_layers=32, n_heads=32, n_kv_heads=32,
                   ffn_dim=11008, **kw)

    @classmethod
    def llama3_8b(cls, **kw) -> "LlamaConfig":
        return cls(vocab_size=128256, dim=4096, n_layers=32, n_heads=32,
                   n_kv_heads=8, ffn_dim=14336, rope_theta=500000.0, **kw)

    @classmethod
    def tiny(cls, **kw) -> "LlamaConfig":
        """Test/CI-size config."""
        return cls(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                   n_kv_heads=2, ffn_dim=256, max_seq_len=256, **kw)

    def num_params(self) -> int:
        hd = self.head_dim
        per_layer = (
            self.dim * self.n_heads * hd          # wq
            + 2 * self.dim * self.n_kv_heads * hd  # wk, wv
            + self.n_heads * hd * self.dim         # wo
            + 3 * self.dim * self.ffn_dim          # w1, w2, w3 (w2 transposed)
            + 2 * self.dim                         # ln1, ln2
        )
        return (
            self.vocab_size * self.dim             # tok_emb
            + self.n_layers * per_layer
            + self.dim                             # final norm
            + self.dim * self.vocab_size           # lm_head
        )


# ---------------------------------------------------------------------------
# parameter init + sharding specs
# ---------------------------------------------------------------------------


def param_specs(cfg: LlamaConfig) -> Dict[str, Any]:
    """PartitionSpec pytree matching init_params' structure.

    Leading axis of layer params is the scan (layer) axis — never sharded.
    tp shards the 'parallel' dim (megatron column/row), fsdp the other.
    """
    return {
        # dim rides tp (matching every other column-parallel weight) so the
        # at-use constraint is a pure fsdp all-gather with no axis transpose
        "tok_emb": P("fsdp", "tp"),
        "layers": {
            "ln1": P(None, None),
            "ln2": P(None, None),
            "wq": P(None, "fsdp", "tp"),
            "wk": P(None, "fsdp", "tp"),
            "wv": P(None, "fsdp", "tp"),
            "wo": P(None, "tp", "fsdp"),
            "w1": P(None, "fsdp", "tp"),
            "w3": P(None, "fsdp", "tp"),
            "w2": P(None, "tp", "fsdp"),
        },
        "norm": P(None),
        "lm_head": P("fsdp", "tp"),
    }


def init_params(cfg: LlamaConfig, key: jax.Array) -> Dict[str, Any]:
    hd = cfg.head_dim
    k = iter(jax.random.split(key, 16))
    pd = cfg.param_dtype

    def dense(key, fan_in, shape):
        return (jax.random.normal(key, shape, dtype=jnp.float32)
                * (1.0 / math.sqrt(fan_in))).astype(pd)

    L = cfg.n_layers
    return {
        "tok_emb": dense(next(k), cfg.dim, (cfg.vocab_size, cfg.dim)),
        "layers": {
            "ln1": jnp.ones((L, cfg.dim), pd),
            "ln2": jnp.ones((L, cfg.dim), pd),
            "wq": dense(next(k), cfg.dim, (L, cfg.dim, cfg.n_heads * hd)),
            "wk": dense(next(k), cfg.dim, (L, cfg.dim, cfg.n_kv_heads * hd)),
            "wv": dense(next(k), cfg.dim, (L, cfg.dim, cfg.n_kv_heads * hd)),
            "wo": dense(next(k), cfg.n_heads * hd, (L, cfg.n_heads * hd, cfg.dim)),
            "w1": dense(next(k), cfg.dim, (L, cfg.dim, cfg.ffn_dim)),
            "w3": dense(next(k), cfg.dim, (L, cfg.dim, cfg.ffn_dim)),
            "w2": dense(next(k), cfg.ffn_dim, (L, cfg.ffn_dim, cfg.dim)),
        },
        "norm": jnp.ones((cfg.dim,), pd),
        "lm_head": dense(next(k), cfg.dim, (cfg.dim, cfg.vocab_size)),
    }


# ---------------------------------------------------------------------------
# model pieces
# ---------------------------------------------------------------------------


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    # fp32 statistics even under bf16 activations (numerical parity with
    # the usual implementations)
    x32 = x.astype(jnp.float32)
    rms = jax.lax.rsqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (x32 * rms).astype(x.dtype) * weight.astype(x.dtype)


def rope_tables(cfg: LlamaConfig, positions: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """positions: (..., seq) int32 → cos/sin (..., seq, head_dim/2), fp32."""
    half = cfg.head_dim // 2
    freqs = cfg.rope_theta ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = positions.astype(jnp.float32)[..., None] * freqs
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (b, s, h, hd); cos/sin: (b, s, hd/2) or (s, hd/2)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    if cos.ndim == 2:
        cos, sin = cos[None, :, None, :], sin[None, :, None, :]
    else:
        cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(jnp.bfloat16 if x.dtype == jnp.bfloat16 else x.dtype)


def apply_rope_bhsd(x: jax.Array, cos: jax.Array, sin: jax.Array) -> jax.Array:
    """x: (b, h, s, hd); cos/sin: (s, hd/2)."""
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    cos, sin = cos[None, None, :, :], sin[None, None, :, :]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1
    ).astype(jnp.bfloat16 if x.dtype == jnp.bfloat16 else x.dtype)


def _attention_xla(q, k, v, causal: bool = True):
    """Plain XLA attention; fp32 softmax. q: (b, s, h, hd), k/v (b, s, kv, hd)."""
    b, sq, h, hd = q.shape
    kv = k.shape[2]
    if kv != h:  # GQA: repeat kv heads
        rep = h // kv
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    scale = 1.0 / math.sqrt(hd)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    if causal:
        sk = k.shape[1]
        mask = jnp.tril(jnp.ones((sq, sk), dtype=bool), k=sk - sq)
        logits = jnp.where(mask[None, None], logits, -1e30)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def attention(cfg: LlamaConfig, q, k, v, mesh: Optional[Mesh]):
    if cfg.attention_impl == "ring" and mesh is not None and mesh.shape["sp"] > 1:
        from ray_tpu.parallel.ring_attention import ring_attention_sharded

        return ring_attention_sharded(q, k, v, mesh, causal=True)
    if cfg.attention_impl == "ulysses" and mesh is not None and mesh.shape["sp"] > 1:
        from ray_tpu.parallel.ulysses import ulysses_attention_sharded

        return ulysses_attention_sharded(q, k, v, mesh, causal=True)
    if cfg.attention_impl == "flash":
        from ray_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=True)
    return _attention_xla(q, k, v, causal=True)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _gather_for_use(w, mesh, spec):
    return constrain(w, mesh, spec)


def _gather_for_use_fwd(w, mesh, spec):
    return constrain(w, mesh, spec), None


def _gather_for_use_bwd(mesh, spec, _res, g):
    # cotangent passes through UNconstrained: pinning the grad to the
    # gathered spec would force all-reduce + slice instead of letting XLA
    # reduce-scatter straight into the fsdp-sharded grad accumulator
    return (g,)


_gather_for_use.defvjp(_gather_for_use_fwd, _gather_for_use_bwd)


def _use(mesh: Optional[Mesh], w, spec: P):
    """Constrain a parameter AT USE (forward only): fsdp-sharded storage is
    all-gathered here (ZeRO-3 semantics) while the tp (megatron) sharding is
    kept. This pins XLA's contraction strategy to batch-sharded activations —
    without it the partitioner prefers contracting-dim-sharded activations
    for the matmuls, conflicting with the scan carry's batch sharding and
    forcing an involuntary full rematerialization per layer (VERDICT r3
    weak #2)."""
    if mesh is None:
        return w
    if mesh.shape.get("fsdp", 1) == 1 and mesh.shape.get("tp", 1) == 1:
        # nothing to gather or pin — and a trivial sharding_constraint is
        # not free: it blocks fusion around the weight on a single chip
        return w
    return _gather_for_use(w, mesh, spec)


def _ffn(cfg: LlamaConfig, mesh: Optional[Mesh], h, p):
    dt = cfg.dtype
    x = rms_norm(h, p["ln2"], cfg.norm_eps)
    gate = jax.nn.silu(x @ _use(mesh, p["w1"].astype(dt), P(None, "tp")))
    up = x @ _use(mesh, p["w3"].astype(dt), P(None, "tp"))
    out = (gate * up) @ _use(mesh, p["w2"].astype(dt), P("tp", None))
    if mesh is not None:
        out = constrain(out, mesh, P(BATCH_AXES, "sp", None))
    return out


def _layer(cfg: LlamaConfig, mesh: Optional[Mesh], h, layer_params, cos, sin,
           remat_ffn: bool = False):
    p = layer_params
    hd = cfg.head_dim
    b, s, _ = h.shape
    dt = cfg.dtype

    x = rms_norm(h, p["ln1"], cfg.norm_eps)
    if cfg.attention_impl == "flash":
        # bhsd hot path: projections emit (b, h, s, hd) directly — head_dim
        # rides the 128-lane dimension into the kernel, no transposes.
        from ray_tpu.ops.flash_attention import flash_attention_bhsd

        wq = _use(mesh, p["wq"].astype(dt), P(None, "tp")).reshape(
            cfg.dim, cfg.n_heads, hd)
        wk = _use(mesh, p["wk"].astype(dt), P(None, "tp")).reshape(
            cfg.dim, cfg.n_kv_heads, hd)
        wv = _use(mesh, p["wv"].astype(dt), P(None, "tp")).reshape(
            cfg.dim, cfg.n_kv_heads, hd)
        q = jnp.einsum("bsd,dhk->bhsk", x, wq)
        k = jnp.einsum("bsd,dhk->bhsk", x, wk)
        v = jnp.einsum("bsd,dhk->bhsk", x, wv)
        q = apply_rope_bhsd(q, cos, sin)
        k = apply_rope_bhsd(k, cos, sin)
        o = flash_attention_bhsd(q, k, v, causal=True)
        wo = _use(mesh, p["wo"].astype(dt), P("tp", None)).reshape(
            cfg.n_heads, hd, cfg.dim)
        attn = jnp.einsum("bhsk,hkd->bsd", o, wo)
    else:
        q = (x @ _use(mesh, p["wq"].astype(dt), P(None, "tp"))).reshape(
            b, s, cfg.n_heads, hd)
        k = (x @ _use(mesh, p["wk"].astype(dt), P(None, "tp"))).reshape(
            b, s, cfg.n_kv_heads, hd)
        v = (x @ _use(mesh, p["wv"].astype(dt), P(None, "tp"))).reshape(
            b, s, cfg.n_kv_heads, hd)
        q = apply_rope(q, cos, sin)
        k = apply_rope(k, cos, sin)
        attn = attention(cfg, q, k, v, mesh)
        attn = attn.reshape(b, s, cfg.n_heads * hd) @ _use(
            mesh, p["wo"].astype(dt), P("tp", None))
    if mesh is not None:
        attn = constrain(attn, mesh, P(BATCH_AXES, "sp", None))
    h = h + attn

    ffn = _ffn
    if remat_ffn:
        ffn = jax.checkpoint(_ffn, static_argnums=(0, 1))
    return h + ffn(cfg, mesh, h, p)


def forward(
    cfg: LlamaConfig,
    params: Dict[str, Any],
    tokens: jax.Array,
    mesh: Optional[Mesh] = None,
    positions: Optional[jax.Array] = None,
) -> jax.Array:
    """tokens (b, s) int32 → logits (b, s, vocab) in fp32."""
    dt = cfg.dtype
    h = _use(mesh, params["tok_emb"].astype(dt), P(None, "tp"))[tokens]
    if mesh is not None:
        h = constrain(h, mesh, P(BATCH_AXES, "sp", None))
    if positions is None:
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
    cos, sin = rope_tables(cfg, positions)

    def body(carry, layer_params):
        return _layer(cfg, mesh, carry, layer_params, cos, sin), None

    h, _ = jax.lax.scan(body, h, params["layers"])
    h = rms_norm(h, params["norm"], cfg.norm_eps)
    logits = h @ _use(mesh, params["lm_head"].astype(dt), P(None, "tp"))
    return logits.astype(jnp.float32)


def loss_fn(cfg, params, tokens, mesh=None):
    """Next-token cross entropy; tokens (b, s)."""
    logits = forward(cfg, params, tokens[:, :-1], mesh)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    return nll.mean()


# ---------------------------------------------------------------------------
# training step factory
# ---------------------------------------------------------------------------


def make_train_step(cfg: LlamaConfig, mesh: Mesh, learning_rate: float = 3e-4,
                    remat=False, loss_chunk: int = 512):
    """Build (init_state, jitted train_step) sharded over `mesh`.

    State = (params, opt_state). Donated on update. AdamW via optax.
    `remat` selects the HBM↔FLOPs trade per scanned layer:
      False  — save all layer activations (fastest when memory allows; the
               flash-attention custom VJP already avoids (s,s) residuals)
      "ffn"  — rematerialize only the FFN block (recomputes the cheap
               elementwise + 3 matmuls; attention residuals kept)
      "dots" — jax.checkpoint with dots_with_no_batch_dims_saveable policy
      True   — full per-layer rematerialization (long-context fallback)
    """
    import optax

    from ray_tpu.parallel.mesh import data_spec, logical_to_sharding

    tx = optax.adamw(learning_rate)
    specs = param_specs(cfg)
    param_shardings = logical_to_sharding(specs, mesh)

    lcfg = cfg
    layer = partial(_layer, lcfg, mesh)
    if remat == "ffn":
        layer = partial(_layer, lcfg, mesh, remat_ffn=True)
    elif remat == "dots":
        layer = jax.checkpoint(
            layer, policy=jax.checkpoint_policies.dots_with_no_batch_dims_saveable)
    elif remat:
        layer = jax.checkpoint(layer)

    def backbone(params, tokens):
        dt = lcfg.dtype
        h = _use(mesh, params["tok_emb"].astype(dt), P(None, "tp"))[tokens]
        h = constrain(h, mesh, P(BATCH_AXES, "sp", None))
        positions = jnp.arange(tokens.shape[1], dtype=jnp.int32)
        cos, sin = rope_tables(lcfg, positions)

        def body(carry, lp):
            return layer(carry, lp, cos, sin), None

        h, _ = jax.lax.scan(body, h, params["layers"])
        return rms_norm(h, params["norm"], lcfg.norm_eps)

    # The (b, s, vocab) fp32 logits (and their log_softmax) are by far the
    # largest activations; computing the loss in sequence chunks under
    # jax.checkpoint keeps only one chunk's logits live at a time in both
    # directions (the chunk is recomputed from `h` in the backward pass).
    chunk = loss_chunk

    def _chunk_nll(params, h_c, tgt_c, mask_c):
        """Masked NLL sum over one sequence chunk. tgt -1 = no target."""
        dt = lcfg.dtype
        logits = (h_c @ _use(mesh, params["lm_head"].astype(dt),
                             P(None, "tp"))).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        tgt = jnp.maximum(tgt_c, 0)
        nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
        return (nll * mask_c).sum()

    def compute_loss(params, tokens):
        # forward on the FULL sequence (keeps the input length divisible by
        # the sp axis for sharding); position s-1 has no target and is masked
        # out instead of sliced off, so the chunking below divides evenly
        h = backbone(params, tokens)
        b, s = tokens.shape
        targets = jnp.concatenate(
            [tokens[:, 1:], jnp.full((b, 1), -1, tokens.dtype)], axis=1)
        mask = (targets >= 0).astype(jnp.float32)
        denom = mask.sum()
        if chunk and s % chunk == 0 and s > chunk:
            hs = h.reshape(b, s // chunk, chunk, lcfg.dim).swapaxes(0, 1)
            ts = targets.reshape(b, s // chunk, chunk).swapaxes(0, 1)
            ms = mask.reshape(b, s // chunk, chunk).swapaxes(0, 1)
            nll_fn = jax.checkpoint(partial(_chunk_nll, params))
            total = jax.lax.map(lambda htm: nll_fn(*htm), (hs, ts, ms)).sum()
            return total / denom
        return _chunk_nll(params, h, targets, mask) / denom

    def init_state(key):
        params = init_params(cfg, key)
        opt_state = tx.init(params)
        return params, opt_state

    def train_step(state, tokens):
        params, opt_state = state
        loss, grads = jax.value_and_grad(compute_loss)(params, tokens)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return (params, opt_state), loss

    data_sharding = jax.sharding.NamedSharding(mesh, data_spec())

    def shard_state(state):
        """Place a (params, opt_state) pytree onto the mesh (moment leaves
        matched to param shardings by key-path suffix — see
        parallel.mesh.shard_train_state)."""
        from ray_tpu.parallel.mesh import shard_train_state

        params, opt_state = state
        return shard_train_state(params, opt_state, param_shardings, mesh)

    jitted = jax.jit(train_step, donate_argnums=(0,))
    return init_state, shard_state, jitted, data_sharding
