"""TPU/GCE preemption watcher: turn the 30-90s of warning a spot or
maintenance-scheduled TPU VM gets into a graceful drain.

Reference surface: the GCE metadata server's maintenance-event and
preemption endpoints (the signals python/ray/autoscaler and cloud TPU
training loops poll) plus the ACPI SIGTERM a preempted VM receives.
Redesign: one watcher object owned by the node daemon, speaking the
metadata HTTP surface through a swappable `MetadataTransport` seam so the
exact production path runs offline against `FakeMetadataTransport` — the
same fake-transport pattern as `autoscaler/gcp.py`.

On a notice the watcher fires exactly once. In legacy (reactive) mode it
invokes `on_notice(reason, deadline_s)` immediately; the daemon's
`_self_drain` routes it through the control store's DrainNode protocol
(stop granting leases, finish running work, replicate primary copies,
migrate actors, exit with an expected-termination record).

With a `publish` callable and `preempt_proactive` on, the watcher instead
publishes a TTL'd `report_preemption_notice{node_id, deadline_s}` into the
control store and keeps re-publishing it every `preempt_republish_period_s`
(idempotent — the store only refreshes the TTL, never extends the deadline,
so the notice also survives a control-store failover mid-window). The node
sits in the reversible PREEMPTING state while the autoscaler pre-provisions
replacement capacity; the drain itself is started by the control plane once
replacements register, and only if that hasn't happened by
`preempt_drain_grace_frac` of the deadline does the watcher force the
legacy self-drain with whatever deadline remains — overlapping node boot
with the drain window instead of serializing them.
"""

from __future__ import annotations

import asyncio
import logging
import signal
from typing import Awaitable, Callable, Optional

from ray_tpu._private.config import GLOBAL_CONFIG
from ray_tpu._private.protocol import DRAIN_REASON_PREEMPTION

logger = logging.getLogger(__name__)

_METADATA_BASE = ("http://metadata.google.internal/computeMetadata/v1/"
                  "instance")
MAINTENANCE_URL = f"{_METADATA_BASE}/maintenance-event"
PREEMPTED_URL = f"{_METADATA_BASE}/preempted"

# maintenance-event values that mean "this host is about to go away"
_TERMINAL_EVENTS = ("TERMINATE_ON_HOST_MAINTENANCE", "MIGRATE_ON_HOST_MAINTENANCE")


class MetadataTransport:
    """The HTTP seam: get(url) -> response body string (or raise)."""

    def get(self, url: str) -> str:
        raise NotImplementedError


class GceMetadataTransport(MetadataTransport):
    """Real transport against the GCE metadata server."""

    def __init__(self, timeout: float = 2.0):
        self.timeout = timeout

    def get(self, url: str) -> str:
        import urllib.request

        req = urllib.request.Request(
            url, headers={"Metadata-Flavor": "Google"})
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            return resp.read().decode("utf-8", "replace").strip()


class FakeMetadataTransport(MetadataTransport):
    """Offline simulation: tests flip `maintenance_event`/`preempted` and
    the watcher reacts exactly as it would on a real TPU VM."""

    def __init__(self):
        self.maintenance_event = "NONE"
        self.preempted = "FALSE"
        self.calls = 0

    def schedule_maintenance(self):
        self.maintenance_event = "TERMINATE_ON_HOST_MAINTENANCE"

    def preempt(self):
        self.preempted = "TRUE"

    def clear(self):
        """The scheduled event passed / the reclaim was cancelled (spot
        capacity returned). Pairs with PreemptionWatcher.rearm() in elastic
        soak tests that preempt the same simulated host repeatedly."""
        self.maintenance_event = "NONE"
        self.preempted = "FALSE"

    def get(self, url: str) -> str:
        self.calls += 1
        if url == MAINTENANCE_URL:
            return self.maintenance_event
        if url == PREEMPTED_URL:
            return self.preempted
        raise ValueError(f"FakeMetadataTransport: unhandled {url}")


class PreemptionWatcher:
    """Polls the metadata endpoints (and optionally hooks SIGTERM) and
    fires `on_notice(reason, deadline_s)` once when the host is scheduled
    to die. Owned by the node daemon; runs on its event loop."""

    def __init__(self, on_notice: Callable[[str, float], Awaitable],
                 transport: Optional[MetadataTransport] = None,
                 poll_period_s: Optional[float] = None,
                 drain_deadline_s: Optional[float] = None,
                 hook_sigterm: bool = False,
                 publish: Optional[Callable[[float], Awaitable]] = None,
                 drain_started: Optional[Callable[[], bool]] = None):
        self.on_notice = on_notice
        self.transport = transport or GceMetadataTransport()
        self.poll_period_s = (
            poll_period_s
            if poll_period_s is not None
            else GLOBAL_CONFIG.get("preemption_poll_period_s"))
        self.drain_deadline_s = (
            drain_deadline_s
            if drain_deadline_s is not None
            else GLOBAL_CONFIG.get("drain_deadline_s"))
        self.hook_sigterm = hook_sigterm
        # proactive seam: publish(deadline_remaining_s) files the TTL'd
        # notice at the control store; drain_started() tells the republish
        # loop the control plane has taken over (daemon began its drain)
        self.publish = publish
        self.drain_started = drain_started
        self.fired = False
        self._stopped = False
        # telemetry for tests/bench: how many times the notice was
        # (re-)published, and whether the grace deadline forced the drain
        self.publishes = 0
        self.forced_drains = 0

    def stop(self):
        self._stopped = True

    def rearm(self):
        """Reset the one-shot latch so a NEW `run()` can fire again.

        A GCE maintenance event can be cancelled (or a drain undrained by
        the autoscaler when capacity demand returns) — a watcher that
        stays latched after a survived notice would sleep through the
        NEXT reclaim of the same host. `run()` returns once it fires, so
        the owner must re-arm AND schedule `run()` again (spawn a fresh
        task); rearm alone does not resurrect the finished poll loop. The
        elastic train plane preempts the same node repeatedly across
        shrink/regrow cycles; re-arm after the drain resolves."""
        self.fired = False

    async def _fire(self, cause: str):
        if self.fired:
            return
        self.fired = True
        if self.publish is not None and GLOBAL_CONFIG.get("preempt_proactive"):
            await self._fire_proactive(cause)
            return
        logger.warning("preemption notice (%s): draining node with %.1fs "
                       "deadline", cause, self.drain_deadline_s)
        try:
            await self.on_notice(DRAIN_REASON_PREEMPTION,
                                 self.drain_deadline_s)
        except Exception:  # noqa: BLE001 — the drain path logs its own
            logger.exception("preemption drain callback failed")

    async def _fire_proactive(self, cause: str):
        """Publish-and-wait: keep the TTL'd notice fresh while the control
        plane pre-provisions, force the self-drain at the grace point."""
        loop = asyncio.get_running_loop()
        deadline_ts = loop.time() + self.drain_deadline_s
        grace_frac = GLOBAL_CONFIG.get("preempt_drain_grace_frac")
        grace_ts = loop.time() + self.drain_deadline_s * grace_frac
        period = GLOBAL_CONFIG.get("preempt_republish_period_s")
        logger.warning(
            "preemption notice (%s): publishing PREEMPTING with %.1fs "
            "deadline, drain grace at %.1fs", cause, self.drain_deadline_s,
            self.drain_deadline_s * grace_frac)
        while not self._stopped:
            if self.drain_started is not None and self.drain_started():
                # the control plane started the drain (replacement capacity
                # registered, or an operator drained us) — the daemon's
                # normal drain orchestration owns the exit from here
                return
            now = loop.time()
            if now >= grace_ts:
                break
            try:
                # idempotent: the store refreshes the TTL and keeps
                # min(prior, new) as the deadline — re-publishing every
                # period is also what survives a control-store failover
                # mid-notice (the new primary may have an expired/absent
                # entry until this lands)
                await self.publish(max(0.1, deadline_ts - now))
                self.publishes += 1
            except Exception:  # noqa: BLE001 — store unreachable/failover
                logger.warning("preemption-notice publish failed; retrying",
                               exc_info=True)
            await asyncio.sleep(
                max(0.05, min(period, grace_ts - loop.time())))
        if self._stopped:
            return
        self.forced_drains += 1
        remaining = max(0.1, deadline_ts - loop.time())
        logger.warning("preemption drain grace expired: forcing self-drain "
                       "with %.1fs remaining", remaining)
        try:
            await self.on_notice(DRAIN_REASON_PREEMPTION, remaining)
        except Exception:  # noqa: BLE001 — the drain path logs its own
            logger.exception("preemption drain callback failed")

    def _poll_once(self) -> Optional[str]:
        """Returns the cause string when a terminal notice is present."""
        try:
            ev = self.transport.get(MAINTENANCE_URL)
            if ev in _TERMINAL_EVENTS:
                return f"maintenance-event {ev}"
            pre = self.transport.get(PREEMPTED_URL)
            if pre.upper() == "TRUE":
                return "instance preempted"
        except Exception:  # noqa: BLE001 — metadata server unreachable
            # (not on GCE, or transient): nothing to act on
            return None
        return None

    async def run(self):
        if self.hook_sigterm:
            # a preempted VM gets SIGTERM ~30s before hard power-off; hook
            # it so the drain starts even if the metadata poll is slow
            loop = asyncio.get_running_loop()
            try:
                loop.add_signal_handler(
                    signal.SIGTERM,
                    lambda: loop.create_task(self._fire("SIGTERM")))
            except (NotImplementedError, RuntimeError, ValueError):
                pass  # non-main thread / unsupported platform
        while not self._stopped and not self.fired:
            cause = await asyncio.to_thread(self._poll_once)
            if cause:
                await self._fire(cause)
                return
            await asyncio.sleep(self.poll_period_s)


__all__ = [
    "FakeMetadataTransport",
    "GceMetadataTransport",
    "MetadataTransport",
    "PreemptionWatcher",
]
