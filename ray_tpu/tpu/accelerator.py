"""TPU accelerator manager: detection, visibility, resource naming.

Capability parity with the reference's TPU accelerator plugin (reference:
python/ray/_private/accelerators/tpu.py — GCE metadata + GKE env detection
:24-40, TPU_VISIBLE_CHIPS :42, chips/host logic :155-258, topology validation
:96, pod-head resource `TPU-{type}-head` :345) and the AcceleratorManager ABC
(accelerator.py). Rebuilt for a zero-egress environment: detection prefers
explicit env/config over GCE metadata (which is gated), then live JAX devices.

Key semantics ported:
- one worker process owns a host's chip set; `TPU_VISIBLE_CHIPS` restricts it;
- TPU resources are named by accelerator version ("TPU-v5e" etc.);
- the FIRST host of a slice additionally exposes `TPU-{pod_type}-head: 1`, the
  hook the slice scheduler gangs on.
"""

from __future__ import annotations

import logging
import os
import re
from dataclasses import dataclass
from typing import Dict, List, Optional

from ray_tpu._private.config import GLOBAL_CONFIG

logger = logging.getLogger(__name__)

TPU_VISIBLE_CHIPS_ENV = "TPU_VISIBLE_CHIPS"
TPU_CHIPS_PER_HOST_BOUNDS_ENV = "TPU_CHIPS_PER_HOST_BOUNDS"
TPU_ACCELERATOR_TYPE_ENV = "TPU_ACCELERATOR_TYPE"  # e.g. "v5litepod-16"
TPU_WORKER_ID_ENV = "TPU_WORKER_ID"
TPU_NAME_ENV = "TPU_NAME"

# accelerator generation -> chips per host (reference: tpu.py host-shape logic)
_CHIPS_PER_HOST = {"v2": 4, "v3": 4, "v4": 4, "v5p": 4, "v5e": 8, "v5litepod": 8,
                   "v6e": 8}


@dataclass
class TpuInfo:
    generation: str            # "v5e", "v4", ...
    pod_type: str              # "v5e-16" style (accelerator_type normalized)
    topology: str              # "4x4" style when known
    chips_on_host: int
    hosts_in_slice: int
    worker_id: int             # this host's index within the slice
    slice_name: str

    @property
    def resource_name(self) -> str:
        return f"TPU-{self.generation}"

    @property
    def head_resource_name(self) -> str:
        return f"TPU-{self.pod_type}-head"


def _normalize_generation(accel_type: str) -> str:
    gen = accel_type.split("-")[0].lower()
    return {"v5litepod": "v5e", "v5lite": "v5e"}.get(gen, gen)


class TpuAcceleratorManager:
    """Detection + env handling for the node daemon and worker pool."""

    @staticmethod
    def detect(allow_jax_probe: bool = True) -> Optional[TpuInfo]:
        """Detect TPU presence. `allow_jax_probe=False` for the node daemon:
        importing jax initializes libtpu and would CLAIM the host's chips —
        only worker processes may do that (reference: one process per chip
        set, tpu.py:42-55 / SURVEY §7 hard part 2)."""
        accel_type = (
            os.environ.get(TPU_ACCELERATOR_TYPE_ENV)
            or os.environ.get("PALLAS_AXON_TPU_GEN")  # this image's env
        )
        chips = GLOBAL_CONFIG.get("tpu_chips_per_host")
        topology = GLOBAL_CONFIG.get("tpu_topology") or os.environ.get(
            "TPU_TOPOLOGY", ""
        )
        if accel_type is None and not chips and not allow_jax_probe:
            return None
        if accel_type is None and not chips:
            # live-JAX fallback: count local TPU devices if a backend is up
            try:
                import jax

                devs = [d for d in jax.local_devices() if d.platform == "tpu"]
                if not devs:
                    return None
                kind = devs[0].device_kind.lower()  # e.g. "tpu v5 lite"
                gen = "v5e" if "v5 lite" in kind else (
                    "v6e" if "v6 lite" in kind else
                    re.sub(r"[^v0-9p]", "", kind.replace("tpu", "")) or "v4"
                )
                accel_type = f"{gen}-{len(devs)}"
                chips = chips or len(devs)
            except Exception:  # noqa: BLE001
                return None
        if accel_type is None:
            return None
        gen = _normalize_generation(accel_type)
        num_chips_total = 0
        m = re.match(r".*-(\d+)$", accel_type)
        if m:
            num_chips_total = int(m.group(1))
            # for v2/v3/v4/v5p the accelerator-type suffix counts TensorCores
            # (2 per chip), not chips (reference: tpu.py get_tpu_cores_per_chip
            # semantics, :155-188); v5e/v6e suffixes already count chips
            if gen in ("v2", "v3", "v4", "v5p"):
                num_chips_total = max(1, num_chips_total // 2)
        chips_on_host = chips or min(
            _CHIPS_PER_HOST.get(gen, 4), num_chips_total or 4
        )
        hosts = max(1, (num_chips_total or chips_on_host) // chips_on_host)
        pod_type = f"{gen}-{num_chips_total or chips_on_host}"
        return TpuInfo(
            generation=gen,
            pod_type=pod_type,
            topology=topology,
            chips_on_host=chips_on_host,
            hosts_in_slice=hosts,
            worker_id=int(os.environ.get(TPU_WORKER_ID_ENV, "0")),
            slice_name=os.environ.get(TPU_NAME_ENV, pod_type),
        )

    @staticmethod
    def node_resources_and_labels(info: Optional[TpuInfo] = None):
        """Resources + labels the node daemon should advertise."""
        info = info or TpuAcceleratorManager.detect()
        if info is None:
            return {}, {}
        resources: Dict[str, float] = {
            "TPU": float(info.chips_on_host),
            info.resource_name: float(info.chips_on_host),
        }
        if info.worker_id == 0:
            resources[info.head_resource_name] = 1.0
        labels = {
            "tpu-generation": info.generation,
            "tpu-pod-type": info.pod_type,
            "tpu-slice-name": info.slice_name,
            "tpu-worker-id": str(info.worker_id),
        }
        if info.topology:
            labels["tpu-topology"] = info.topology
        return resources, labels

    @staticmethod
    def set_visible_chips_env(env: Dict[str, str], chip_ids: List[int],
                              chips_per_host: int) -> None:
        """Restrict a worker process to specific chips (reference: tpu.py:42-55).

        With all chips granted, the env vars are left unset so libtpu owns the
        full host (the fast path — one long-lived gang worker per host).
        """
        if len(chip_ids) >= chips_per_host:
            return
        env[TPU_VISIBLE_CHIPS_ENV] = ",".join(str(c) for c in chip_ids)
        if len(chip_ids) == 1:
            env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,1,1"
        elif len(chip_ids) == 2:
            env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "1,2,1"
        elif len(chip_ids) == 4:
            env[TPU_CHIPS_PER_HOST_BOUNDS_ENV] = "2,2,1"
