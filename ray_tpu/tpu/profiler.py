"""JAX/TPU profiler capture across the cluster.

Reference surface: python/ray/util/tpu.py:1060 init_jax_profiler (starts
the profiler server inside workers) and the dashboard's JAX capture
endpoint (dashboard/modules/reporter/jax_profile_manager.py:11). Here
capture is a plain remote task pinned to the target node, writing an
XPlane/perfetto trace directory the driver can fetch or inspect.
"""

from __future__ import annotations

import os
import time
from typing import List, Optional

import ray_tpu


def init_jax_profiler(port: int = 9999) -> int:
    """Start the in-process profiler server (attachable from TensorBoard /
    xprof; reference: util/tpu.py init_jax_profiler)."""
    import jax

    jax.profiler.start_server(port)
    return port


def capture_local(logdir: str, duration_s: float = 2.0,
                  workload=None) -> str:
    """Trace this process's JAX activity for duration_s (or around
    `workload()` if given); returns the trace dir."""
    import jax

    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        if workload is not None:
            workload()
        else:
            time.sleep(duration_s)
    finally:
        jax.profiler.stop_trace()
    return logdir


@ray_tpu.remote
def _capture_task(logdir: Optional[str], duration_s: float):
    """Runs on the target node's worker: captures its JAX runtime trace.
    logdir=None creates a temp dir ON THE TARGET (a dashboard-side path
    would be meaningless on another node). Returns (logdir, files)."""
    if logdir is None:
        import tempfile

        logdir = tempfile.mkdtemp(prefix="rt_jaxprof_")
    capture_local(logdir, duration_s)
    out = []
    for root, _dirs, files in os.walk(logdir):
        out.extend(os.path.join(root, f) for f in files)
    return logdir, out


def node_capture_task(node_id_hex: str):
    """The capture task pinned to `node_id_hex` (shared by capture_on_node
    and the dashboard's /api/jax_profile)."""
    from ray_tpu._private.protocol import SchedulingStrategy

    return _capture_task.options(
        scheduling_strategy=SchedulingStrategy(
            kind="NODE_AFFINITY", node_id=node_id_hex, soft=False),
    )


def capture_on_node(node_id_hex: str, logdir: Optional[str] = None,
                    duration_s: float = 2.0) -> List[str]:
    """Capture a JAX profile on a specific node (reference: the dashboard
    agent's per-node capture). Returns trace file paths on that node."""
    _dir, files = ray_tpu.get(
        node_capture_task(node_id_hex).remote(logdir, duration_s),
        timeout=duration_s + 120)
    return files


__all__ = ["capture_local", "capture_on_node", "init_jax_profiler", "node_capture_task"]
