"""TPU slice orchestration: whole-slice reservation + per-host dispatch.

Capability parity with the reference's `ray.util.tpu` (reference:
python/ray/util/tpu.py — SlicePlacementGroup :421 reserves whole slices via
the `TPU-{pod_type}-head` resource + label selector, slice_placement_group
:803, dispatch :849 runs a fn on every host of the slices,
get_tpu_coordinator_env_vars :213 builds the MEGASCALE cross-slice env).

A slice reservation works in two stages, like the reference:
1. grab one `TPU-{pod_type}-head: 1` per slice — the head resource exists on
   exactly one host per slice, so owning it owns the slice;
2. resolve each claimed head's `tpu-slice-name` node label and gang that
   slice's per-host TPU bundles with a `bundle_label_selector` pinning them to
   the slice's own hosts (STRICT_SPREAD within the slice). On clusters whose
   nodes don't carry slice labels (single-host dev boxes), stage 2 falls back
   to an unpinned gang.

`dispatch` injects the MEGASCALE_* env into every host's task for multi-slice
reservations (coordinator = slice 0's head host).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

from ray_tpu._private.protocol import TPU_COORD_LABEL
from ray_tpu.util.placement_group import PlacementGroup, placement_group

logger = logging.getLogger(__name__)

# MEGASCALE env keys for cross-slice DCN coordination (reference:
# python/ray/train/v2/jax/config.py:29-35, util/tpu.py:213)
MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"
MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
MEGASCALE_PORT = "MEGASCALE_PORT"


def get_tpu_coordinator_env_vars(
    coordinator_address: str, num_slices: int, slice_id: int,
    port: int = 8081,
) -> Dict[str, str]:
    """Env to inject into every worker of a multi-slice job."""
    if num_slices <= 1:
        return {}
    return {
        MEGASCALE_COORDINATOR: coordinator_address,
        MEGASCALE_NUM_SLICES: str(num_slices),
        MEGASCALE_SLICE_ID: str(slice_id),
        MEGASCALE_PORT: str(port),
    }


@dataclass
class SlicePlacementGroup:
    """Reservation of one or more whole TPU slices."""

    pod_type: str                   # e.g. "v5e-16"
    num_slices: int = 1
    chips_per_host: int = 8
    hosts_per_slice: int = 1
    megascale_port: int = 8081
    _head_pg: Optional[PlacementGroup] = None
    _slice_pgs: List[PlacementGroup] = field(default_factory=list)
    _slice_names: List[Optional[str]] = field(default_factory=list)
    _coordinator: str = ""

    def reserve(self) -> "SlicePlacementGroup":
        head_resource = f"TPU-{self.pod_type}-head"
        self._head_pg = placement_group(
            [{head_resource: 1.0} for _ in range(self.num_slices)],
            strategy="STRICT_SPREAD" if self.num_slices > 1 else "PACK",
            name=f"slice-head:{self.pod_type}",
        )
        return self

    def ready(self, timeout: float = 120.0) -> bool:
        if self._head_pg is None or not self._head_pg.ready(timeout):
            return False
        if not self._slice_pgs:
            self._create_slice_pgs()
        return all(pg.ready(timeout) for pg in self._slice_pgs)

    def _create_slice_pgs(self):
        """Stage 2: pin per-host gangs to the claimed slices via node labels."""
        import ray_tpu

        placements = self._head_pg.bundle_placements()
        node_info = {n["node_id"]: n for n in ray_tpu.nodes()}
        bundles = [
            {"TPU": float(self.chips_per_host)}
            for _ in range(self.hosts_per_slice)
        ]
        for slice_idx in range(self.num_slices):
            head_node = node_info.get(placements.get(slice_idx, ""), {})
            labels = head_node.get("labels", {})
            slice_name = labels.get("tpu-slice-name")
            self._slice_names.append(slice_name)
            if slice_idx == 0 and head_node:
                host = head_node.get("address", "").rsplit(":", 1)[0]
                self._coordinator = f"{host}:{self.megascale_port}"
            selector = {"tpu-slice-name": slice_name} if slice_name else None
            # multi-host gangs use ICI-topology-aware placement when ENOUGH
            # in-scope hosts advertise coordinates (rt.tpu.coord) to place
            # every bundle — a partial label rollout must fall back to
            # STRICT_SPREAD, not time out on an unplaceable topology PG
            # (reference: topology_bundle_scheduling_policy.h:89)
            labeled_in_scope = sum(
                1 for n in node_info.values()
                if TPU_COORD_LABEL in n.get("labels", {})
                and (not slice_name
                     or n.get("labels", {}).get("tpu-slice-name") == slice_name)
            )
            if self.hosts_per_slice <= 1:
                strategy = "PACK"
            elif labeled_in_scope >= self.hosts_per_slice:
                strategy = "TOPOLOGY_STRICT_PACK"
            else:
                strategy = "STRICT_SPREAD"
            self._slice_pgs.append(placement_group(
                bundles,
                strategy=strategy,
                name=f"slice:{self.pod_type}:{slice_idx}",
                bundle_label_selector=selector,
            ))

    @property
    def placement_group(self) -> PlacementGroup:
        """The slice-0 gang PG (after ready())."""
        return self._slice_pgs[0] if self._slice_pgs else self._head_pg

    def remove(self):
        from ray_tpu.util.placement_group import remove_placement_group

        for pg in [*self._slice_pgs, self._head_pg]:
            if pg is not None:
                remove_placement_group(pg)

    def dispatch(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run `fn` once per host of every slice (reference: tpu.py:849).

        Returns one ObjectRef per host, slice-major. For multi-slice
        reservations the MEGASCALE_* cross-slice env rides each task's
        runtime_env (coordinator = slice 0's head host).
        """
        import ray_tpu

        if not self.ready():
            raise RuntimeError("slice placement group is not ready")
        remote_fn = ray_tpu.remote(fn) if not hasattr(fn, "remote") else fn
        refs = []
        for slice_idx, pg in enumerate(self._slice_pgs):
            env = get_tpu_coordinator_env_vars(
                self._coordinator, self.num_slices, slice_idx,
                self.megascale_port,
            )
            for host_index in range(self.hosts_per_slice):
                refs.append(
                    remote_fn.options(
                        num_cpus=0,  # the bundle reserves TPU, not CPU
                        resources={"TPU": float(self.chips_per_host)},
                        placement_group=pg,
                        placement_group_bundle_index=host_index,
                        runtime_env={"env_vars": env} if env else None,
                    ).remote(*args, **kwargs)
                )
        return refs


def slice_placement_group(pod_type: str, num_slices: int = 1,
                          chips_per_host: int = 8,
                          hosts_per_slice: int = 1) -> SlicePlacementGroup:
    """Reserve `num_slices` whole slices of `pod_type` (reference: tpu.py:803)."""
    return SlicePlacementGroup(
        pod_type=pod_type,
        num_slices=num_slices,
        chips_per_host=chips_per_host,
        hosts_per_slice=hosts_per_slice,
    ).reserve()
