"""JobManager: the durable, multi-tenant job-plane brain.

Reference: dashboard/modules/job/job_manager.py:57 — but where the
reference keeps job records in the GCS KV, this manager keeps the whole
job table in the control store's persisted `submitted_jobs` table
(WAL-backed, surviving HA failover), and layers two things the stub
never had:

  * per-tenant quotas — caps on concurrently admitted jobs / resources
    per tenant key, so one tenant's burst can't occupy the cluster;
  * weighted fair-share admission — stride scheduling over tenants:
    each admission charges the tenant virtual time = job cost / weight,
    and the queued job of the lowest-vtime admissible tenant goes next,
    so completed-work share converges to the weight ratio under
    contention no matter how lopsided the submission rates are.

The manager actor itself holds only soft state (supervisor handles, the
admission queue, working-dir payloads): on restart it rebuilds from the
store table — QUEUED jobs re-enqueue, RUNNING jobs re-adopt their
supervisor actors by name, jobs whose supervisor is gone fail (or requeue
under their max_retries budget).
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import ray_tpu
from ray_tpu._private import flight_recorder
from ray_tpu._private import config as _config
from ray_tpu.job_submission._supervisor import (
    FAILED,
    PENDING,
    QUEUED,
    RUNNING,
    STOPPED,
    SUCCEEDED,
    TERMINAL,
    JobSupervisor,
)

logger = logging.getLogger(__name__)

JOB_MANAGER_NAME = "job-manager"
JOBS_NAMESPACE = "_jobs"
_TENANTS_KV_NS = "_job_plane"
_TENANTS_KV_KEY = b"tenants"
_FINAL_LOG_TAIL = 256 * 1024


def job_cost(resources: Dict[str, float]) -> float:
    """Scalar service cost charged to a tenant per admission: the sum of
    requested resource quantities (floor 1 so zero-resource jobs still
    consume schedule share)."""
    return max(1.0, float(sum(resources.values()))) if resources else 1.0


class FairShareQueue:
    """Weighted fair-share admission order over tenant keys (stride
    scheduling: virtual time advances by cost/weight per admission —
    reference: the classic WFQ virtual-clock formulation).

    Pure and synchronous so the convergence property is unit-testable
    without a cluster; the JobManager and the bench fleet driver both
    run this exact code.
    """

    def __init__(self, weight_of: Callable[[str], float]):
        self._weight_of = weight_of
        self._queues: Dict[str, collections.deque] = {}
        self._vtime: Dict[str, float] = {}

    def push(self, tenant: str, item, cost: float) -> None:
        q = self._queues.setdefault(tenant, collections.deque())
        if not q:
            # a tenant returning from idle starts at the active floor —
            # idle time must not bank credit that would let it monopolize
            # admissions until its stale vtime catches up
            active = [t for t, qq in self._queues.items() if qq and t != tenant]
            floor = min((self._vtime.get(t, 0.0) for t in active), default=0.0)
            self._vtime[tenant] = max(self._vtime.get(tenant, 0.0), floor)
        q.append((item, cost))

    def remove(self, tenant: str, item) -> bool:
        q = self._queues.get(tenant)
        if not q:
            return False
        for pair in q:
            if pair[0] == item:
                q.remove(pair)
                return True
        return False

    def backlog(self, tenant: Optional[str] = None) -> int:
        if tenant is not None:
            return len(self._queues.get(tenant, ()))
        return sum(len(q) for q in self._queues.values())

    def vtime(self, tenant: str) -> float:
        return self._vtime.get(tenant, 0.0)

    def pop(self, can_admit: Callable[[str, object], bool]
            ) -> Optional[Tuple[str, object]]:
        """Next (tenant, item) in fair-share order among tenants whose
        HEAD job passes can_admit (quota headroom); None if nothing is
        admissible. Charges the admitted tenant's virtual time."""
        order = sorted(
            (t for t, q in self._queues.items() if q),
            key=lambda t: (self._vtime.get(t, 0.0), t))
        for t in order:
            item, cost = self._queues[t][0]
            if not can_admit(t, item):
                continue
            self._queues[t].popleft()
            self._vtime[t] = (self._vtime.get(t, 0.0)
                              + cost / max(self._weight_of(t), 1e-9))
            return t, item
        return None


@ray_tpu.remote
class JobManager:
    """Tracks all submitted jobs; admits by fair share under quotas."""

    def __init__(self):
        self._lock = threading.RLock()
        # tenant -> {"weight", "max_running", "max_resources"|None}
        self._tenants: Dict[str, dict] = {}
        self._queue = FairShareQueue(self._weight_of)
        self._jobs: Dict[str, dict] = {}            # mirror of store records
        self._supervisors: Dict[str, object] = {}   # sid -> actor handle
        self._zips: Dict[str, Optional[bytes]] = {}
        self._final_logs: Dict[str, str] = {}
        self._poll_strikes: Dict[str, int] = {}
        # quota + fair-share accounting (tenant-keyed)
        self._running: Dict[str, set] = {}
        self._running_res: Dict[str, Dict[str, float]] = {}
        self._completed_cost: Dict[str, float] = {}
        self._load_tenants()
        self._recover()
        self._stop_evt = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="job-manager-tick", daemon=True)
        self._thread.start()

    # -- control-store access ------------------------------------------

    @staticmethod
    def _store(method: str, payload: dict, timeout: float = 15.0) -> dict:
        from ray_tpu._private.core_worker import get_core_worker

        cw = get_core_worker()
        return cw.run_sync(cw.control.call(method, payload), timeout)

    def _write_job(self, rec: dict):
        """Merge-write into the durable table: job_update keeps fields the
        supervisor stamped store-side (start_time, driver_pid) that this
        mirror may not have seen; job_put only for brand-new records. A
        terminal-guard rejection means a racing writer (the supervisor, an
        old manager) finalized first — the read-back adopts its version."""
        sid = rec["submission_id"]
        reply = self._store("job_update",
                            {"submission_id": sid, "fields": dict(rec)})
        if not reply.get("ok") and not reply.get("terminal"):
            self._store("job_put", {"job": dict(rec)})
        stored = self._store("job_get", {"submission_id": sid}).get("job")
        if stored:
            rec.update(stored)
            self._jobs[sid] = rec

    # -- tenants --------------------------------------------------------

    def _weight_of(self, tenant: str) -> float:
        cfg = self._tenants.get(tenant)
        if cfg and cfg.get("weight") is not None:
            return float(cfg["weight"])
        return float(_config.GLOBAL_CONFIG.get("job_tenant_weight"))

    def _tenant_cfg(self, tenant: str) -> dict:
        cfg = dict(self._tenants.get(tenant, {}))
        cfg.setdefault("weight", _config.GLOBAL_CONFIG.get("job_tenant_weight"))
        cfg.setdefault("max_running",
                       _config.GLOBAL_CONFIG.get("job_tenant_max_running"))
        cfg.setdefault("max_resources", None)
        return cfg

    def set_tenant(self, tenant: str, weight: Optional[float] = None,
                   max_running: Optional[int] = None,
                   max_resources: Optional[Dict[str, float]] = None) -> dict:
        """Configure one tenant's quota/weight; persisted in the control
        store KV so it survives manager restarts AND store failovers."""
        with self._lock:
            cfg = self._tenants.setdefault(tenant, {})
            if weight is not None:
                cfg["weight"] = float(weight)
            if max_running is not None:
                cfg["max_running"] = int(max_running)
            if max_resources is not None:
                cfg["max_resources"] = dict(max_resources)
            try:
                self._store("kv_put", {
                    "ns": _TENANTS_KV_NS, "key": _TENANTS_KV_KEY,
                    "value": json.dumps(self._tenants).encode(),
                })
            except Exception:  # noqa: BLE001 — config survives in-memory
                logger.exception("persisting tenant config failed")
            return self._tenant_cfg(tenant)

    def _load_tenants(self):
        try:
            reply = self._store("kv_get", {"ns": _TENANTS_KV_NS,
                                           "key": _TENANTS_KV_KEY})
            if reply.get("value"):
                self._tenants = json.loads(bytes(reply["value"]).decode())
        except Exception:  # noqa: BLE001 — defaults apply
            logger.exception("loading tenant config failed")

    # -- recovery -------------------------------------------------------

    def _recover(self):
        """Rebuild soft state from the durable table (manager restart /
        adoption after a control-store failover)."""
        offset, records = 0, []
        while True:
            reply = self._store("job_list", {"offset": offset, "limit": 1000})
            records.extend(reply.get("jobs", []))
            offset += len(reply.get("jobs", []))
            if offset >= reply.get("total", 0) or not reply.get("jobs"):
                break
        for rec in sorted(records, key=lambda r: r.get("submit_time") or 0.0):
            sid = rec["submission_id"]
            self._jobs[sid] = rec
            status = rec.get("status")
            if status in TERMINAL:
                continue
            if status == QUEUED:
                self._queue.push(rec.get("tenant", ""), sid,
                                 job_cost(rec.get("resources") or {}))
                continue
            # PENDING/RUNNING: re-adopt the supervisor if it still exists
            try:
                handle = ray_tpu.get_actor(f"job-supervisor:{sid}",
                                           namespace=JOBS_NAMESPACE)
                self._supervisors[sid] = handle
                self._charge(rec)
            except ValueError:
                self._on_supervisor_death(
                    rec, "supervisor lost across manager restart")
        flight_recorder.record("job", "manager_recovered",
                               jobs=len(records),
                               queued=self._queue.backlog())

    # -- quota accounting ----------------------------------------------

    def _charge(self, rec: dict):
        tenant = rec.get("tenant", "")
        self._running.setdefault(tenant, set()).add(rec["submission_id"])
        tot = self._running_res.setdefault(tenant, {})
        for k, v in (rec.get("resources") or {}).items():
            tot[k] = tot.get(k, 0.0) + float(v)

    def _release(self, rec: dict):
        tenant = rec.get("tenant", "")
        if rec["submission_id"] not in self._running.get(tenant, ()):
            return
        self._running[tenant].discard(rec["submission_id"])
        tot = self._running_res.get(tenant, {})
        for k, v in (rec.get("resources") or {}).items():
            tot[k] = tot.get(k, 0.0) - float(v)
            if tot[k] <= 1e-9:
                tot.pop(k, None)

    def _can_admit(self, tenant: str, sid: str) -> bool:
        cfg = self._tenant_cfg(tenant)
        if len(self._running.get(tenant, ())) >= int(cfg["max_running"]):
            return False
        cap = cfg.get("max_resources")
        if cap:
            rec = self._jobs.get(sid, {})
            tot = self._running_res.get(tenant, {})
            for k, limit in cap.items():
                want = tot.get(k, 0.0) + float(
                    (rec.get("resources") or {}).get(k, 0.0))
                if want > float(limit) + 1e-9:
                    return False
        return True

    # -- submission surface --------------------------------------------

    def submit(self, rec: dict, working_dir_zip: Optional[bytes]) -> str:
        sid = rec["submission_id"]
        with self._lock:
            if sid in self._jobs:
                raise ValueError(f"job {sid!r} already exists")
            existing = self._store("job_get", {"submission_id": sid})
            if existing.get("job") is not None:
                raise ValueError(f"job {sid!r} already exists")
            rec.setdefault("tenant", _config.GLOBAL_CONFIG.get("job_default_tenant"))
            rec.setdefault("resources", {"CPU": 1.0})
            rec.setdefault("max_retries", 0)
            rec.setdefault("retries_used", 0)
            rec["status"] = QUEUED
            rec["message"] = "waiting for admission"
            rec["submit_time"] = time.time()
            self._jobs[sid] = rec
            self._zips[sid] = working_dir_zip
            self._write_job(rec)
            self._queue.push(rec["tenant"], sid,
                             job_cost(rec["resources"]))
            self._admit_locked()
        return sid

    def _admit_locked(self):
        """Admit queued jobs in fair-share order while quotas allow."""
        while True:
            picked = self._queue.pop(self._can_admit)
            if picked is None:
                return
            tenant, sid = picked
            rec = self._jobs[sid]
            try:
                res = dict(rec.get("resources") or {})
                opts = {"name": f"job-supervisor:{sid}",
                        "namespace": JOBS_NAMESPACE, "lifetime": "detached"}
                if "CPU" in res:
                    opts["num_cpus"] = res.pop("CPU")
                if "TPU" in res:
                    opts["num_tpus"] = res.pop("TPU")
                if res:
                    opts["resources"] = res
                # a job's singleton supervisor prefers non-spot capacity —
                # losing it mid-job burns one of the job's retries for no
                # user fault (the job's own tasks still go wherever the
                # scheduler puts them; all-spot clusters fall back)
                from ray_tpu._private.spot import anti_spot_placement

                opts.update(anti_spot_placement(f"job supervisor {sid}"))
                handle = JobSupervisor.options(**opts).remote(
                    sid, rec["entrypoint"], dict(rec.get("env_vars") or {}),
                    self._zips.get(sid))
            except Exception as e:  # noqa: BLE001 — spawn failed outright
                if "already taken" in str(e):
                    # a requeued job racing its previous attempt's reap:
                    # the detached name frees once the dead supervisor is
                    # marked ACTOR_DEAD — retry on the next tick
                    self._queue.push(tenant, sid,
                                     job_cost(rec.get("resources") or {}))
                    return
                rec.update(status=FAILED,
                           message=f"supervisor spawn failed: {e}",
                           end_time=time.time())
                self._write_job(rec)
                continue
            self._supervisors[sid] = handle
            self._poll_strikes.pop(sid, None)
            self._charge(rec)
            rec.update(status=PENDING, message="supervisor starting")
            self._write_job(rec)

    # -- the reconcile tick --------------------------------------------

    def _loop(self):
        period = _config.GLOBAL_CONFIG.get("job_poll_period_s")
        while not self._stop_evt.wait(period):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — keep reconciling
                logger.exception("job manager tick failed")

    def _tick(self):
        with self._lock:
            active = {sid: h for sid, h in self._supervisors.items()
                      if self._jobs.get(sid, {}).get("status")
                      in (PENDING, RUNNING)}
        if active:
            refs = {sid: h.poll.remote() for sid, h in active.items()}
            ray_tpu.wait(list(refs.values()), num_returns=len(refs),
                         timeout=_config.GLOBAL_CONFIG.get(
                             "job_supervisor_poll_timeout_s"))
            for sid, ref in refs.items():
                try:
                    st = ray_tpu.get(ref, timeout=0.5)
                except ray_tpu.GetTimeoutError:
                    self._on_poll_timeout(sid)
                    continue
                except Exception as e:  # noqa: BLE001 — supervisor died
                    with self._lock:
                        rec = self._jobs.get(sid)
                        if rec is not None:
                            self._on_supervisor_death(
                                rec, f"supervisor died: {e}")
                    continue
                self._on_poll(sid, st)
        with self._lock:
            self._admit_locked()

    def _on_poll_timeout(self, sid: str):
        """A hung poll (node dying, store mid-failover): three strikes
        inside the poll budget before declaring the supervisor dead."""
        with self._lock:
            strikes = self._poll_strikes.get(sid, 0) + 1
            self._poll_strikes[sid] = strikes
            if strikes < 3:
                return
            rec = self._jobs.get(sid)
            if rec is not None:
                self._on_supervisor_death(
                    rec, "supervisor unresponsive (poll timeout)")

    def _on_poll(self, sid: str, st: dict):
        with self._lock:
            self._poll_strikes.pop(sid, None)
            rec = self._jobs.get(sid)
            if rec is None or rec.get("status") in TERMINAL:
                return
            if st["status"] == RUNNING:
                if rec.get("status") == PENDING:
                    # normally the supervisor stamped RUNNING (and
                    # start_time) itself; mirror what the poll proved and
                    # only backfill start_time if the stamp never landed
                    rec.update(status=RUNNING, message="")
                    self._write_job(rec)
                    if "start_time" not in rec:
                        rec["start_time"] = time.time()
                        self._write_job(rec)
                return
            if st["status"] in TERMINAL:
                self._finalize(rec, st["status"], st.get("message", ""))

    def _finalize(self, rec: dict, status: str, message: str):
        """Terminal transition: final log capture, table write, quota
        release, completed-work accounting, supervisor teardown."""
        sid = rec["submission_id"]
        handle = self._supervisors.pop(sid, None)
        if handle is not None and sid not in self._final_logs:
            try:
                logs = ray_tpu.get(handle.logs.remote(), timeout=10)
                self._final_logs[sid] = logs[-_FINAL_LOG_TAIL:]
            except Exception:  # noqa: BLE001 — logs are best-effort
                pass
        rec.update(status=status, message=message, end_time=time.time())
        self._write_job(rec)
        self._release(rec)
        tenant = rec.get("tenant", "")
        if status in (SUCCEEDED, STOPPED) or rec.get("start_time"):
            # work was performed: charge the tenant's completed share
            self._completed_cost[tenant] = (
                self._completed_cost.get(tenant, 0.0)
                + job_cost(rec.get("resources") or {}))
        if handle is not None:
            try:
                # detached supervisors outlive every driver: reap them or
                # each finished job leaks an idle actor + its resources
                ray_tpu.kill(handle)
            except Exception:  # noqa: BLE001
                pass
        self._zips.pop(sid, None)
        flight_recorder.record("job", "finalized", sid=sid, status=status)

    def _on_supervisor_death(self, rec: dict, cause: str):
        """Supervisor gone mid-flight: release quota, then retry under
        the job's max_retries budget or fail with the surfaced cause."""
        sid = rec["submission_id"]
        self._supervisors.pop(sid, None)
        self._poll_strikes.pop(sid, None)
        self._release(rec)
        retries_used = int(rec.get("retries_used", 0))
        if retries_used < int(rec.get("max_retries", 0)):
            rec.update(status=QUEUED, retries_used=retries_used + 1,
                       message=f"requeued (attempt {retries_used + 2}): "
                               f"{cause}")
            rec.pop("start_time", None)
            self._write_job(rec)
            self._queue.push(rec.get("tenant", ""), sid,
                             job_cost(rec.get("resources") or {}))
            flight_recorder.record("job", "requeued", sid=sid, cause=cause)
        else:
            self._finalize(rec, FAILED, cause)

    # -- query/control surface -----------------------------------------

    def status(self, submission_id: str) -> dict:
        with self._lock:
            rec = self._jobs.get(submission_id)
        if rec is None:
            reply = self._store("job_get", {"submission_id": submission_id})
            rec = reply.get("job")
            if rec is None:
                raise ValueError(f"no job {submission_id!r}")
        return {"status": rec.get("status"),
                "message": rec.get("message", ""), **rec}

    def logs(self, submission_id: str, offset: int = 0) -> str:
        with self._lock:
            handle = self._supervisors.get(submission_id)
            final = self._final_logs.get(submission_id)
        if handle is not None:
            try:
                return ray_tpu.get(handle.logs.remote(offset), timeout=30)
            except Exception:  # noqa: BLE001 — fall through to the capture
                pass
        if final is not None:
            return final[offset:]
        return ""

    def stop(self, submission_id: str) -> bool:
        with self._lock:
            rec = self._jobs.get(submission_id)
            if rec is None:
                raise ValueError(f"no job {submission_id!r}")
            if rec.get("status") in (SUCCEEDED, FAILED):
                return False  # terminal states never transition
            if rec.get("status") == QUEUED:
                self._queue.remove(rec.get("tenant", ""), submission_id)
                rec.update(status=STOPPED, message="stopped by user",
                           end_time=time.time())
                self._write_job(rec)
                return True
            handle = self._supervisors.get(submission_id)
        if handle is not None:
            try:
                ray_tpu.get(handle.stop.remote(), timeout=30)
            except Exception:  # noqa: BLE001 — dying anyway
                pass
        with self._lock:
            rec = self._jobs.get(submission_id)
            if rec is not None and rec.get("status") not in TERMINAL:
                self._finalize(rec, STOPPED, "stopped by user")
        return True

    def list(self, offset: int = 0, limit: int = 100,
             tenant: Optional[str] = None) -> List[dict]:
        reply = self._store("job_list", {
            "offset": offset, "limit": limit,
            **({"tenant": tenant} if tenant is not None else {}),
        })
        return reply.get("jobs", [])

    def fair_share_stats(self) -> dict:
        """Per-tenant accounting for the fairness proof: completed work,
        running/queued depth, configured weight, virtual time."""
        with self._lock:
            tenants = (set(self._tenants) | set(self._running)
                       | set(self._completed_cost)
                       | {r.get("tenant", "") for r in self._jobs.values()})
            return {
                t: {
                    "weight": self._weight_of(t),
                    "completed_cost": self._completed_cost.get(t, 0.0),
                    "running": len(self._running.get(t, ())),
                    "queued": self._queue.backlog(t),
                    "vtime": self._queue.vtime(t),
                }
                for t in tenants if t
            }
