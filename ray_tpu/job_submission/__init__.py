"""Job submission: run driver scripts on the cluster with status/log
tracking, multi-tenant quotas, and weighted fair-share admission.

Reference surface: python/ray/dashboard/modules/job/ — JobSubmissionClient
(sdk.py), JobManager (job_manager.py:57), JobSupervisor (job_supervisor.py:57
— one supervisor actor per job runs the entrypoint as a child process and
fate-shares), JobStatus lifecycle. Submission travels over the actor plane
instead of REST; the CLI (`ray_tpu.scripts job ...`) wraps this client the
way `ray job submit` wraps the REST SDK.

Durability: the job table lives in the control store's persisted
`submitted_jobs` table (WAL/snapshot, replayed by the HA standby), so
status reads go straight to the store and records survive both manager
restarts and a control-store kill+takeover. The manager actor holds only
soft state (supervisor handles, the admission queue) and rebuilds it from
the table on restart.
"""

from __future__ import annotations

import io
import os
import time
import uuid
import zipfile
from typing import Dict, List, Optional

import ray_tpu
from ray_tpu.job_submission._manager import (
    JOB_MANAGER_NAME,
    JOBS_NAMESPACE,
    FairShareQueue,
    JobManager,
    job_cost,
)
from ray_tpu.job_submission._supervisor import (
    FAILED,
    PENDING,
    QUEUED,
    RUNNING,
    STOPPED,
    SUCCEEDED,
    TERMINAL,
    JobSupervisor,
)


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, path))
    return buf.getvalue()


def _store_call(method: str, payload: dict, timeout: float = 15.0) -> dict:
    from ray_tpu._private.core_worker import get_core_worker

    cw = get_core_worker()
    return cw.run_sync(cw.control.call(method, payload), timeout)


class JobSubmissionClient:
    """Reference: python/ray/dashboard/modules/job/sdk.py
    JobSubmissionClient — same surface, actor-plane transport. Status and
    listing reads come straight from the durable store table (no manager
    round-trip); logs/stop go through the manager, which owns the
    supervisors."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._manager = self._get_or_create_manager()

    @staticmethod
    def _get_or_create_manager():
        try:
            return ray_tpu.get_actor(JOB_MANAGER_NAME, namespace=JOBS_NAMESPACE)
        except ValueError:
            pass
        try:
            # cluster singleton: prefer non-spot capacity (a reclaim wave
            # must not take the job control point with it; all-spot falls
            # back to unconstrained placement)
            from ray_tpu._private.spot import anti_spot_placement

            return JobManager.options(
                name=JOB_MANAGER_NAME, namespace=JOBS_NAMESPACE,
                lifetime="detached",
                **anti_spot_placement("the JobManager"),
            ).remote()
        except Exception as e:  # noqa: BLE001 — name-collision race only
            if "already taken" not in str(e):
                raise
            # lost a create race with a concurrent client: use the winner's
            return ray_tpu.get_actor(JOB_MANAGER_NAME, namespace=JOBS_NAMESPACE)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None,
                   tenant: Optional[str] = None,
                   resources: Optional[Dict[str, float]] = None,
                   max_retries: int = 0) -> str:
        """Submit an entrypoint. `tenant` keys quota/fair-share accounting;
        `resources` is the job's cluster footprint (drives both admission
        quotas and autoscaler demand); `max_retries` allows resubmission
        after supervisor loss."""
        runtime_env = runtime_env or {}
        sid = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        wd = runtime_env.get("working_dir")
        wd_zip = _zip_dir(wd) if wd else None
        rec = {
            "submission_id": sid,
            "entrypoint": entrypoint,
            "env_vars": dict(runtime_env.get("env_vars", {})),
            "metadata": dict(metadata or {}),
            "max_retries": int(max_retries),
        }
        if tenant is not None:
            rec["tenant"] = tenant
        if resources is not None:
            rec["resources"] = dict(resources)
        return ray_tpu.get(
            self._manager.submit.remote(rec, wd_zip), timeout=120)

    def get_job_info(self, submission_id: str) -> dict:
        reply = _store_call("job_get", {"submission_id": submission_id})
        rec = reply.get("job")
        if rec is None:
            raise ValueError(f"no job {submission_id!r}")
        return rec

    def get_job_status(self, submission_id: str) -> str:
        return self.get_job_info(submission_id)["status"]

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        return ray_tpu.get(
            self._manager.logs.remote(submission_id, offset), timeout=60)

    def stop_job(self, submission_id: str) -> bool:
        return ray_tpu.get(
            self._manager.stop.remote(submission_id), timeout=60)

    def list_jobs(self, offset: int = 0, limit: int = 100,
                  tenant: Optional[str] = None,
                  status: Optional[str] = None) -> List[dict]:
        payload = {"offset": offset, "limit": limit}
        if tenant is not None:
            payload["tenant"] = tenant
        if status is not None:
            payload["status"] = status
        return _store_call("job_list", payload).get("jobs", [])

    def set_tenant(self, tenant: str, weight: Optional[float] = None,
                   max_running: Optional[int] = None,
                   max_resources: Optional[Dict[str, float]] = None) -> dict:
        """Configure a tenant's fair-share weight and quota caps."""
        return ray_tpu.get(
            self._manager.set_tenant.remote(
                tenant, weight, max_running, max_resources),
            timeout=60)

    def fair_share_stats(self) -> dict:
        return ray_tpu.get(
            self._manager.fair_share_stats.remote(), timeout=60)

    def tail_job_logs(self, submission_id: str, poll_s: float = 1.0):
        """Generator of log increments until the job finishes. Each poll
        ships only the unseen suffix (offset-based) — re-fetching the whole
        growing file every tick would be O(n^2) bytes over the wire."""
        seen = 0
        while True:
            chunk = self.get_job_logs(submission_id, offset=seen)
            if chunk:
                yield chunk
                seen += len(chunk.encode("utf-8", "replace"))
            if self.get_job_status(submission_id) in TERMINAL:
                chunk = self.get_job_logs(submission_id, offset=seen)
                if chunk:
                    yield chunk
                return
            time.sleep(poll_s)


__all__ = [
    "FAILED",
    "FairShareQueue",
    "JobManager",
    "JobSubmissionClient",
    "JobSupervisor",
    "PENDING",
    "QUEUED",
    "RUNNING",
    "STOPPED",
    "SUCCEEDED",
    "TERMINAL",
    "job_cost",
]
