"""Job submission: run driver scripts on the cluster with status/log
tracking.

Reference surface: python/ray/dashboard/modules/job/ — JobSubmissionClient
(sdk.py), JobManager (job_manager.py:57), JobSupervisor (job_supervisor.py:57
— one supervisor actor per job runs the entrypoint as a child process and
fate-shares), JobStatus lifecycle. Submission travels over the actor plane
instead of REST; the CLI (`ray_tpu.scripts job ...`) wraps this client the
way `ray job submit` wraps the REST SDK.
"""

from __future__ import annotations

import base64
import io
import os
import time
import uuid
import zipfile
from typing import Any, Dict, List, Optional

import ray_tpu

JOB_MANAGER_NAME = "job-manager"
JOBS_NAMESPACE = "_jobs"

# JobStatus (reference: job/common.py JobStatus)
PENDING = "PENDING"
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"


@ray_tpu.remote
class JobSupervisor:
    """Runs one job's entrypoint as a child process (reference:
    job_supervisor.py:57 — the supervisor actor fate-shares with the job)."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Dict[str, str],
                 working_dir_zip: Optional[bytes] = None):
        import subprocess
        import tempfile

        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self._status = RUNNING
        self._message = ""
        workdir = None
        if working_dir_zip:
            workdir = tempfile.mkdtemp(prefix=f"job_{submission_id}_")
            zipfile.ZipFile(io.BytesIO(working_dir_zip)).extractall(workdir)
        self._log_path = os.path.join(
            tempfile.gettempdir(), f"rt_job_{submission_id}.log")
        env = dict(os.environ)
        env.update(env_vars)
        # the job's driver joins THIS cluster
        env["RT_ADDRESS"] = os.environ.get("RT_CONTROL_ADDR", "")
        log = open(self._log_path, "ab")
        self._proc = subprocess.Popen(
            entrypoint, shell=True, env=env, cwd=workdir,
            stdout=log, stderr=subprocess.STDOUT, start_new_session=True,
        )
        log.close()

    def poll(self) -> dict:
        rc = self._proc.poll()
        if self._status == RUNNING and rc is not None:
            self._status = SUCCEEDED if rc == 0 else FAILED
            self._message = f"exit code {rc}"
        return {"status": self._status, "message": self._message}

    def logs(self, offset: int = 0) -> str:
        try:
            with open(self._log_path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def stop(self) -> bool:
        self.poll()
        if self._status in (SUCCEEDED, FAILED):
            return False  # terminal states never transition (reference: JobStatus)
        if self._proc.poll() is None:
            import signal

            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            deadline = time.time() + 5
            while time.time() < deadline and self._proc.poll() is None:
                time.sleep(0.1)
            if self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        self._status = STOPPED
        return True


@ray_tpu.remote
class JobManager:
    """Tracks all jobs (reference: job_manager.py:57)."""

    def __init__(self):
        self.jobs: Dict[str, dict] = {}

    def submit(self, submission_id: str, entrypoint: str,
               env_vars: Dict[str, str],
               working_dir_zip: Optional[bytes],
               metadata: Dict[str, str]) -> str:
        if submission_id in self.jobs:
            raise ValueError(f"job {submission_id!r} already exists")
        supervisor = JobSupervisor.options(
            name=f"job-supervisor:{submission_id}", namespace=JOBS_NAMESPACE,
            lifetime="detached",
        ).remote(submission_id, entrypoint, env_vars, working_dir_zip)
        self.jobs[submission_id] = {
            "submission_id": submission_id,
            "entrypoint": entrypoint,
            "metadata": metadata,
            "start_time": time.time(),
            "supervisor": supervisor,
            "final": None,
        }
        return submission_id

    def status(self, submission_id: str) -> dict:
        job = self._get(submission_id)
        if job["final"] is not None:
            return job["final"]
        try:
            st = ray_tpu.get(job["supervisor"].poll.remote(), timeout=30)
        except Exception as e:  # noqa: BLE001 — supervisor died = job failed
            st = {"status": FAILED, "message": f"supervisor died: {e}"}
        if st["status"] in (SUCCEEDED, FAILED, STOPPED):
            job["final"] = st
        return st

    def logs(self, submission_id: str, offset: int = 0) -> str:
        job = self._get(submission_id)
        try:
            return ray_tpu.get(
                job["supervisor"].logs.remote(offset), timeout=30)
        except Exception:  # noqa: BLE001
            return ""

    def stop(self, submission_id: str) -> bool:
        job = self._get(submission_id)
        current = self.status(submission_id)
        if current["status"] in (SUCCEEDED, FAILED):
            return False  # terminal states never transition
        try:
            ray_tpu.get(job["supervisor"].stop.remote(), timeout=30)
        except Exception:  # noqa: BLE001
            pass
        job["final"] = {"status": STOPPED, "message": "stopped by user"}
        return True

    def list(self) -> List[dict]:
        # poll every not-yet-final supervisor CONCURRENTLY: one dead
        # supervisor must not serialize 30 s stalls across the listing
        pending = {
            sid: job["supervisor"].poll.remote()
            for sid, job in self.jobs.items() if job["final"] is None
        }
        if pending:
            ray_tpu.wait(list(pending.values()),
                         num_returns=len(pending), timeout=10)
        out = []
        for sid, job in self.jobs.items():
            if job["final"] is not None:
                st = job["final"]
            else:
                try:
                    st = ray_tpu.get(pending[sid], timeout=1)
                except Exception as e:  # noqa: BLE001 — dead/unresponsive
                    st = {"status": FAILED, "message": f"supervisor died: {e}"}
                if st["status"] in (SUCCEEDED, FAILED, STOPPED):
                    job["final"] = st
            out.append({
                "submission_id": sid,
                "entrypoint": job["entrypoint"],
                "status": st["status"],
                "message": st.get("message", ""),
                "start_time": job["start_time"],
                "metadata": job["metadata"],
            })
        return out

    def _get(self, submission_id: str) -> dict:
        job = self.jobs.get(submission_id)
        if job is None:
            raise ValueError(f"no job {submission_id!r}")
        return job


def _zip_dir(path: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
        for root, _dirs, files in os.walk(path):
            for f in files:
                full = os.path.join(root, f)
                zf.write(full, os.path.relpath(full, path))
    return buf.getvalue()


class JobSubmissionClient:
    """Reference: python/ray/dashboard/modules/job/sdk.py
    JobSubmissionClient — same surface, actor-plane transport."""

    def __init__(self, address: Optional[str] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init(address=address)
        self._manager = self._get_or_create_manager()

    @staticmethod
    def _get_or_create_manager():
        try:
            return ray_tpu.get_actor(JOB_MANAGER_NAME, namespace=JOBS_NAMESPACE)
        except ValueError:
            pass
        try:
            return JobManager.options(
                name=JOB_MANAGER_NAME, namespace=JOBS_NAMESPACE,
                lifetime="detached",
            ).remote()
        except Exception as e:  # noqa: BLE001 — name-collision race only
            if "already taken" not in str(e):
                raise
            # lost a create race with a concurrent client: use the winner's
            return ray_tpu.get_actor(JOB_MANAGER_NAME, namespace=JOBS_NAMESPACE)

    def submit_job(self, *, entrypoint: str,
                   runtime_env: Optional[dict] = None,
                   submission_id: Optional[str] = None,
                   metadata: Optional[Dict[str, str]] = None) -> str:
        runtime_env = runtime_env or {}
        sid = submission_id or f"raysubmit_{uuid.uuid4().hex[:12]}"
        wd = runtime_env.get("working_dir")
        wd_zip = _zip_dir(wd) if wd else None
        return ray_tpu.get(
            self._manager.submit.remote(
                sid, entrypoint, dict(runtime_env.get("env_vars", {})),
                wd_zip, dict(metadata or {}),
            ),
            timeout=120,
        )

    def get_job_status(self, submission_id: str) -> str:
        return ray_tpu.get(
            self._manager.status.remote(submission_id), timeout=60
        )["status"]

    def get_job_info(self, submission_id: str) -> dict:
        return ray_tpu.get(
            self._manager.status.remote(submission_id), timeout=60)

    def get_job_logs(self, submission_id: str, offset: int = 0) -> str:
        return ray_tpu.get(
            self._manager.logs.remote(submission_id, offset), timeout=60)

    def stop_job(self, submission_id: str) -> bool:
        return ray_tpu.get(
            self._manager.stop.remote(submission_id), timeout=60)

    def list_jobs(self) -> List[dict]:
        return ray_tpu.get(self._manager.list.remote(), timeout=60)

    def tail_job_logs(self, submission_id: str, poll_s: float = 1.0):
        """Generator of log increments until the job finishes. Each poll
        ships only the unseen suffix (offset-based) — re-fetching the whole
        growing file every tick would be O(n^2) bytes over the wire."""
        seen = 0
        while True:
            chunk = self.get_job_logs(submission_id, offset=seen)
            if chunk:
                yield chunk
                seen += len(chunk.encode("utf-8", "replace"))
            if self.get_job_status(submission_id) in (
                SUCCEEDED, FAILED, STOPPED,
            ):
                chunk = self.get_job_logs(submission_id, offset=seen)
                if chunk:
                    yield chunk
                return
            time.sleep(poll_s)


__all__ = [
    "FAILED",
    "JobSubmissionClient",
    "PENDING",
    "RUNNING",
    "STOPPED",
    "SUCCEEDED",
]
