"""Per-job supervisor actor: runs one job's entrypoint as a child driver
process and fate-shares with it in BOTH directions.

Reference: dashboard/modules/job/job_supervisor.py:57 — one detached
supervisor actor per job; the entrypoint runs as a subprocess whose driver
joins the cluster; the supervisor polls it and the JobManager polls the
supervisor. Fate-sharing: the child dying flips the supervisor's status
(manager-visible), and the supervisor dying kills the child's whole
process group (atexit for clean exits, PR_SET_PDEATHSIG for hard kills),
so no orphaned driver keeps computing against a job the table already
declared dead.
"""

from __future__ import annotations

import atexit
import io
import os
import signal
import subprocess
import tempfile
import time
import zipfile
from typing import Dict, Optional

import ray_tpu
from ray_tpu._private import flight_recorder
from ray_tpu._private import config as _config

# JobStatus (reference: job/common.py JobStatus)
QUEUED = "QUEUED"        # submitted, waiting for fair-share admission
PENDING = "PENDING"      # admitted: supervisor actor creation in flight
RUNNING = "RUNNING"
SUCCEEDED = "SUCCEEDED"
FAILED = "FAILED"
STOPPED = "STOPPED"
TERMINAL = (SUCCEEDED, FAILED, STOPPED)


def _child_preexec():
    """Runs in the forked child before exec: new session (own process
    group, so stop() can killpg) + PR_SET_PDEATHSIG so the kernel SIGKILLs
    the driver if the supervisor dies without running atexit hooks.
    (pdeathsig arms against the forking THREAD's death — the actor
    executor thread — which only dies when the supervisor process does;
    atexit covers the graceful-exit paths the signal doesn't.)"""
    os.setsid()
    try:
        import ctypes

        PR_SET_PDEATHSIG = 1
        ctypes.CDLL(None, use_errno=True).prctl(
            PR_SET_PDEATHSIG, signal.SIGKILL, 0, 0, 0)
    except Exception:  # noqa: BLE001 — non-Linux: atexit still covers us
        pass


@ray_tpu.remote
class JobSupervisor:
    """Runs one job's entrypoint as a child process."""

    def __init__(self, submission_id: str, entrypoint: str,
                 env_vars: Dict[str, str],
                 working_dir_zip: Optional[bytes] = None):
        self.submission_id = submission_id
        self.entrypoint = entrypoint
        self._status = RUNNING
        self._message = ""
        # the job's driver joins THIS cluster: a submission on a machine
        # where the worker env lost the control address would otherwise
        # run the driver against a silent "" address and fail obscurely
        # deep inside its own init — fail the submission loudly instead
        control_addr = os.environ.get("RT_CONTROL_ADDR", "")
        if not control_addr:
            raise RuntimeError(
                f"job {submission_id!r}: RT_CONTROL_ADDR is not set in the "
                "supervisor's environment — cannot point the driver at the "
                "cluster (refusing to run it against an empty RT_ADDRESS)")
        workdir = None
        if working_dir_zip:
            workdir = tempfile.mkdtemp(prefix=f"job_{submission_id}_")
            zipfile.ZipFile(io.BytesIO(working_dir_zip)).extractall(workdir)
        self._log_path = os.path.join(
            tempfile.gettempdir(), f"rt_job_{submission_id}.log")
        env = dict(os.environ)
        env.update(env_vars)
        env["RT_ADDRESS"] = control_addr
        log = open(self._log_path, "ab")
        try:
            self._proc = subprocess.Popen(
                entrypoint, shell=True, env=env, cwd=workdir,
                stdout=log, stderr=subprocess.STDOUT,
                preexec_fn=_child_preexec,
            )
        finally:
            # the child inherited the descriptor; keeping ours open leaks
            # one fd per job for the supervisor's lifetime
            log.close()
        atexit.register(self._kill_child)
        flight_recorder.record("job", "driver_spawned", sid=submission_id,
                               pid=self._proc.pid)
        self._report_running()

    def _report_running(self):
        """Stamp RUNNING (+ host/pid) into the control-store job table
        directly: the transition must not wait on the manager's next poll,
        and the record survives the manager."""
        try:
            from ray_tpu._private.core_worker import get_core_worker

            cw = get_core_worker()
            cw.run_sync(cw.control.call("job_update", {
                "submission_id": self.submission_id,
                "fields": {"status": RUNNING, "message": "",
                           "start_time": time.time(),
                           "driver_pid": self._proc.pid,
                           "supervisor_node": cw.node_id_hex},
            }), 10)
        except Exception:  # noqa: BLE001 — the manager's poll still covers it
            pass

    def _kill_child(self):
        """Supervisor->child fate-share: SIGKILL the driver's process
        group on any supervisor exit path."""
        proc = getattr(self, "_proc", None)
        if proc is None or proc.poll() is not None:
            return
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass

    def pid(self) -> int:
        """Supervisor process pid (chaos harness: kill me and assert the
        driver dies with me)."""
        return os.getpid()

    def child_pid(self) -> int:
        return self._proc.pid

    def poll(self) -> dict:
        rc = self._proc.poll()
        if self._status == RUNNING and rc is not None:
            self._status = SUCCEEDED if rc == 0 else FAILED
            self._message = f"exit code {rc}"
        return {"status": self._status, "message": self._message}

    def logs(self, offset: int = 0) -> str:
        try:
            with open(self._log_path, "rb") as f:
                if offset:
                    f.seek(offset)
                return f.read().decode("utf-8", "replace")
        except OSError:
            return ""

    def stop(self) -> bool:
        self.poll()
        if self._status in (SUCCEEDED, FAILED):
            return False  # terminal states never transition
        if self._proc.poll() is None:
            try:
                os.killpg(os.getpgid(self._proc.pid), signal.SIGTERM)
            except (ProcessLookupError, PermissionError):
                pass
            deadline = time.time() + _config.GLOBAL_CONFIG.get("job_stop_grace_s")
            while time.time() < deadline and self._proc.poll() is None:
                time.sleep(0.1)
            if self._proc.poll() is None:
                try:
                    os.killpg(os.getpgid(self._proc.pid), signal.SIGKILL)
                except (ProcessLookupError, PermissionError):
                    pass
        self._status = STOPPED
        return True
