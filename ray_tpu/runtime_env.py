"""Public runtime-env surface (reference: python/ray/runtime_env/).

A runtime_env dict on @remote / .options() describes the environment a
task or actor runs in:

    ray_tpu.remote(runtime_env={
        "env_vars": {"TOKENIZERS_PARALLELISM": "false"},
        "pip": ["emoji==2.0"],          # or "uv": [...] (faster builds)
        "working_dir": "./my_project",  # content-addressed upload
        "py_modules": ["./libs/mylib"],
    })

`pip`/`uv` build content-addressed venvs on each node (workers are pooled
per env, so conflicting deps run concurrently in separate processes);
`working_dir`/`py_modules` ship as content-addressed zips through the
control store. Custom fields are added by registering a RuntimeEnvPlugin
(reference: _private/runtime_env/ARCHITECTURE.md's plugin registry).
"""

from ray_tpu._private.runtime_env_mgr import (
    RuntimeEnvPlugin,
    register_runtime_env_plugin,
    unregister_runtime_env_plugin,
)

__all__ = [
    "RuntimeEnvPlugin",
    "register_runtime_env_plugin",
    "unregister_runtime_env_plugin",
]
