"""ray_tpu.data — distributed, block-based data pipelines feeding TPU SPMD
training (reference surface: python/ray/data/__init__.py).

Datasets build a lazy LOGICAL PLAN; a rule-based optimizer (operator
fusion, limit/projection/predicate pushdown — ray_tpu/data/_logical/)
rewrites it and the physical planner compiles it onto the streaming
executor: one fused remote task per block. Blocks are columnar
dict-of-numpy; `Dataset.split()` shards blocks across train workers and
`iter_batches(device_put=True)` prefetches host→device.
"""

from ray_tpu.data.block import Block
from ray_tpu.data.context import DataContext
from ray_tpu.data.dataset import Dataset
from ray_tpu.data.datasource import (
    from_items,
    from_numpy,
    range,
    read_binary_files,
    read_csv,
    read_images,
    read_json,
    read_parquet,
    read_sql,
)

__all__ = [
    "Block",
    "DataContext",
    "Dataset",
    "range",
    "from_items",
    "from_numpy",
    "read_parquet",
    "read_sql",
    "read_csv",
    "read_json",
    "read_binary_files",
    "read_images",
]

from ray_tpu._private.usage import record_library_usage as _rlu

_rlu("data")
del _rlu
