"""Dataset: lazy, distributed, block-based data pipelines.

Reference surface: python/ray/data/dataset.py:203 (map/map_batches/filter/
flat_map/split/iter_batches/take/count) executed by the streaming executor
(python/ray/data/_internal/execution/streaming_executor.py:106).

TPU-first redesign instead of a port:
- a Dataset is (block producers, fused op chain). Materialization submits ONE
  task per block that applies the whole chain — operator fusion is the
  default (the reference fuses map chains inside its executor; here the
  chain is literally one function), and blocks execute in parallel across
  the cluster with no central executor loop.
- blocks are columnar dict-of-numpy (block.py), the layout `iter_batches`
  feeds straight to `jax.device_put` for host→device prefetch.
- `split()` hands disjoint block sets to SPMD train workers (the
  split-per-worker iterator of the reference's streaming_split).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.data.block import (
    Block,
    block_concat,
    block_num_rows,
    block_rows,
    block_slice,
    normalize_batch,
    rows_to_block,
)

# one op: (kind, fn) where kind in {"map_batches", "map", "filter", "flat_map"}
_Op = Tuple[str, Callable]


def _apply_ops(block: Block, ops: List[_Op]) -> Block:
    for kind, fn in ops:
        if kind == "map_batches":
            block = fn(normalize_batch(block))
        elif kind == "map":
            block = rows_to_block([fn(r) for r in block_rows(block)])
        elif kind == "filter":
            block = rows_to_block([r for r in block_rows(block) if fn(r)])
        elif kind == "flat_map":
            out: List[Any] = []
            for r in block_rows(block):
                out.extend(fn(r))
            block = rows_to_block(out)
        else:  # pragma: no cover — plan construction guards kinds
            raise ValueError(f"unknown op {kind}")
    return block


def _run_chain(producer_or_block, ops: List[_Op]) -> Block:
    """The per-block fused task body: produce (or receive) the source block,
    then apply the whole op chain."""
    block = producer_or_block() if callable(producer_or_block) else producer_or_block
    from ray_tpu._private.core_worker import ObjectRef

    if isinstance(block, ObjectRef):
        # closure-captured ref (union of materialized datasets): resolve
        # in-task — only top-level args resolve automatically
        import ray_tpu

        block = ray_tpu.get(block, timeout=600)
    return _apply_ops(block, ops)


# A pipeline stage: ("tasks", ops) — stateless fused segment, one task per
# block; or ("actors", udf_factory, args, kwargs, concurrency) — stateful
# map_batches through an actor pool (reference:
# python/ray/data/_internal/execution/operators/actor_pool_map_operator.py:1).
_Stage = Tuple


def _stable_key_hash(v) -> int:
    """Deterministic cross-process key hash for shuffles/joins. NOT hash():
    str hashing is per-process randomized. Numeric keys canonicalize first
    (1, 1.0, np.int64(1), True are dict-equal and must co-partition)."""
    import hashlib as _hl

    if hasattr(v, "item"):
        v = v.item()
    if isinstance(v, bool):
        v = int(v)
    if isinstance(v, float) and v.is_integer():
        v = int(v)
    d = _hl.blake2b(repr(v).encode(), digest_size=8).digest()
    return int.from_bytes(d, "little")



def _shuffle_partitions(refs, requested: Optional[int] = None) -> int:
    """Partition count for shuffle-class ops (sort/shuffle/groupby/join).

    Spill-aware sizing (reference: the shuffle partitioning in
    execution/operators/hash_shuffle + resource_manager budgets): target
    ~shuffle_target_partition_bytes per partition from SAMPLED block sizes,
    capped at shuffle_max_partitions — without the cap, B input blocks x
    B partitions costs B^2 return refs and B-arg merge tasks, which is what
    falls over at hundreds of blocks, not the O(N) data movement."""
    if requested:
        return max(1, int(requested))
    n = len(refs)
    if n <= 1:
        return max(1, n)
    from ray_tpu.data.context import DataContext

    ctx = DataContext.get_current()
    target = ctx.shuffle_target_partition_bytes
    cap = ctx.shuffle_max_partitions
    from ray_tpu.data._executor import _ref_size

    # strided sample: leading blocks are often unrepresentative (header /
    # remainder blocks from readers)
    probe = refs[::max(1, n // 8)][:8]
    sizes = [sz for sz in (_ref_size(r) for r in probe) if sz is not None]
    if sizes:
        est_total = (sum(sizes) / len(sizes)) * n
        want = -(-int(est_total) // max(1, target))
        return max(1, min(n, cap, max(want, 1)))
    return max(1, min(n, cap))


def _slice_row_range(lo: int, hi: int, block_starts, *blocks) -> Block:
    """Rows [lo, hi) of a virtual concatenation, given each block's global
    start offset (shared by repartition and zip alignment)."""
    parts = []
    for s, b in zip(block_starts, blocks):
        n = block_num_rows(b)
        a, z = max(lo, s), min(hi, s + n)
        if z > a:
            parts.append(block_slice(b, a - s, z - s))
    return block_concat(parts) if parts else rows_to_block([])


class _CallableWrapper:
    """Adapts a plain function to the actor-pool UDF-class protocol."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, batch):
        return self._fn(batch)

    @staticmethod
    def of(fn):
        import functools

        return functools.partial(_CallableWrapper, fn)


class _Pipeline:
    """Executable form of a Dataset plan: source producers + stage list.
    Submits ONE chained ref pipeline per source block; actor stages route
    through their pool.

    Pools here are FIRE-AND-FORGET: materialize() submits every block
    before any resolves and shuts the pools down right after the barrier,
    so no task_done feedback flows and least-loaded routing degrades to
    submission-count balancing (which is uniform). The streaming executor
    (_executor.StreamingExecutorV2) is the path with live load feedback."""

    def __init__(self, producers, stages: List[_Stage]):
        from ray_tpu.remote_function import RemoteFunction

        self.producers = producers
        self.stages = stages
        from ray_tpu.data._executor import AutoScalingActorPool

        self._run = RemoteFunction(_run_chain)
        self._pools: List[Optional[AutoScalingActorPool]] = []
        for st in stages:
            if st[0] == "actors":
                _, cls, args, kwargs, size = st
                if isinstance(size, tuple):  # (min, max) autoscaling spec
                    size = size[1]
                # fixed-size pool (materialize() has no scheduling loop to
                # drive scaling); the streaming executor autoscales
                self._pools.append(
                    AutoScalingActorPool(cls, args, kwargs, size, size))
            else:
                self._pools.append(None)

    def submit_block(self, producer):
        """Chain the whole stage pipeline for one source block; returns the
        final block ref. No barriers — downstream stages start as soon as
        their input ref resolves."""
        from ray_tpu._private.core_worker import ObjectRef

        ref = producer
        materialized = isinstance(ref, ObjectRef)
        for st, pool in zip(self.stages, self._pools):
            if st[0] == "tasks":
                if st[1] or not materialized:
                    ref = self._run.remote(ref, st[1])
                    materialized = True
            else:
                if not materialized:
                    # actor stage first: actors take BLOCKS, so a callable
                    # source materializes through one producer task
                    ref = self._run.remote(ref, [])
                    materialized = True
                ref = pool.submit(ref)
        if not materialized:
            ref = self._run.remote(ref, [])
        return ref

    def shutdown(self):
        for p in self._pools:
            if p is not None:
                p.shutdown()


class Dataset:
    """A lazy distributed collection of blocks.

    `_producers` are zero-arg callables (or ObjectRefs of already-computed
    blocks) each yielding one source block; `_ops` is the pending fused
    chain. All transforms are lazy; `materialize()`/consumption triggers one
    remote task per block.
    """

    def __init__(self, producers: List[Any], ops: Optional[List[_Op]] = None,
                 *, _refs: Optional[List[Any]] = None,
                 _pre_stages: Optional[List[_Stage]] = None):
        self._producers = producers
        self._ops: List[_Op] = list(ops or [])
        # completed pipeline segments before the trailing fused chain
        # (actor-pool stages split the chain)
        self._pre_stages: List[_Stage] = list(_pre_stages or [])
        self._refs = _refs  # cached materialized block refs
        # global row cap from limit(); blocks are cut wherever they surface
        self._row_limit: Optional[int] = None
        # limit FENCE: when a row-count-changing op is chained after
        # limit(), this dataset's ops apply to the PARENT's stream-order-cut
        # output (never to rows past the global budget) instead of fusing
        # into the per-block chain — see _chain
        self._limit_src: Optional["Dataset"] = None

    def _stages(self) -> List[_Stage]:
        stages = list(self._pre_stages)
        if self._ops or not stages:
            stages.append(("tasks", self._ops))
        return stages

    # -- transforms (lazy) ---------------------------------------------

    def _chain(self, kind: str, fn: Callable) -> "Dataset":
        if self._row_limit is not None and kind in (
                "filter", "flat_map", "map_batches"):
            # A row-count-changing op chained after limit(): the per-block
            # cap + surface cut would let this op see rows past the global
            # budget (and keep post-limit rows the cut can't tell apart).
            # Fence the plan: the parent's stream-order cut runs first, and
            # this op applies only to the capped stream. ("map" is 1:1, so
            # it keeps riding the fused chain + surface cut.)
            out = Dataset([], [(kind, fn)])
            out._limit_src = self
            return out
        if self._refs is not None:
            out = Dataset(list(self._refs), [(kind, fn)])
        else:
            out = Dataset(list(self._producers), self._ops + [(kind, fn)],
                          _pre_stages=self._pre_stages)
            out._limit_src = self._limit_src
        out._row_limit = self._row_limit
        return out

    def map_batches(self, fn: Any, *, concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None) -> "Dataset":
        """Apply fn to whole blocks in columnar {col: ndarray} form.

        A CLASS (or any callable with `concurrency=`) becomes a stateful
        actor-pool stage: `concurrency` actors each construct the UDF once
        (fn_constructor_args) and stream blocks through it — the reference's
        ActorPoolMapOperator, for UDFs with expensive setup (model weights,
        tokenizers). `concurrency=(min, max)` enables queue-driven actor
        AUTOSCALING in the streaming executor (reference:
        actor_pool_map_operator.py + actor_autoscaler)."""
        if concurrency is not None or isinstance(fn, type):
            if self._refs is None and (
                    self._limit_src is not None
                    or self._row_limit is not None):
                # actor stages can change row counts too: bake the
                # stream-order cut before the pool sees any block
                self._block_refs()
            base = self._refs if self._refs is not None else self._producers
            pre = [] if self._refs is not None else self._pre_stages
            ops = [] if self._refs is not None else self._ops
            udf = fn if isinstance(fn, type) else _CallableWrapper.of(fn)
            if isinstance(concurrency, tuple):
                conc: Any = (int(concurrency[0]), int(concurrency[1]))
            else:
                conc = int(concurrency or 1)
            stage = ("actors", udf, tuple(fn_constructor_args),
                     dict(fn_constructor_kwargs or {}), conc)
            return Dataset(
                list(base), [],
                _pre_stages=pre + [("tasks", ops), stage] if ops
                else pre + [stage],
            )
        return self._chain("map_batches", fn)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._chain("map", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._chain("filter", fn)

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return self._chain("flat_map", fn)

    # -- execution ------------------------------------------------------

    def materialize(self) -> "Dataset":
        """Execute the plan: one fused remote task per block (actor stages
        route through their pools). Returns a Dataset backed by block
        ObjectRefs (repeat consumption is free)."""
        if self._refs is not None:
            return self
        if self._limit_src is not None:
            # limit fence: bake the parent's stream-order cut into refs,
            # then run this dataset's post-limit ops over those (≤ n rows).
            # A limit chained AFTER the fence must propagate so its global
            # cut bakes too (_block_refs applies it), not just the fused
            # per-block cap.
            base = self._limit_src._block_refs()
            mid = Dataset(list(base), list(self._ops))
            mid._row_limit = self._row_limit
            refs = mid._block_refs()
            return Dataset(refs, [], _refs=refs)
        import ray_tpu
        from ray_tpu._private.core_worker import ObjectRef

        stages = self._stages()
        if len(stages) == 1 and stages[0] == ("tasks", []):
            if all(isinstance(p, ObjectRef) for p in self._producers):
                refs = list(self._producers)
                return Dataset(refs, [], _refs=refs)
        pipeline = _Pipeline(self._producers, stages)
        refs = [pipeline.submit_block(p) for p in self._producers]
        if any(pool is not None for pool in pipeline._pools):
            # actor pools must outlive their in-flight blocks
            ray_tpu.wait(refs, num_returns=len(refs), timeout=None)
        pipeline.shutdown()
        return Dataset(refs, [], _refs=refs)

    def iter_blocks(self, *, window: Optional[int] = None) -> Iterator[Block]:
        """STREAMING consumption: pull blocks through the plan under the
        v2 streaming executor (per-stage dispatch, per-op byte budgets,
        actor autoscaling — see ray_tpu.data._executor). Materialized
        datasets iterate their cached refs.

        Streaming deliberately does NOT cache results: repeat consumption
        re-executes the plan (and re-creates actor pools). Call
        materialize() first to pin block refs for repeated reads — the
        aggregate/sort/shuffle paths do so internally via _block_refs."""
        budget = self._row_limit

        def cut(blocks):
            nonlocal budget
            for block in blocks:
                if budget is None:
                    yield block
                    continue
                if budget <= 0:
                    return  # global limit reached: stop pulling upstream
                rows = block_num_rows(block)
                if rows > budget:
                    yield Dataset._truncate_block(block, budget)
                    budget = 0
                    return
                budget -= rows
                yield block

        import ray_tpu

        if self._refs is not None:
            yield from cut(
                ray_tpu.get(ref, timeout=600) for ref in self._refs)
            return
        if self._limit_src is not None:
            # limit fence: the parent applies its own stream-order cut (and
            # stops pulling upstream once the budget is spent); this
            # dataset's ops only ever see rows within the global limit
            yield from cut(
                _apply_ops(block, self._ops)
                for block in self._limit_src.iter_blocks(window=window))
            return
        if window is None:
            from ray_tpu.data.context import DataContext

            window = DataContext.get_current().streaming_block_window
        from ray_tpu.data._executor import StreamingExecutorV2

        ex = StreamingExecutorV2(
            self._producers, self._stages(), window=window)
        try:
            yield from cut(ex)
        finally:
            self._last_stats = getattr(ex, "last_stats", None)

    def _block_refs(self) -> List[Any]:
        # cache the materialization on THIS dataset too: repeated consumers
        # (sum then mean then std; schema after count) must not re-execute
        # the whole plan per call
        if (self._refs is None and self._row_limit is not None
                and self._limit_src is None and len(self._producers) > 1):
            # limit pushdown into the PLAN, not just the surface: execute
            # producers in stream order and stop submitting once the row
            # budget is covered — ds.limit(10) over 1,000 blocks runs the
            # prefix, never all 1,000 tasks (reference: the logical
            # optimizer's limit pushdown + streaming early termination)
            refs = self._materialize_limit_prefix(self._row_limit)
            self._row_limit = None
            self._refs = refs
            return refs
        refs = self.materialize()._refs
        if self._row_limit is not None:
            refs = self._cut_refs(refs, self._row_limit)
            self._row_limit = None  # the cut is baked into the refs now
        self._refs = refs
        return refs

    def _materialize_limit_prefix(self, n: int) -> List[Any]:
        """Execute the plan over the shortest producer prefix whose rows
        cover `n`, in submission windows: count each window's output and
        stop before the next window once the budget is met. Blocks past the
        boundary are never submitted."""
        from ray_tpu.data.context import DataContext
        from ray_tpu.remote_function import RemoteFunction

        window = max(1, DataContext.get_current().streaming_block_window)
        cut = RemoteFunction(Dataset._truncate_block)
        pipeline = _Pipeline(self._producers, self._stages())
        out: List[Any] = []
        remaining = n
        try:
            for start in range(0, len(self._producers), window):
                if remaining <= 0:
                    break
                batch = [
                    pipeline.submit_block(p)
                    for p in self._producers[start:start + window]
                ]
                # the count barrier doubles as the pools'
                # must-outlive-in-flight-blocks barrier per window
                counts = self._block_row_counts(batch)
                for ref, c in zip(batch, counts):
                    if remaining <= 0:
                        break  # computed past the boundary; dropped
                    if c <= remaining:
                        out.append(ref)
                        remaining -= c
                    else:
                        out.append(cut.remote(ref, remaining))
                        remaining = 0
        finally:
            # safe here: every pool-produced block resolved at its window's
            # count barrier; the boundary cut is a plain task over an
            # already-computed ref, so it survives pool shutdown
            pipeline.shutdown()
        return out

    def _cut_refs(self, refs: List[Any], n: int) -> List[Any]:
        """Global limit over materialized blocks: keep whole blocks up to
        the boundary, slice the boundary block remotely, drop the rest."""
        from ray_tpu.remote_function import RemoteFunction

        counts = self._block_row_counts(refs)
        out: List[Any] = []
        remaining = n
        cut = RemoteFunction(Dataset._truncate_block)
        for ref, c in zip(refs, counts):
            if remaining <= 0:
                break
            if c <= remaining:
                out.append(ref)
                remaining -= c
            else:
                out.append(cut.remote(ref, remaining))
                remaining = 0
        return out

    # -- consumption ----------------------------------------------------

    def num_blocks(self) -> int:
        if self._limit_src is not None and self._refs is None:
            return self._limit_src.num_blocks()
        return len(self._producers)

    def count(self) -> int:
        import ray_tpu

        refs = self._block_refs()
        return sum(
            block_num_rows(b) for b in ray_tpu.get(refs, timeout=600)
        )

    def limit(self, n: int) -> "Dataset":
        """Truncate to the first `n` rows (reference: Dataset.limit +
        the logical optimizer's limit pushdown). Two halves: a per-block
        cap PUSHES DOWN into the fused task chain, and the GLOBAL cut is
        enforced in stream order wherever blocks surface — _block_refs,
        iter_blocks, take/count — via the propagated row-limit mark.
        Chaining a row-count-changing op (filter/flat_map/map_batches)
        after limit() fences the plan at the limit (see _chain), so such
        ops never observe rows beyond the global budget."""
        if n < 0:
            raise ValueError("limit must be >= 0")

        def _truncate(block: Block) -> Block:
            if isinstance(block, dict):
                return {c: v[:n] for c, v in block.items()}
            return list(block)[:n]

        out = self._chain("map_batches", _truncate)
        prev = getattr(self, "_row_limit", None)
        out._row_limit = n if prev is None else min(prev, n)
        return out

    @staticmethod
    def _truncate_block(block: Block, n: int) -> Block:
        if isinstance(block, dict):
            return {c: np.asarray(v)[:n] for c, v in block.items()}
        return list(block)[:n]

    def explain(self) -> str:
        """Human-readable logical plan: the fused stage chain this dataset
        executes (reference: the logical plan the data optimizer prints).
        One "tasks[...]" stage = ONE fused remote task per block; a
        "limit[...]" line marks a stream-order fence (ops below it only see
        rows within the global budget)."""
        if self._limit_src is not None and self._refs is None:
            lines = self._limit_src.explain().splitlines()
            lines.append("  limit[stream-order fence: "
                         f"{self._limit_src._row_limit} rows]")
        else:
            lines = [f"Dataset({len(self._producers)} blocks"
                     f"{', materialized' if self._refs is not None else ''})"]
        for kind, *rest in self._stages():
            if kind == "tasks":
                ops = rest[0]
                names = [op for op, _fn in ops] or ["read"]
                lines.append(f"  tasks[fused: {' -> '.join(names)}]")
            else:
                _cls, _args, _kwargs, conc = rest
                lines.append(f"  actors[{_cls.__name__}, "
                             f"concurrency={conc}]")
        return "\n".join(lines)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for block in self.iter_blocks():
            for row in block_rows(block):
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Any]:
        return self.take(limit=2**62)

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from block_rows(block)

    def iter_batches(
        self,
        batch_size: Optional[int] = 256,
        *,
        drop_last: bool = False,
        device_put: bool = False,
        prefetch_blocks: int = 2,
    ) -> Iterator[Block]:
        """Iterate fixed-size columnar batches across block boundaries.

        device_put=True moves each numpy batch onto the default JAX device
        before yielding — host→device transfer overlaps the consumer's step
        (the reference's iter_torch_batches prefetch, TPU-flavored).

        Unmaterialized datasets STREAM: at most `prefetch_blocks` source
        blocks are in flight and consumed blocks free their shm copies
        before more are admitted, so datasets larger than the object store
        iterate in constant memory.
        """
        carry: Optional[Block] = None

        def to_out(b: Block) -> Block:
            if device_put and isinstance(b, dict):
                import jax

                return {k: jax.device_put(v) for k, v in b.items()}
            return b

        for block in self.iter_blocks(
                window=None if prefetch_blocks is None
                else max(1, prefetch_blocks)):
            carry = block if carry is None else block_concat([carry, block])
            if batch_size is None:
                yield to_out(carry)
                carry = None
                continue
            while block_num_rows(carry) >= batch_size:
                yield to_out(block_slice(carry, 0, batch_size))
                carry = block_slice(carry, batch_size, block_num_rows(carry))
        if carry is not None and block_num_rows(carry) > 0 and not drop_last:
            yield to_out(carry)

    # -- reorganization -------------------------------------------------

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Split into n datasets over disjoint blocks (per-train-worker
        shards; reference: Dataset.split / streaming_split). equal=True
        repartitions first so every shard has the same row count (±1), which
        SPMD training needs for lockstep batches."""
        if equal:
            refs = self.repartition(n)._refs
            return [Dataset([r], [], _refs=[r]) for r in refs]
        refs = self._block_refs()
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [Dataset(s, [], _refs=s) for s in shards]

    def _block_row_counts(self, refs: List[Any]) -> List[int]:
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        count = RemoteFunction(block_num_rows)
        return ray_tpu.get([count.remote(r) for r in refs], timeout=600)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance rows into `num_blocks` equal blocks (materializes).

        Each output task receives only the input blocks overlapping its row
        range — O(N) total movement, not all-blocks-to-every-task."""
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        refs = self._block_refs()
        counts = self._block_row_counts(refs)
        starts = list(np.cumsum([0] + counts))  # global start offset per block
        total = starts[-1]

        run = RemoteFunction(_slice_row_range)
        new_refs = []
        for i in range(num_blocks):
            lo, hi = (total * i) // num_blocks, (total * (i + 1)) // num_blocks
            overlap = [
                j for j in range(len(refs))
                if starts[j] < hi and starts[j] + counts[j] > lo
            ]
            new_refs.append(run.remote(
                lo, hi, [starts[j] for j in overlap], *[refs[j] for j in overlap]
            ))
        return Dataset(new_refs, [], _refs=new_refs)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global random shuffle (materializes). Two-stage push shuffle as in
        the reference's shuffle ops: each input block scatters its rows into
        k partitions (one task, k returns); each output concatenates and
        permutes its k incoming parts — O(N) total movement."""
        from ray_tpu.remote_function import RemoteFunction

        refs = self._block_refs()
        k = _shuffle_partitions(refs)
        if len(refs) <= 1:
            return Dataset(list(refs), [], _refs=list(refs))

        def _scatter(sd, j: int, k: int, block):
            rng = np.random.default_rng(None if sd is None else sd * 1_000_003 + j)
            n = block_num_rows(block)
            assign = rng.integers(0, k, size=n)
            if isinstance(block, dict):
                return tuple(
                    {c: v[assign == i] for c, v in block.items()} for i in range(k)
                )
            items = list(block)
            return tuple(
                [items[t] for t in np.flatnonzero(assign == i)] for i in range(k)
            )

        def _merge(sd, i: int, *parts):
            whole = block_concat(list(parts))
            rng = np.random.default_rng(None if sd is None else sd * 7_000_003 + i)
            n = block_num_rows(whole)
            perm = rng.permutation(n)
            if isinstance(whole, dict):
                return {c: v[perm] for c, v in whole.items()}
            return [whole[j] for j in perm]

        merge = RemoteFunction(_merge)
        if k == 1:
            # size-driven single partition: permute everything in one task
            new_refs = [merge.remote(seed, 0, *refs)]
            return Dataset(new_refs, [], _refs=new_refs)
        scatter = RemoteFunction(_scatter).options(num_returns=k)
        # EVERY input block scatters (k is the partition count, which may
        # be smaller than the block count under spill-aware sizing)
        partitions = [
            scatter.remote(seed, j, k, refs[j]) for j in range(len(refs))
        ]
        new_refs = [
            merge.remote(seed, i, *[p[i] for p in partitions])
            for i in range(k)
        ]
        return Dataset(new_refs, [], _refs=new_refs)

    @staticmethod
    def _sort_single_partition(refs, key, descending) -> "Dataset":
        """One global sort task (a per-block sort would not be a global
        order when several blocks feed one partition)."""
        from ray_tpu.remote_function import RemoteFunction

        def _sort_all(*blocks):
            return _sort_block(block_concat(list(blocks)), key, descending)

        new_refs = [RemoteFunction(_sort_all).remote(*refs)]
        return Dataset(new_refs, [], _refs=new_refs)

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Distributed sort (materializes): sample key range → range-partition
        scatter → per-partition sort (reference: data sort ops; the classic
        TeraSort shape, O(N) movement + parallel partition sorts)."""
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        refs = self._block_refs()
        k = _shuffle_partitions(refs)
        if not refs:
            return Dataset([], [], _refs=[])
        if k == 1:
            # no range bounds needed — skip the sampling round-trip
            return self._sort_single_partition(refs, key, descending)

        def _sample(block):
            col = np.asarray(block[key]) if isinstance(block, dict) else (
                np.asarray([r[key] for r in block_rows(block)])
            )
            if col.size == 0:
                return col
            take = min(64, col.size)
            idx = np.random.default_rng(0).choice(col.size, take, replace=False)
            return col[idx]

        samples = np.concatenate([
            s for s in ray_tpu.get(
                [RemoteFunction(_sample).remote(r) for r in refs], timeout=600)
            if s.size
        ])
        if samples.size == 0:
            return self._sort_single_partition(refs, key, descending)
        # positional quantiles, not np.quantile: sort keys may be strings
        # (any sortable dtype) and only order matters for range bounds
        srt = np.sort(samples)
        bounds = srt[[
            min(srt.size - 1, max(0, (srt.size * i) // k)) for i in range(1, k)
        ]]

        def _scatter(block, bounds):
            col = np.asarray(block[key]) if isinstance(block, dict) else (
                np.asarray([r[key] for r in block_rows(block)])
            )
            assign = np.searchsorted(bounds, col, side="right")
            n_parts = len(bounds) + 1
            if isinstance(block, dict):
                return tuple(
                    {c: np.asarray(v)[assign == i] for c, v in block.items()}
                    for i in range(n_parts)
                )
            items = list(block)
            return tuple(
                [items[t] for t in np.flatnonzero(assign == i)]
                for i in range(n_parts)
            )

        def _merge_sort(*parts):
            return _sort_block(block_concat(list(parts)), key, descending)

        scatter = RemoteFunction(_scatter).options(num_returns=k)
        partitions = [scatter.remote(r, bounds) for r in refs]
        order = range(k - 1, -1, -1) if descending else range(k)
        # fan-in over EVERY scatter (len(refs)), not range(k): k may be
        # size-driven < len(refs)
        new_refs = [
            RemoteFunction(_merge_sort).remote(*[p[i] for p in partitions])
            for i in order
        ]
        return Dataset(new_refs, [], _refs=new_refs)

    def groupby(self, key: str) -> "GroupedData":
        """Group rows by a key column (reference: Dataset.groupby +
        hash-shuffle aggregate ops)."""
        return GroupedData(self, key)

    # -- multi-dataset ops (reference: Dataset.union/zip/join) ----------

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets (block-wise, no materialization): each
        source block carries its own pending chain into the combined plan."""
        import functools

        def items(ds: "Dataset") -> List[Any]:
            if ds._refs is not None:
                return list(ds._refs)
            if ds._limit_src is not None or ds._row_limit is not None:
                # limit semantics can't ride a fused closure: bake the cut
                return list(ds._block_refs())
            stages = ds._stages()
            if stages == [("tasks", [])]:
                return list(ds._producers)
            if all(s[0] == "tasks" for s in stages):
                ops = [op for s in stages for op in s[1]]
                return [functools.partial(_run_chain, p, ops)
                        for p in ds._producers]
            # actor stages can't ride a closure: materialize that branch
            return list(ds.materialize()._refs)

        combined: List[Any] = []
        for ds in (self, *others):
            combined.extend(items(ds))
        return Dataset(combined, [])

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two datasets with equal row counts (reference:
        Dataset.zip): the other dataset is range-repartitioned to this one's
        block boundaries, then each aligned pair merges columns in one task
        (duplicate names get a _1 suffix)."""
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        left = self._block_refs()
        counts = self._block_row_counts(left)
        right_all = other._block_refs()
        r_counts = other._block_row_counts(right_all)
        if sum(counts) != sum(r_counts):
            raise ValueError(
                f"zip needs equal row counts: {sum(counts)} vs {sum(r_counts)}")
        r_starts = list(np.cumsum([0] + r_counts))

        def _zip_blocks(a, b):
            if not isinstance(a, dict) or not isinstance(b, dict):
                return [
                    (ra, rb) for ra, rb in zip(block_rows(a), block_rows(b))
                ]
            out = dict(a)
            for k, v in b.items():
                out[k if k not in out else f"{k}_1"] = v
            return out

        slicer = RemoteFunction(_slice_row_range)
        zipper = RemoteFunction(_zip_blocks)
        new_refs = []
        lo = 0
        for ref, n in zip(left, counts):
            hi = lo + n
            overlap = [
                j for j in range(len(right_all))
                if r_starts[j] < hi and r_starts[j] + r_counts[j] > lo
            ]
            aligned = slicer.remote(
                lo, hi, [r_starts[j] for j in overlap],
                *[right_all[j] for j in overlap])
            new_refs.append(zipper.remote(ref, aligned))
            lo = hi
        return Dataset(new_refs, [], _refs=new_refs)

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join on column `on` (reference: the data join
        operator / hash_shuffle): both sides scatter rows by hash(key) into
        k partitions (one task per block, k returns), then one task per
        partition builds a hash table from the left rows and probes with the
        right — O(N) movement, k-way parallel joins."""
        if how not in ("inner", "left"):
            raise ValueError(f"unsupported join type {how!r}")
        from ray_tpu.remote_function import RemoteFunction

        left = self._block_refs()
        right = other._block_refs()
        # size BOTH sides: a huge few-block side must not collapse the
        # join because the other side has more (tiny) blocks
        k = (int(num_partitions) if num_partitions
             else max(_shuffle_partitions(left), _shuffle_partitions(right)))

        def _scatter(block, k):
            rows = list(block_rows(block))
            parts: List[List[Any]] = [[] for _ in range(k)]
            for r in rows:
                parts[_stable_key_hash(r[on]) % k].append(r)
            return tuple(rows_to_block(p) for p in parts)

        def _join_partition(n_left, *parts):
            lrows = [r for b in parts[:n_left] for r in block_rows(b)]
            rrows = [r for b in parts[n_left:] for r in block_rows(b)]
            table: Dict[Any, List[Any]] = {}
            for r in rrows:
                table.setdefault(r[on], []).append(r)
            out = []
            for lr in lrows:
                matches = table.get(lr[on])
                if matches:
                    for rr in matches:
                        merged = dict(lr)
                        for ck, cv in rr.items():
                            if ck != on:
                                merged[ck if ck not in merged
                                       else f"{ck}_1"] = cv
                        out.append(merged)
                elif how == "left":
                    out.append(dict(lr))
            return rows_to_block(out)

        joiner = RemoteFunction(_join_partition)
        if k == 1:
            # num_returns=1 .remote() stores the 1-tuple whole; skip the
            # scatter and hand the raw block refs to the join task (advisor r3)
            new_refs = [joiner.remote(len(left), *left, *right)]
        else:
            scatter = RemoteFunction(_scatter).options(num_returns=k)
            lparts = [scatter.remote(r, k) for r in left]
            rparts = [scatter.remote(r, k) for r in right]
            new_refs = [
                joiner.remote(
                    len(lparts),
                    *[lp[i] for lp in lparts],
                    *[rp[i] for rp in rparts],
                )
                for i in range(k)
            ]
        return Dataset(new_refs, [], _refs=new_refs)

    # -- global aggregates (reference: Dataset.sum/min/max/mean/std) ----

    def _column_stats(self, col: str):
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        def _stats(block):
            v = np.asarray(block[col]) if isinstance(block, dict) else (
                np.asarray([r[col] for r in block_rows(block)])
            )
            if v.size == 0:
                # None (not 0.0) so an empty block can't masquerade as a
                # numeric contribution on a non-numeric column
                return (0, None, None, None, None)
            # String keys are legal sort()/min()/max() inputs; only numeric
            # dtypes have a sum / sum-of-squares (advisor r2).
            if np.issubdtype(v.dtype, np.number) or v.dtype == np.bool_:
                total = float(v.sum())
                sq = float((v.astype(np.float64) ** 2).sum())
                mn, mx = v.min().item(), v.max().item()
            else:
                # np.min has no ufunc loop for str/object dtypes
                total = sq = None
                vals = v.tolist()
                mn, mx = min(vals), max(vals)
            return (int(v.size), total, sq, mn, mx)

        parts = ray_tpu.get(
            [RemoteFunction(_stats).remote(r) for r in self._block_refs()],
            timeout=600,
        )
        n = sum(p[0] for p in parts)
        sums = [p[1] for p in parts if p[1] is not None]
        sqs = [p[2] for p in parts if p[2] is not None]
        total = sum(sums) if sums else None
        sq = sum(sqs) if sqs else None
        mins = [p[3] for p in parts if p[3] is not None]
        maxs = [p[4] for p in parts if p[4] is not None]
        return n, total, sq, (min(mins) if mins else None), (max(maxs) if maxs else None)

    def sum(self, col: str):
        return self._column_stats(col)[1]

    def mean(self, col: str):
        n, total, *_ = self._column_stats(col)
        return total / n if (n and total is not None) else None

    def min(self, col: str):
        return self._column_stats(col)[3]

    def max(self, col: str):
        return self._column_stats(col)[4]

    def std(self, col: str, ddof: int = 1):
        n, total, sq, _, _ = self._column_stats(col)
        if n <= ddof or total is None or sq is None:
            return None
        mean = total / n
        return float(np.sqrt(max(0.0, (sq - n * mean * mean) / (n - ddof))))

    # -- introspection --------------------------------------------------

    def stats(self) -> str:
        """Per-op execution table of the most recent STREAMING consumption
        (reference: python/ray/data/stats.py — blocks, bytes, task times,
        peak concurrency/queue, backpressure time per operator)."""
        st = getattr(self, "_last_stats", None)
        if st is None:
            return ("(no stats yet: stats cover streaming consumption — "
                    "iterate the dataset first)")
        return str(st)

    def schema(self) -> Optional[Dict[str, str]]:
        import ray_tpu

        refs = self._block_refs()
        if not refs:
            return None
        block = ray_tpu.get(refs[0], timeout=600)
        if isinstance(block, dict):
            return {k: str(v.dtype) for k, v in block.items()}
        return None

    def __repr__(self):
        ops = "->".join(k for k, _ in self._ops) or "source"
        return f"Dataset(blocks={len(self._producers)}, plan={ops})"


def _sort_block(block: Block, key: str, descending: bool) -> Block:
    if isinstance(block, dict):
        col = np.asarray(block[key])
        order = np.argsort(col, kind="stable")
        if descending:
            order = order[::-1]
        return {c: np.asarray(v)[order] for c, v in block.items()}
    rows = sorted(block_rows(block), key=lambda r: r[key], reverse=descending)
    return rows_to_block(rows)


class GroupedData:
    """Hash-partitioned group-by + aggregates (reference: data groupby with
    hash_shuffle aggregate operators). Keys scatter to k partitions by hash;
    each partition aggregates its groups independently."""

    # per-group leaf computed inside one partition: hash partitioning puts
    # ALL rows of a group in the same partition, so no cross-partition
    # combine is needed — mean included
    _AGGS = {
        "count": len,
        "sum": lambda vals: np.sum(vals).item(),
        "min": lambda vals: np.min(vals).item(),
        "max": lambda vals: np.max(vals).item(),
        "mean": lambda vals: float(np.mean(vals)),
    }

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _aggregate(self, agg: str, col: Optional[str]) -> Dataset:
        from ray_tpu.remote_function import RemoteFunction

        if agg not in self._AGGS:
            raise ValueError(f"unknown aggregate {agg!r}")
        key = self._key
        refs = self._ds._block_refs()
        if not refs:
            return Dataset([], [], _refs=[])
        k = _shuffle_partitions(refs)

        def _scatter(block, k):
            keys = (np.asarray(block[key]) if isinstance(block, dict)
                    else np.asarray([r[key] for r in block_rows(block)]))
            assign = np.asarray(
                [_stable_key_hash(x) % k for x in keys.tolist()])
            if isinstance(block, dict):
                return tuple(
                    {c: np.asarray(v)[assign == i] for c, v in block.items()}
                    for i in range(k)
                )
            items = list(block)
            return tuple(
                [items[t] for t in np.flatnonzero(assign == i)]
                for i in range(k)
            )

        def _agg_partition(agg, col, *parts):
            whole = block_concat(list(parts))
            groups: Dict[Any, list] = {}
            for r in block_rows(whole):
                groups.setdefault(r[key], []).append(
                    r[col] if col is not None else 1
                )
            leaf = GroupedData._AGGS[agg]
            out_name = f"{agg}({col})" if col else "count()"
            return rows_to_block([
                {key: gk, out_name: leaf(vals)} for gk, vals in groups.items()
            ])

        agg_fn = RemoteFunction(_agg_partition)
        if k == 1:
            # no scatter needed — but EVERY block feeds the one partition
            # (k may be size-driven < len(refs) now)
            new_refs = [agg_fn.remote(agg, col, *refs)]
        else:
            scatter = RemoteFunction(_scatter).options(num_returns=k)
            partitions = [scatter.remote(r, k) for r in refs]
            # fan-in over EVERY scatter (len(refs) of them), not range(k):
            # k may be size-driven < len(refs)
            new_refs = [
                agg_fn.remote(agg, col, *[p[i] for p in partitions])
                for i in range(k)
            ]
        return Dataset(new_refs, [], _refs=new_refs)

    def count(self) -> Dataset:
        return self._aggregate("count", None)

    def sum(self, col: str) -> Dataset:
        return self._aggregate("sum", col)

    def mean(self, col: str) -> Dataset:
        return self._aggregate("mean", col)

    def min(self, col: str) -> Dataset:
        return self._aggregate("min", col)

    def max(self, col: str) -> Dataset:
        return self._aggregate("max", col)
