"""Dataset: lazy, distributed, block-based data pipelines over a logical
query plan.

Reference surface: python/ray/data/dataset.py:203 (map/map_batches/filter/
flat_map/split/iter_batches/take/count) + the logical planning stack
(`_internal/logical/` operators and rules, `_internal/planner/planner.py`)
executed by the streaming executor
(python/ray/data/_internal/execution/streaming_executor.py:106).

TPU-first redesign instead of a port:
- a Dataset holds a LOGICAL PLAN (ray_tpu/data/_logical/operators.py) it
  never mutates: every transform stacks a node. Consumption optimizes the
  plan (rules to fixpoint: operator fusion, limit pushdown, projection and
  predicate pushdown into datasources — see _logical/rules.py), compiles
  it to streamable segments (_logical/planner.py), and executes ONE fused
  remote task per source block, in parallel across the cluster.
- limit semantics come from the planner, not special cases: a per-block
  cap fuses into the task chain, the global cut is stream-order, a
  row-count-changing op after `limit(n)` lands behind a fence segment (it
  never observes rows beyond the budget — ADVICE r5 #1), and a limited
  plan executes only the covering producer prefix.
- `count()`/`schema()`/`num_blocks()` are answered from parquet footers /
  range arithmetic with zero data blocks read when the plan shape allows.
- blocks are columnar dict-of-numpy (block.py), the layout `iter_batches`
  feeds straight to `jax.device_put` for host→device prefetch.
- `split()` hands disjoint block sets to SPMD train workers (the
  split-per-worker iterator of the reference's streaming_split).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.data.block import (
    Block,
    block_concat,
    block_filter_expr,
    block_num_rows,
    block_rows,
    block_select_columns,
    block_slice,
    normalize_batch,
    rows_to_block,
)

# one fused op: (kind, payload) — see _logical/operators.py FusedOp
_Op = Tuple[str, Any]


def _apply_ops(block: Block, ops: List[_Op]) -> Block:
    for kind, fn in ops:
        if kind == "map_batches":
            block = fn(normalize_batch(block))
        elif kind == "map":
            block = rows_to_block([fn(r) for r in block_rows(block)])
        elif kind == "filter":
            block = rows_to_block([r for r in block_rows(block) if fn(r)])
        elif kind == "flat_map":
            out: List[Any] = []
            for r in block_rows(block):
                out.extend(fn(r))
            block = rows_to_block(out)
        elif kind == "project":
            block = block_select_columns(block, fn)
        elif kind == "filter_expr":
            block = block_filter_expr(block, fn)
        elif kind == "limit":
            # the per-block cap limit pushdown fuses into the chain; the
            # GLOBAL stream-order cut happens where blocks surface
            if block_num_rows(block) > fn:
                block = block_slice(block, 0, fn)
        else:  # pragma: no cover — plan construction guards kinds
            raise ValueError(f"unknown op {kind}")
    return block


def _run_chain(producer_or_block, ops: List[_Op]) -> Block:
    """The per-block fused task body: produce (or receive) the source block,
    then apply the whole op chain."""
    block = producer_or_block() if callable(producer_or_block) else producer_or_block
    from ray_tpu._private.core_worker import ObjectRef

    if isinstance(block, ObjectRef):
        # closure-captured ref (union over materialized blocks): resolve
        # in-task — only top-level args resolve automatically
        import ray_tpu

        block = ray_tpu.get(block, timeout=600)
    return _apply_ops(block, ops)


class _CallableWrapper:
    """Adapts a plain function to the actor-pool UDF-class protocol."""

    def __init__(self, fn):
        self._fn = fn

    def __call__(self, batch):
        return self._fn(batch)

    @staticmethod
    def of(fn):
        import functools

        return functools.partial(_CallableWrapper, fn)


class Dataset:
    """A lazy distributed collection of blocks, described by a logical
    plan. All transforms are lazy (they stack plan nodes); consumption
    optimizes + compiles the plan and triggers one fused remote task per
    block. All-to-all ops (sort/shuffle/groupby/join/zip) execute when
    called, through the same planner node executors.
    """

    def __init__(self, producers: Optional[List[Any]] = None, *,
                 _refs: Optional[List[Any]] = None,
                 _plan=None):
        from ray_tpu.data._logical import operators as lops

        if _plan is not None:
            plan = _plan
        elif _refs is not None:
            plan = lops.InputBlocks(list(_refs))
        else:
            from ray_tpu.data.datasource import SimpleDatasource

            plan = lops.Read(SimpleDatasource(list(producers or [])))
        self._plan = plan
        self._refs = list(_refs) if _refs is not None else None
        self._last_stats = None
        self._opt_cache = None  # (plan identity, optimized, fired)
        self._agg_refs: Dict[str, List[Any]] = {}

    # -- plan plumbing --------------------------------------------------

    @classmethod
    def _from_plan(cls, plan) -> "Dataset":
        return cls(_plan=plan)

    @classmethod
    def _from_datasource(cls, datasource) -> "Dataset":
        from ray_tpu.data._logical import operators as lops

        return cls(_plan=lops.Read(datasource))

    @classmethod
    def _from_refs(cls, refs: List[Any]) -> "Dataset":
        return cls(_refs=list(refs))

    def _plan_for_child(self):
        """Derived datasets build on the materialized blocks once this one
        executed (repeat consumption of a shared prefix is free)."""
        from ray_tpu.data._logical import operators as lops

        if self._refs is not None:
            return lops.InputBlocks(self._refs)
        return self._plan

    def _optimizer_enabled(self) -> bool:
        from ray_tpu.data.context import DataContext

        return DataContext.get_current().optimizer_enabled

    def _optimized(self):
        """(optimized plan, fired-rule log) — cached per logical plan."""
        if not self._optimizer_enabled():
            return self._plan, []
        if self._opt_cache is None or self._opt_cache[0] is not self._plan:
            from ray_tpu.data._logical.optimizer import optimize

            opt, fired = optimize(self._plan)
            self._opt_cache = (self._plan, opt, fired)
        return self._opt_cache[1], self._opt_cache[2]

    # -- transforms (lazy) ---------------------------------------------

    def map_batches(self, fn: Any, *, columns: Optional[List[str]] = None,
                    concurrency: Optional[int] = None,
                    fn_constructor_args: tuple = (),
                    fn_constructor_kwargs: Optional[dict] = None) -> "Dataset":
        """Apply fn to whole blocks in columnar {col: ndarray} form.

        `columns=` declares the column subset the UDF needs — a Project
        node the optimizer folds into `read_parquet(columns=)` / `read_sql`
        column lists (projection pushdown), so dropped columns are never
        materialized.

        A CLASS (or any callable with `concurrency=`) becomes a stateful
        actor-pool stage: `concurrency` actors each construct the UDF once
        (fn_constructor_args) and stream blocks through it — the reference's
        ActorPoolMapOperator, for UDFs with expensive setup (model weights,
        tokenizers). `concurrency=(min, max)` enables queue-driven actor
        AUTOSCALING in the streaming executor (reference:
        actor_pool_map_operator.py + actor_autoscaler)."""
        from ray_tpu.data._logical import operators as lops

        plan = self._plan_for_child()
        if columns is not None:
            plan = lops.Project(plan, list(columns))
        if concurrency is not None or isinstance(fn, type):
            udf = fn if isinstance(fn, type) else _CallableWrapper.of(fn)
            if isinstance(concurrency, tuple):
                conc: Any = (int(concurrency[0]), int(concurrency[1]))
            else:
                conc = int(concurrency or 1)
            return Dataset._from_plan(lops.ActorPoolMap(
                plan, udf, tuple(fn_constructor_args),
                dict(fn_constructor_kwargs or {}), conc))
        return Dataset._from_plan(lops.MapBatches(plan, fn))

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        from ray_tpu.data._logical import operators as lops

        return Dataset._from_plan(lops.MapRows(self._plan_for_child(), fn))

    def filter(self, fn: Optional[Callable[[Any], bool]] = None, *,
               expr=None) -> "Dataset":
        """Keep rows where fn(row) is true — or where a STRUCTURED column
        predicate holds: `expr=("col", ">=", 5)` (or a list of such tuples,
        AND semantics; the pyarrow `filters=` shape). Only the structured
        form is visible to predicate pushdown: over `read_parquet` it
        reaches the reader's `filters=` and prunes row groups at the IO
        layer."""
        from ray_tpu.data._logical import operators as lops

        if expr is not None:
            if fn is not None:
                raise ValueError("filter takes fn OR expr, not both")
            return Dataset._from_plan(lops.Filter(
                self._plan_for_child(),
                expr=lops.normalize_filter_expr(expr)))
        if fn is None:
            raise ValueError("filter needs a callable or expr=")
        return Dataset._from_plan(lops.Filter(self._plan_for_child(), fn=fn))

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        from ray_tpu.data._logical import operators as lops

        return Dataset._from_plan(lops.FlatMap(self._plan_for_child(), fn))

    def select_columns(self, columns: List[str]) -> "Dataset":
        """Project to a column subset (reference: Dataset.select_columns).
        Folds into column-capable datasources via projection pushdown."""
        from ray_tpu.data._logical import operators as lops

        return Dataset._from_plan(
            lops.Project(self._plan_for_child(), list(columns)))

    def limit(self, n: int) -> "Dataset":
        """Truncate to the first `n` rows (reference: Dataset.limit + the
        logical optimizer's limit pushdown). The planner compiles this to
        (a) a per-block cap fused into the task chain, (b) a global
        stream-order cut wherever blocks surface, and (c) covering-prefix
        execution — `limit(k)` over B blocks submits only the producer
        prefix whose rows cover k. A row-count-changing op chained after
        limit() lands behind a stream-order fence, so it never observes
        rows beyond the global budget."""
        from ray_tpu.data._logical import operators as lops

        if n < 0:
            raise ValueError("limit must be >= 0")
        return Dataset._from_plan(lops.Limit(self._plan_for_child(), n))

    def union(self, *others: "Dataset") -> "Dataset":
        """Concatenate datasets at the PLAN level: each branch's producers
        (with their pending chains baked into closures) join one producer
        list — no materialization, no driver row round-trip."""
        from ray_tpu.data._logical import operators as lops

        return Dataset._from_plan(lops.Union(
            self._plan_for_child(),
            *[ds._plan_for_child() for ds in others]))

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance rows into `num_blocks` equal blocks (lazy plan node;
        executes on consumption). Each output task receives only the input
        blocks overlapping its row range — O(N) total movement, not
        all-blocks-to-every-task."""
        from ray_tpu.data._logical import operators as lops

        return Dataset._from_plan(
            lops.Repartition(self._plan_for_child(), int(num_blocks)))

    # -- execution ------------------------------------------------------

    def materialize(self) -> "Dataset":
        """Execute the plan (optimize → compile → one fused remote task
        per block; actor stages route through their pools). Returns a
        Dataset backed by block ObjectRefs (repeat consumption is free)."""
        if self._refs is not None:
            return self
        refs = self._block_refs()
        return Dataset(_refs=refs)

    def _block_refs(self) -> List[Any]:
        # cache the materialization on THIS dataset too: repeated consumers
        # (sum then mean then std; schema after count) must not re-execute
        # the whole plan per call
        if self._refs is None:
            from ray_tpu.data._logical import planner

            plan, _fired = self._optimized()
            refs, stats = planner.execute_to_refs(planner.compile_plan(plan))
            self._refs = refs
            self._last_stats = stats
        return self._refs

    def iter_blocks(self, *, window: Optional[int] = None) -> Iterator[Block]:
        """STREAMING consumption: pull blocks through the plan under the
        v2 streaming executor (per-stage dispatch, per-op byte budgets,
        actor autoscaling — see ray_tpu.data._executor). Materialized
        datasets iterate their cached refs.

        Streaming deliberately does NOT cache results: repeat consumption
        re-executes the plan (and re-creates actor pools). Call
        materialize() first to pin block refs for repeated reads — the
        aggregate/sort/shuffle paths do so internally via _block_refs."""
        import ray_tpu

        if self._refs is not None:
            for ref in self._refs:
                yield ray_tpu.get(ref, timeout=600)
            return
        if window is None:
            from ray_tpu.data.context import DataContext

            window = DataContext.get_current().streaming_block_window
        from ray_tpu.data._logical import planner

        plan, _fired = self._optimized()
        segments = planner.compile_plan(plan)
        holder: dict = {}
        try:
            yield from planner.iter_plan(segments, window=window,
                                         holder=holder)
        finally:
            self._last_stats = holder.get("stats") or self._last_stats

    # -- consumption ----------------------------------------------------

    def num_blocks(self) -> int:
        if self._refs is not None:
            return len(self._refs)
        from ray_tpu.data._logical import planner

        n = planner.resolve_num_blocks(self._plan)
        if n is not None:
            return n
        return len(self._block_refs())

    def count(self) -> int:
        """Row count. When the (optimized) plan supports it — parquet
        footers, range/from_items arithmetic, row-preserving chains — the
        answer comes from METADATA with zero data blocks read; the
        recorded stats show no tasks ran."""
        from ray_tpu.data._logical import planner

        if self._refs is None and self._optimizer_enabled():
            plan, _fired = self._optimized()
            n = planner.resolve_count(plan)
            if n is not None:
                self._last_stats = planner.record_metadata_stats(
                    "", "count", f"{n} rows, zero blocks read")
                return n
        refs = self._block_refs()
        return sum(planner._row_counts(refs))

    def explain(self) -> str:
        """The planner's full story: the logical plan this dataset built,
        the optimizer rules that fired (fusion, limit/projection/predicate
        pushdown), and the compiled physical stages. One "tasks[...]" line
        = ONE fused remote task per block; a "limit[stream-order fence: n
        rows]" line marks a fence (ops below it only ever see rows within
        the global budget)."""
        from ray_tpu.data._logical import operators as lops
        from ray_tpu.data._logical import planner

        lines = ["Logical plan:"]
        lines += ["  " + s for s in lops.render_tree(self._plan)]
        if self._optimizer_enabled():
            plan, fired = self._optimized()
            lines.append("Rules fired:")
            lines += [f"  - {f}" for f in fired] or ["  (none)"]
        else:
            plan = self._plan
            lines.append("Rules fired:")
            lines.append("  (optimizer disabled)")
        lines.append("Physical plan:")
        lines += planner.describe_segments(
            planner.compile_plan(plan, allow_execute=False))
        return "\n".join(lines)

    def take(self, limit: int = 20) -> List[Any]:
        out: List[Any] = []
        for block in self.iter_blocks():
            for row in block_rows(block):
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Any]:
        return self.take(limit=2**62)

    def iter_rows(self) -> Iterator[Any]:
        for block in self.iter_blocks():
            yield from block_rows(block)

    def iter_batches(
        self,
        batch_size: Optional[int] = 256,
        *,
        drop_last: bool = False,
        device_put: bool = False,
        prefetch_blocks: int = 2,
    ) -> Iterator[Block]:
        """Iterate fixed-size columnar batches across block boundaries.

        device_put=True moves each numpy batch onto the default JAX device
        before yielding — host→device transfer overlaps the consumer's step
        (the reference's iter_torch_batches prefetch, TPU-flavored).

        Unmaterialized datasets STREAM: at most `prefetch_blocks` source
        blocks are in flight and consumed blocks free their shm copies
        before more are admitted, so datasets larger than the object store
        iterate in constant memory.
        """
        carry: Optional[Block] = None

        def to_out(b: Block) -> Block:
            if device_put and isinstance(b, dict):
                import jax

                return {k: jax.device_put(v) for k, v in b.items()}
            return b

        for block in self.iter_blocks(
                window=None if prefetch_blocks is None
                else max(1, prefetch_blocks)):
            carry = block if carry is None else block_concat([carry, block])
            if batch_size is None:
                yield to_out(carry)
                carry = None
                continue
            while block_num_rows(carry) >= batch_size:
                yield to_out(block_slice(carry, 0, batch_size))
                carry = block_slice(carry, batch_size, block_num_rows(carry))
        if carry is not None and block_num_rows(carry) > 0 and not drop_last:
            yield to_out(carry)

    # -- reorganization -------------------------------------------------

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Split into n datasets over disjoint blocks (per-train-worker
        shards; reference: Dataset.split / streaming_split). equal=True
        repartitions first so every shard has the same row count (±1), which
        SPMD training needs for lockstep batches."""
        if equal:
            refs = self.repartition(n)._block_refs()
            return [Dataset(_refs=[r]) for r in refs]
        refs = self._block_refs()
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [Dataset(_refs=s) for s in shards]

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global random shuffle (materializes). Two-stage push shuffle as
        in the reference's shuffle ops — O(N) total movement; executed by
        the planner's RandomShuffle node."""
        from ray_tpu.data._logical import operators as lops
        from ray_tpu.data._logical import planner

        node = lops.RandomShuffle(lops.InputBlocks(self._block_refs()), seed)
        return Dataset._from_refs(planner.execute_node(node))

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Distributed sort (materializes): sample key range →
        range-partition scatter → per-partition sort (reference: data sort
        ops; the classic TeraSort shape) — the planner's Sort node."""
        from ray_tpu.data._logical import operators as lops
        from ray_tpu.data._logical import planner

        node = lops.Sort(lops.InputBlocks(self._block_refs()), key,
                         descending)
        return Dataset._from_refs(planner.execute_node(node))

    def groupby(self, key: str) -> "GroupedData":
        """Group rows by a key column (reference: Dataset.groupby +
        hash-shuffle aggregate ops)."""
        return GroupedData(self, key)

    # -- multi-dataset ops (reference: Dataset.union/zip/join) ----------

    def zip(self, other: "Dataset") -> "Dataset":
        """Column-wise zip of two datasets with equal row counts
        (reference: Dataset.zip): the other dataset is range-repartitioned
        to this one's block boundaries, then each aligned pair merges
        columns in one task (duplicate names get a _1 suffix). Validates
        row counts up front (materializes both sides)."""
        from ray_tpu.data._logical import operators as lops
        from ray_tpu.data._logical import planner

        node = lops.Zip(lops.InputBlocks(self._block_refs()),
                        lops.InputBlocks(other._block_refs()))
        return Dataset._from_refs(planner.execute_node(node))

    def join(self, other: "Dataset", on: str, *, how: str = "inner",
             num_partitions: Optional[int] = None) -> "Dataset":
        """Distributed hash join on column `on` (reference: the data join
        operator / hash_shuffle) — the planner's Join node: both sides
        scatter rows by hash(key) into k partitions, then one task per
        partition builds-and-probes — O(N) movement, k-way parallel."""
        if how not in ("inner", "left"):
            raise ValueError(f"unsupported join type {how!r}")
        from ray_tpu.data._logical import operators as lops
        from ray_tpu.data._logical import planner

        node = lops.Join(lops.InputBlocks(self._block_refs()),
                         lops.InputBlocks(other._block_refs()),
                         on, how, num_partitions)
        return Dataset._from_refs(planner.execute_node(node))

    # -- global aggregates (reference: Dataset.sum/min/max/mean/std) ----

    def _agg_input_refs(self, col: Optional[str]) -> List[Any]:
        """Block refs feeding a single-column aggregate. On an
        unmaterialized plan over a column-capable source, a Project([col])
        is pushed through the optimizer first — the read materializes ONLY
        that column (projection pushdown for aggregates)."""
        if self._refs is not None:
            return self._refs
        if col is not None and self._optimizer_enabled():
            if col in self._agg_refs:
                return self._agg_refs[col]
            from ray_tpu.data._logical import operators as lops
            from ray_tpu.data._logical.optimizer import optimize
            from ray_tpu.data._logical import planner

            opt, _fired = optimize(lops.Project(self._plan, [col]))
            if planner.projection_folded(opt):
                refs, stats = planner.execute_to_refs(
                    planner.compile_plan(opt))
                self._agg_refs[col] = refs
                self._last_stats = stats
                return refs
        return self._block_refs()

    def _column_stats(self, col: str):
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        def _stats(block):
            v = np.asarray(block[col]) if isinstance(block, dict) else (
                np.asarray([r[col] for r in block_rows(block)])
            )
            if v.size == 0:
                # None (not 0.0) so an empty block can't masquerade as a
                # numeric contribution on a non-numeric column
                return (0, None, None, None, None)
            # String keys are legal sort()/min()/max() inputs; only numeric
            # dtypes have a sum / sum-of-squares (advisor r2).
            if np.issubdtype(v.dtype, np.number) or v.dtype == np.bool_:
                total = float(v.sum())
                sq = float((v.astype(np.float64) ** 2).sum())
                mn, mx = v.min().item(), v.max().item()
            else:
                # np.min has no ufunc loop for str/object dtypes
                total = sq = None
                vals = v.tolist()
                mn, mx = min(vals), max(vals)
            return (int(v.size), total, sq, mn, mx)

        parts = ray_tpu.get(
            [RemoteFunction(_stats).remote(r)
             for r in self._agg_input_refs(col)],
            timeout=600,
        )
        n = sum(p[0] for p in parts)
        sums = [p[1] for p in parts if p[1] is not None]
        sqs = [p[2] for p in parts if p[2] is not None]
        total = sum(sums) if sums else None
        sq = sum(sqs) if sqs else None
        mins = [p[3] for p in parts if p[3] is not None]
        maxs = [p[4] for p in parts if p[4] is not None]
        return n, total, sq, (min(mins) if mins else None), (max(maxs) if maxs else None)

    def sum(self, col: str):
        return self._column_stats(col)[1]

    def mean(self, col: str):
        n, total, *_ = self._column_stats(col)
        return total / n if (n and total is not None) else None

    def min(self, col: str):
        return self._column_stats(col)[3]

    def max(self, col: str):
        return self._column_stats(col)[4]

    def std(self, col: str, ddof: int = 1):
        n, total, sq, _, _ = self._column_stats(col)
        if n <= ddof or total is None or sq is None:
            return None
        mean = total / n
        return float(np.sqrt(max(0.0, (sq - n * mean * mean) / (n - ddof))))

    # -- introspection --------------------------------------------------

    def stats(self) -> str:
        """Per-op execution table of the most recent consumption
        (reference: python/ray/data/stats.py — blocks, bytes, task times,
        peak concurrency/queue, backpressure time per operator; the
        materialize path reports per-segment rows, metadata-answered
        queries report a zero-task metadata row)."""
        st = self._last_stats
        if st is None:
            return ("(no stats yet: stats cover plan execution — "
                    "consume the dataset first)")
        return str(st)

    def schema(self) -> Optional[Dict[str, str]]:
        """{column: dtype}. Answered from datasource METADATA (parquet
        footer schema, range arithmetic) when the plan shape allows —
        zero data blocks read."""
        import ray_tpu

        from ray_tpu.data._logical import planner

        if self._refs is None and self._optimizer_enabled():
            plan, _fired = self._optimized()
            s = planner.resolve_schema(plan)
            if s is not None:
                self._last_stats = planner.record_metadata_stats(
                    "", "schema", "zero blocks read")
                return s
        refs = self._block_refs()
        if not refs:
            return None
        block = ray_tpu.get(refs[0], timeout=600)
        if isinstance(block, dict):
            return {k: str(v.dtype) for k, v in block.items()}
        return None

    def __repr__(self):
        from ray_tpu.data._logical import planner

        nb = (len(self._refs) if self._refs is not None
              else planner.resolve_num_blocks(self._plan))
        return (f"Dataset(blocks={'?' if nb is None else nb}, "
                f"plan={self._plan.label()})")


class GroupedData:
    """Hash-partitioned group-by + aggregates (reference: data groupby with
    hash_shuffle aggregate operators), executed by the planner's GroupByAgg
    node. Keys scatter to k partitions by hash; each partition aggregates
    its groups independently."""

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _aggregate(self, agg: str, col: Optional[str]) -> Dataset:
        from ray_tpu.data._logical import operators as lops
        from ray_tpu.data._logical import planner

        if agg not in planner.GROUP_AGGS:
            raise ValueError(f"unknown aggregate {agg!r}")
        refs = self._ds._block_refs()
        if not refs:
            return Dataset(_refs=[])
        node = lops.GroupByAgg(lops.InputBlocks(refs), self._key, agg, col)
        return Dataset._from_refs(planner.execute_node(node))

    def count(self) -> Dataset:
        return self._aggregate("count", None)

    def sum(self, col: str) -> Dataset:
        return self._aggregate("sum", col)

    def mean(self, col: str) -> Dataset:
        return self._aggregate("mean", col)

    def min(self, col: str) -> Dataset:
        return self._aggregate("min", col)

    def max(self, col: str) -> Dataset:
        return self._aggregate("max", col)
