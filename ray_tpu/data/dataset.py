"""Dataset: lazy, distributed, block-based data pipelines.

Reference surface: python/ray/data/dataset.py:203 (map/map_batches/filter/
flat_map/split/iter_batches/take/count) executed by the streaming executor
(python/ray/data/_internal/execution/streaming_executor.py:106).

TPU-first redesign instead of a port:
- a Dataset is (block producers, fused op chain). Materialization submits ONE
  task per block that applies the whole chain — operator fusion is the
  default (the reference fuses map chains inside its executor; here the
  chain is literally one function), and blocks execute in parallel across
  the cluster with no central executor loop.
- blocks are columnar dict-of-numpy (block.py), the layout `iter_batches`
  feeds straight to `jax.device_put` for host→device prefetch.
- `split()` hands disjoint block sets to SPMD train workers (the
  split-per-worker iterator of the reference's streaming_split).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

from ray_tpu.data.block import (
    Block,
    block_concat,
    block_num_rows,
    block_rows,
    block_slice,
    normalize_batch,
    rows_to_block,
)

# one op: (kind, fn) where kind in {"map_batches", "map", "filter", "flat_map"}
_Op = Tuple[str, Callable]


def _apply_ops(block: Block, ops: List[_Op]) -> Block:
    for kind, fn in ops:
        if kind == "map_batches":
            block = fn(normalize_batch(block))
        elif kind == "map":
            block = rows_to_block([fn(r) for r in block_rows(block)])
        elif kind == "filter":
            block = rows_to_block([r for r in block_rows(block) if fn(r)])
        elif kind == "flat_map":
            out: List[Any] = []
            for r in block_rows(block):
                out.extend(fn(r))
            block = rows_to_block(out)
        else:  # pragma: no cover — plan construction guards kinds
            raise ValueError(f"unknown op {kind}")
    return block


def _run_chain(producer_or_block, ops: List[_Op]) -> Block:
    """The per-block fused task body: produce (or receive) the source block,
    then apply the whole op chain."""
    block = producer_or_block() if callable(producer_or_block) else producer_or_block
    return _apply_ops(block, ops)


class Dataset:
    """A lazy distributed collection of blocks.

    `_producers` are zero-arg callables (or ObjectRefs of already-computed
    blocks) each yielding one source block; `_ops` is the pending fused
    chain. All transforms are lazy; `materialize()`/consumption triggers one
    remote task per block.
    """

    def __init__(self, producers: List[Any], ops: Optional[List[_Op]] = None,
                 *, _refs: Optional[List[Any]] = None):
        self._producers = producers
        self._ops: List[_Op] = list(ops or [])
        self._refs = _refs  # cached materialized block refs

    # -- transforms (lazy) ---------------------------------------------

    def _chain(self, kind: str, fn: Callable) -> "Dataset":
        base = self._refs if self._refs is not None else self._producers
        ops = [] if self._refs is not None else self._ops
        return Dataset(list(base), ops + [(kind, fn)])

    def map_batches(self, fn: Callable[[Block], Block]) -> "Dataset":
        """Apply fn to whole blocks in columnar {col: ndarray} form."""
        return self._chain("map_batches", fn)

    def map(self, fn: Callable[[Any], Any]) -> "Dataset":
        return self._chain("map", fn)

    def filter(self, fn: Callable[[Any], bool]) -> "Dataset":
        return self._chain("filter", fn)

    def flat_map(self, fn: Callable[[Any], List[Any]]) -> "Dataset":
        return self._chain("flat_map", fn)

    # -- execution ------------------------------------------------------

    def materialize(self) -> "Dataset":
        """Execute the plan: one fused remote task per block. Returns a
        Dataset backed by block ObjectRefs (repeat consumption is free)."""
        if self._refs is not None:
            return self
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        run = RemoteFunction(_run_chain)
        ops = self._ops
        refs = []
        from ray_tpu._private.core_worker import ObjectRef

        for p in self._producers:
            if isinstance(p, ObjectRef) and not ops:
                refs.append(p)
            else:
                refs.append(run.remote(p, ops))
        return Dataset(refs, [], _refs=refs)

    def _block_refs(self) -> List[Any]:
        # cache the materialization on THIS dataset too: repeated consumers
        # (sum then mean then std; schema after count) must not re-execute
        # the whole plan per call
        refs = self.materialize()._refs
        self._refs = refs
        return refs

    # -- consumption ----------------------------------------------------

    def num_blocks(self) -> int:
        return len(self._producers)

    def count(self) -> int:
        import ray_tpu

        refs = self._block_refs()
        return sum(
            block_num_rows(b) for b in ray_tpu.get(refs, timeout=600)
        )

    def take(self, limit: int = 20) -> List[Any]:
        import ray_tpu

        out: List[Any] = []
        for ref in self._block_refs():
            block = ray_tpu.get(ref, timeout=600)
            for row in block_rows(block):
                out.append(row)
                if len(out) >= limit:
                    return out
        return out

    def take_all(self) -> List[Any]:
        return self.take(limit=2**62)

    def iter_rows(self) -> Iterator[Any]:
        import ray_tpu

        for ref in self._block_refs():
            yield from block_rows(ray_tpu.get(ref, timeout=600))

    def iter_batches(
        self,
        batch_size: Optional[int] = 256,
        *,
        drop_last: bool = False,
        device_put: bool = False,
        prefetch_blocks: int = 2,
    ) -> Iterator[Block]:
        """Iterate fixed-size columnar batches across block boundaries.

        device_put=True moves each numpy batch onto the default JAX device
        before yielding — host→device transfer overlaps the consumer's step
        (the reference's iter_torch_batches prefetch, TPU-flavored).
        """
        import ray_tpu

        # All block tasks were submitted at materialize() and compute in
        # parallel; an in-order get() therefore always has `prefetch_blocks`+
        # of work racing ahead of the consumer. (prefetch_blocks is accepted
        # for API parity; the window is effectively the whole plan.)
        del prefetch_blocks
        refs = self._block_refs()
        carry: Optional[Block] = None

        def to_out(b: Block) -> Block:
            if device_put and isinstance(b, dict):
                import jax

                return {k: jax.device_put(v) for k, v in b.items()}
            return b

        for ref in refs:
            block = ray_tpu.get(ref, timeout=600)
            carry = block if carry is None else block_concat([carry, block])
            if batch_size is None:
                yield to_out(carry)
                carry = None
                continue
            while block_num_rows(carry) >= batch_size:
                yield to_out(block_slice(carry, 0, batch_size))
                carry = block_slice(carry, batch_size, block_num_rows(carry))
        if carry is not None and block_num_rows(carry) > 0 and not drop_last:
            yield to_out(carry)

    # -- reorganization -------------------------------------------------

    def split(self, n: int, *, equal: bool = False) -> List["Dataset"]:
        """Split into n datasets over disjoint blocks (per-train-worker
        shards; reference: Dataset.split / streaming_split). equal=True
        repartitions first so every shard has the same row count (±1), which
        SPMD training needs for lockstep batches."""
        if equal:
            refs = self.repartition(n)._refs
            return [Dataset([r], [], _refs=[r]) for r in refs]
        refs = self._block_refs()
        shards: List[List[Any]] = [[] for _ in range(n)]
        for i, ref in enumerate(refs):
            shards[i % n].append(ref)
        return [Dataset(s, [], _refs=s) for s in shards]

    def _block_row_counts(self, refs: List[Any]) -> List[int]:
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        count = RemoteFunction(block_num_rows)
        return ray_tpu.get([count.remote(r) for r in refs], timeout=600)

    def repartition(self, num_blocks: int) -> "Dataset":
        """Rebalance rows into `num_blocks` equal blocks (materializes).

        Each output task receives only the input blocks overlapping its row
        range — O(N) total movement, not all-blocks-to-every-task."""
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        refs = self._block_refs()
        counts = self._block_row_counts(refs)
        starts = list(np.cumsum([0] + counts))  # global start offset per block
        total = starts[-1]

        def _slice_rows(lo: int, hi: int, block_starts, *blocks):
            parts = []
            for s, b in zip(block_starts, blocks):
                n = block_num_rows(b)
                a, z = max(lo, s), min(hi, s + n)
                if z > a:
                    parts.append(block_slice(b, a - s, z - s))
            return block_concat(parts) if parts else rows_to_block([])

        run = RemoteFunction(_slice_rows)
        new_refs = []
        for i in range(num_blocks):
            lo, hi = (total * i) // num_blocks, (total * (i + 1)) // num_blocks
            overlap = [
                j for j in range(len(refs))
                if starts[j] < hi and starts[j] + counts[j] > lo
            ]
            new_refs.append(run.remote(
                lo, hi, [starts[j] for j in overlap], *[refs[j] for j in overlap]
            ))
        return Dataset(new_refs, [], _refs=new_refs)

    def random_shuffle(self, *, seed: Optional[int] = None) -> "Dataset":
        """Global random shuffle (materializes). Two-stage push shuffle as in
        the reference's shuffle ops: each input block scatters its rows into
        k partitions (one task, k returns); each output concatenates and
        permutes its k incoming parts — O(N) total movement."""
        from ray_tpu.remote_function import RemoteFunction

        refs = self._block_refs()
        k = len(refs)
        if k <= 1:
            return Dataset(list(refs), [], _refs=list(refs))

        def _scatter(sd, j: int, k: int, block):
            rng = np.random.default_rng(None if sd is None else sd * 1_000_003 + j)
            n = block_num_rows(block)
            assign = rng.integers(0, k, size=n)
            if isinstance(block, dict):
                return tuple(
                    {c: v[assign == i] for c, v in block.items()} for i in range(k)
                )
            items = list(block)
            return tuple(
                [items[t] for t in np.flatnonzero(assign == i)] for i in range(k)
            )

        def _merge(sd, i: int, *parts):
            whole = block_concat(list(parts))
            rng = np.random.default_rng(None if sd is None else sd * 7_000_003 + i)
            n = block_num_rows(whole)
            perm = rng.permutation(n)
            if isinstance(whole, dict):
                return {c: v[perm] for c, v in whole.items()}
            return [whole[j] for j in perm]

        scatter = RemoteFunction(_scatter).options(num_returns=k)
        merge = RemoteFunction(_merge)
        partitions = [scatter.remote(seed, j, k, refs[j]) for j in range(k)]
        new_refs = [
            merge.remote(seed, i, *[partitions[j][i] for j in range(k)])
            for i in range(k)
        ]
        return Dataset(new_refs, [], _refs=new_refs)

    def sort(self, key: str, *, descending: bool = False) -> "Dataset":
        """Distributed sort (materializes): sample key range → range-partition
        scatter → per-partition sort (reference: data sort ops; the classic
        TeraSort shape, O(N) movement + parallel partition sorts)."""
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        refs = self._block_refs()
        k = len(refs)
        if k == 0:
            return Dataset([], [], _refs=[])

        def _sample(block):
            col = np.asarray(block[key]) if isinstance(block, dict) else (
                np.asarray([r[key] for r in block_rows(block)])
            )
            if col.size == 0:
                return col
            take = min(64, col.size)
            idx = np.random.default_rng(0).choice(col.size, take, replace=False)
            return col[idx]

        samples = np.concatenate([
            s for s in ray_tpu.get(
                [RemoteFunction(_sample).remote(r) for r in refs], timeout=600)
            if s.size
        ]) if k else np.array([])
        if samples.size == 0 or k == 1:
            def _sort_one(block):
                return _sort_block(block, key, descending)

            new_refs = [RemoteFunction(_sort_one).remote(r) for r in refs]
            return Dataset(new_refs, [], _refs=new_refs)
        # positional quantiles, not np.quantile: sort keys may be strings
        # (any sortable dtype) and only order matters for range bounds
        srt = np.sort(samples)
        bounds = srt[[
            min(srt.size - 1, max(0, (srt.size * i) // k)) for i in range(1, k)
        ]]

        def _scatter(block, bounds):
            col = np.asarray(block[key]) if isinstance(block, dict) else (
                np.asarray([r[key] for r in block_rows(block)])
            )
            assign = np.searchsorted(bounds, col, side="right")
            n_parts = len(bounds) + 1
            if isinstance(block, dict):
                return tuple(
                    {c: np.asarray(v)[assign == i] for c, v in block.items()}
                    for i in range(n_parts)
                )
            items = list(block)
            return tuple(
                [items[t] for t in np.flatnonzero(assign == i)]
                for i in range(n_parts)
            )

        def _merge_sort(*parts):
            return _sort_block(block_concat(list(parts)), key, descending)

        scatter = RemoteFunction(_scatter).options(num_returns=k)
        partitions = [scatter.remote(r, bounds) for r in refs]
        order = range(k - 1, -1, -1) if descending else range(k)
        new_refs = [
            RemoteFunction(_merge_sort).remote(*[partitions[j][i] for j in range(k)])
            for i in order
        ]
        return Dataset(new_refs, [], _refs=new_refs)

    def groupby(self, key: str) -> "GroupedData":
        """Group rows by a key column (reference: Dataset.groupby +
        hash-shuffle aggregate ops)."""
        return GroupedData(self, key)

    # -- global aggregates (reference: Dataset.sum/min/max/mean/std) ----

    def _column_stats(self, col: str):
        import ray_tpu
        from ray_tpu.remote_function import RemoteFunction

        def _stats(block):
            v = np.asarray(block[col]) if isinstance(block, dict) else (
                np.asarray([r[col] for r in block_rows(block)])
            )
            if v.size == 0:
                # None (not 0.0) so an empty block can't masquerade as a
                # numeric contribution on a non-numeric column
                return (0, None, None, None, None)
            # String keys are legal sort()/min()/max() inputs; only numeric
            # dtypes have a sum / sum-of-squares (advisor r2).
            if np.issubdtype(v.dtype, np.number) or v.dtype == np.bool_:
                total = float(v.sum())
                sq = float((v.astype(np.float64) ** 2).sum())
                mn, mx = v.min().item(), v.max().item()
            else:
                # np.min has no ufunc loop for str/object dtypes
                total = sq = None
                vals = v.tolist()
                mn, mx = min(vals), max(vals)
            return (int(v.size), total, sq, mn, mx)

        parts = ray_tpu.get(
            [RemoteFunction(_stats).remote(r) for r in self._block_refs()],
            timeout=600,
        )
        n = sum(p[0] for p in parts)
        sums = [p[1] for p in parts if p[1] is not None]
        sqs = [p[2] for p in parts if p[2] is not None]
        total = sum(sums) if sums else None
        sq = sum(sqs) if sqs else None
        mins = [p[3] for p in parts if p[3] is not None]
        maxs = [p[4] for p in parts if p[4] is not None]
        return n, total, sq, (min(mins) if mins else None), (max(maxs) if maxs else None)

    def sum(self, col: str):
        return self._column_stats(col)[1]

    def mean(self, col: str):
        n, total, *_ = self._column_stats(col)
        return total / n if (n and total is not None) else None

    def min(self, col: str):
        return self._column_stats(col)[3]

    def max(self, col: str):
        return self._column_stats(col)[4]

    def std(self, col: str, ddof: int = 1):
        n, total, sq, _, _ = self._column_stats(col)
        if n <= ddof or total is None or sq is None:
            return None
        mean = total / n
        return float(np.sqrt(max(0.0, (sq - n * mean * mean) / (n - ddof))))

    # -- introspection --------------------------------------------------

    def schema(self) -> Optional[Dict[str, str]]:
        import ray_tpu

        refs = self._block_refs()
        if not refs:
            return None
        block = ray_tpu.get(refs[0], timeout=600)
        if isinstance(block, dict):
            return {k: str(v.dtype) for k, v in block.items()}
        return None

    def __repr__(self):
        ops = "->".join(k for k, _ in self._ops) or "source"
        return f"Dataset(blocks={len(self._producers)}, plan={ops})"


def _sort_block(block: Block, key: str, descending: bool) -> Block:
    if isinstance(block, dict):
        col = np.asarray(block[key])
        order = np.argsort(col, kind="stable")
        if descending:
            order = order[::-1]
        return {c: np.asarray(v)[order] for c, v in block.items()}
    rows = sorted(block_rows(block), key=lambda r: r[key], reverse=descending)
    return rows_to_block(rows)


class GroupedData:
    """Hash-partitioned group-by + aggregates (reference: data groupby with
    hash_shuffle aggregate operators). Keys scatter to k partitions by hash;
    each partition aggregates its groups independently."""

    # per-group leaf computed inside one partition: hash partitioning puts
    # ALL rows of a group in the same partition, so no cross-partition
    # combine is needed — mean included
    _AGGS = {
        "count": len,
        "sum": lambda vals: np.sum(vals).item(),
        "min": lambda vals: np.min(vals).item(),
        "max": lambda vals: np.max(vals).item(),
        "mean": lambda vals: float(np.mean(vals)),
    }

    def __init__(self, ds: Dataset, key: str):
        self._ds = ds
        self._key = key

    def _aggregate(self, agg: str, col: Optional[str]) -> Dataset:
        from ray_tpu.remote_function import RemoteFunction

        if agg not in self._AGGS:
            raise ValueError(f"unknown aggregate {agg!r}")
        key = self._key
        refs = self._ds._block_refs()
        if not refs:
            return Dataset([], [], _refs=[])
        k = len(refs)

        def _scatter(block, k):
            import hashlib as _hl

            def stable(x) -> int:
                # NOT hash(): str hashing is per-process randomized, which
                # would scatter equal keys to different partitions
                x = x.item() if hasattr(x, "item") else x
                d = _hl.blake2b(repr(x).encode(), digest_size=8).digest()
                return int.from_bytes(d, "little")

            keys = (np.asarray(block[key]) if isinstance(block, dict)
                    else np.asarray([r[key] for r in block_rows(block)]))
            assign = np.asarray([stable(x) % k for x in keys.tolist()])
            if isinstance(block, dict):
                return tuple(
                    {c: np.asarray(v)[assign == i] for c, v in block.items()}
                    for i in range(k)
                )
            items = list(block)
            return tuple(
                [items[t] for t in np.flatnonzero(assign == i)]
                for i in range(k)
            )

        def _agg_partition(agg, col, *parts):
            whole = block_concat(list(parts))
            groups: Dict[Any, list] = {}
            for r in block_rows(whole):
                groups.setdefault(r[key], []).append(
                    r[col] if col is not None else 1
                )
            leaf = GroupedData._AGGS[agg]
            out_name = f"{agg}({col})" if col else "count()"
            return rows_to_block([
                {key: gk, out_name: leaf(vals)} for gk, vals in groups.items()
            ])

        agg_fn = RemoteFunction(_agg_partition)
        if k == 1:
            # num_returns=1 .remote() yields a bare ref; no scatter needed
            new_refs = [agg_fn.remote(agg, col, refs[0])]
        else:
            scatter = RemoteFunction(_scatter).options(num_returns=k)
            partitions = [scatter.remote(r, k) for r in refs]
            new_refs = [
                agg_fn.remote(agg, col, *[partitions[j][i] for j in range(k)])
                for i in range(k)
            ]
        return Dataset(new_refs, [], _refs=new_refs)

    def count(self) -> Dataset:
        return self._aggregate("count", None)

    def sum(self, col: str) -> Dataset:
        return self._aggregate("sum", col)

    def mean(self, col: str) -> Dataset:
        return self._aggregate("mean", col)

    def min(self, col: str) -> Dataset:
        return self._aggregate("min", col)

    def max(self, col: str) -> Dataset:
        return self._aggregate("max", col)
