"""Block representation and batch slicing for ray_tpu.data.

Reference: python/ray/data/block.py (Arrow/pandas/simple blocks). TPU-first
redesign: the native block is **columnar dict-of-numpy** — the layout
`iter_batches` can feed straight into `jax.device_put` without conversion —
with a plain row-list fallback for non-tabular data.
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Union

import numpy as np

Block = Union[Dict[str, np.ndarray], List[Any]]


def block_num_rows(block: Block) -> int:
    if isinstance(block, dict):
        if not block:
            return 0
        return len(next(iter(block.values())))
    return len(block)


def block_slice(block: Block, start: int, stop: int) -> Block:
    if isinstance(block, dict):
        return {k: v[start:stop] for k, v in block.items()}
    return block[start:stop]


def block_concat(blocks: List[Block]) -> Block:
    blocks = [b for b in blocks if block_num_rows(b) > 0]
    if not blocks:
        return []
    if isinstance(blocks[0], dict):
        keys = blocks[0].keys()
        return {k: np.concatenate([b[k] for b in blocks]) for k in keys}
    out: List[Any] = []
    for b in blocks:
        out.extend(b)
    return out


def block_rows(block: Block) -> Iterator[Any]:
    """Iterate rows: dict blocks yield per-row dicts, list blocks yield items."""
    if isinstance(block, dict):
        n = block_num_rows(block)
        keys = list(block.keys())
        for i in range(n):
            yield {k: block[k][i] for k in keys}
    else:
        yield from block


def rows_to_block(rows: List[Any]) -> Block:
    """Columnarize a row list when all rows are flat dicts of scalars/arrays
    of matching shape; otherwise keep the row list."""
    if not rows:
        return []
    if all(isinstance(r, dict) for r in rows):
        keys = rows[0].keys()
        if all(r.keys() == keys for r in rows):
            try:
                return {k: np.stack([np.asarray(r[k]) for r in rows]) for k in keys}
            except (ValueError, TypeError):
                pass  # ragged: fall through to row list
    return list(rows)


def normalize_batch(block: Block) -> Block:
    """What a map_batches UDF receives: columnar dicts stay columnar; row
    lists of uniform dicts are columnarized; other rows stay a list."""
    if isinstance(block, dict):
        return block
    return rows_to_block(block)


def block_select_columns(block: Block, columns: List[str]) -> Block:
    """Project a block to a column subset (the Project logical op's task
    body). Missing columns raise KeyError — same surface the downstream
    UDF would have hit."""
    if isinstance(block, dict):
        return {c: block[c] for c in columns}
    return [{c: r[c] for c in columns} for r in block]


def _column_mask(col: np.ndarray, op: str, value) -> np.ndarray:
    if op in ("==", "="):
        return col == value
    if op == "!=":
        return col != value
    if op == "<":
        return col < value
    if op == "<=":
        return col <= value
    if op == ">":
        return col > value
    if op == ">=":
        return col >= value
    if op == "in":
        return np.isin(col, list(value))
    if op == "not in":
        return ~np.isin(col, list(value))
    raise ValueError(f"unknown predicate op {op!r}")


def block_filter_expr(block: Block, exprs) -> Block:
    """Apply a conjunction of (column, op, value) predicates — the same
    tuple shape pyarrow's parquet `filters=` takes, so a predicate that
    could not push into the reader evaluates identically here, vectorized
    over columnar blocks."""
    if isinstance(block, dict):
        n = block_num_rows(block)
        mask = np.ones(n, dtype=bool)
        for col, op, value in exprs:
            mask &= np.asarray(_column_mask(np.asarray(block[col]), op, value))
        return {c: np.asarray(v)[mask] for c, v in block.items()}

    def keep(row) -> bool:
        for col, op, value in exprs:
            if not bool(_column_mask(np.asarray([row[col]]), op, value)[0]):
                return False
        return True

    return [r for r in block if keep(r)]
