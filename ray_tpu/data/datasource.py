"""Datasource constructors for ray_tpu.data.

Reference: python/ray/data/read_api.py (range, from_items, read_parquet,
read_csv, read_json, read_binary_files, read_images) and
data/datasource/*_datasource.py. Each reader builds a Dataset whose logical
plan is a single `Read` leaf over a Datasource object; file IO happens on
cluster workers, one fused task per block.

Datasources are the PUSHDOWN surface of the query planner
(ray_tpu/data/_logical): a column-capable source accepts `with_columns`
(projection pushdown → `read_parquet(columns=)`, `read_sql` column lists),
a predicate-capable one accepts `with_filters` (pyarrow parquet
`filters=`), and metadata-capable ones answer `count_rows`/`schema` from
parquet footers or range arithmetic so `count()`/`schema()` read zero data
blocks.
"""

from __future__ import annotations

import builtins
import functools
import glob as glob_mod
import os
from typing import Any, List, Optional, Sequence, Union

import numpy as np

from ray_tpu.data.dataset import Dataset


# ---------------------------------------------------------------------------
# datasource objects (the Read leaf's payload)
# ---------------------------------------------------------------------------


class Datasource:
    """Base datasource: a list of block producers plus optional metadata
    and pushdown hooks the optimizer rules drive."""

    supports_column_pushdown = False
    supports_predicate_pushdown = False
    columns: Optional[List[str]] = None
    filters: Optional[List[tuple]] = None

    def producers(self) -> List[Any]:
        raise NotImplementedError

    def num_blocks(self) -> Optional[int]:
        return len(self.producers())

    def count_rows(self) -> Optional[int]:
        """Exact row count from metadata only, or None (must execute)."""
        return None

    def schema(self) -> Optional[dict]:
        """{column: numpy-dtype-str} from metadata only, or None."""
        return None

    def with_columns(self, columns: List[str]) -> "Datasource":
        raise NotImplementedError(f"{type(self).__name__} cannot push columns")

    def with_filters(self, exprs: List[tuple]) -> "Datasource":
        raise NotImplementedError(
            f"{type(self).__name__} cannot push predicates")

    def describe(self) -> str:
        return type(self).__name__


class SimpleDatasource(Datasource):
    """A plain producer list (from_items / raw Dataset(producers) /
    readers without pushdown). `num_rows` is optional arithmetic metadata
    (from_items knows its length)."""

    def __init__(self, items: List[Any], *, num_rows: Optional[int] = None,
                 known_schema: Optional[dict] = None, label: str = "blocks"):
        self._items = list(items)
        self._num_rows = num_rows
        self._schema = known_schema
        self._label = label

    def producers(self) -> List[Any]:
        return list(self._items)

    def num_blocks(self) -> int:
        return len(self._items)

    def count_rows(self) -> Optional[int]:
        return self._num_rows

    def schema(self) -> Optional[dict]:
        return dict(self._schema) if self._schema else None

    def describe(self) -> str:
        return f"{self._label}: {len(self._items)} blocks"


class RangeDatasource(Datasource):
    """ray.data.range — all metadata is arithmetic: count, schema, and
    (with limit pushdown) even the covering block prefix need zero tasks."""

    def __init__(self, n: int, parallelism: int):
        self.n = int(n)
        self.parallelism = parallelism

    def producers(self) -> List[Any]:
        return [
            functools.partial(_range_block, lo, hi)
            for lo, hi in _chunk_bounds(self.n, self.parallelism)
        ]

    def num_blocks(self) -> int:
        return self.parallelism

    def count_rows(self) -> int:
        return self.n

    def schema(self) -> dict:
        return {"id": "int64"}

    def describe(self) -> str:
        return f"range({self.n}) x{self.parallelism}"


class ParquetDatasource(Datasource):
    """One block per parquet file. Projection pushdown narrows `columns`,
    predicate pushdown supplies pyarrow `filters=` (row-group pruning at
    the IO layer), and count/schema come from file FOOTERS."""

    supports_column_pushdown = True
    supports_predicate_pushdown = True

    def __init__(self, files: List[str], columns: Optional[List[str]] = None,
                 filters: Optional[List[tuple]] = None):
        self.files = list(files)
        self.columns = list(columns) if columns is not None else None
        self.filters = list(filters) if filters is not None else None
        # footer reads are serial driver IO; the instance is immutable so
        # repeat count()/schema()/explain() calls reuse the first answer
        self._count_cache: Optional[int] = None
        self._schema_cache: Optional[dict] = None

    def producers(self) -> List[Any]:
        return [
            functools.partial(_read_parquet_file, f, self.columns,
                              self.filters)
            for f in self.files
        ]

    def num_blocks(self) -> int:
        return len(self.files)

    def count_rows(self) -> Optional[int]:
        if self.filters is not None:
            return None  # footer counts pre-date row filtering
        if self._count_cache is not None:
            return self._count_cache
        try:
            import pyarrow.parquet as pq

            self._count_cache = sum(
                pq.ParquetFile(f).metadata.num_rows for f in self.files)
            return self._count_cache
        except Exception:  # noqa: BLE001 — metadata is best-effort
            return None

    def schema(self) -> Optional[dict]:
        if self._schema_cache is not None:
            out = self._schema_cache
        else:
            try:
                import pyarrow.parquet as pq

                sch = pq.read_schema(self.files[0])
                out = {
                    f.name: str(np.dtype(f.type.to_pandas_dtype()))
                    for f in sch
                }
                self._schema_cache = out
            except Exception:  # noqa: BLE001 — fall back to executing
                return None
        if self.columns is not None:
            try:
                return {c: out[c] for c in self.columns}
            except KeyError:
                return None
        return out

    def with_columns(self, columns: List[str]) -> "ParquetDatasource":
        return ParquetDatasource(self.files, columns, self.filters)

    def with_filters(self, exprs: List[tuple]) -> "ParquetDatasource":
        return ParquetDatasource(
            self.files, self.columns, (self.filters or []) + list(exprs))

    def describe(self) -> str:
        extra = ""
        if self.columns is not None:
            extra += f", columns={self.columns}"
        if self.filters is not None:
            extra += f", filters={self.filters}"
        return f"parquet: {len(self.files)} files{extra}"


class SQLDatasource(Datasource):
    """read_sql over a DB-API connection factory. Projection pushdown
    rewrites the column list of the wrapping SELECT (identifiers validated
    and quoted — never raw splicing)."""

    supports_column_pushdown = True

    def __init__(self, sql: str, connection_factory, parallelism: int,
                 partition_column: Optional[str], lower_bound, upper_bound,
                 columns: Optional[List[str]] = None):
        self.sql = sql
        self.connection_factory = connection_factory
        self.parallelism = parallelism
        self.partition_column = partition_column  # already validated/quoted
        self.lower_bound = lower_bound
        self.upper_bound = upper_bound
        self.columns = list(columns) if columns is not None else None

    def _select(self, inner: str) -> str:
        if self.columns is None:
            return inner
        cols = ", ".join(_validate_sql_identifier(c) for c in self.columns)
        return f"SELECT {cols} FROM ({inner}) AS _rt_proj"

    def producers(self) -> List[Any]:
        # Partition predicate applies to the INNER query, the projection
        # wraps outside it: the pushed-down column list may exclude
        # partition_column, which must stay visible to the WHERE.
        if self.partition_column is None or self.parallelism <= 1:
            return [functools.partial(_sql_read, self._select(self.sql),
                                      self.connection_factory)]
        span = (float(self.upper_bound) - float(self.lower_bound)) \
            / self.parallelism
        producers = []
        for i in builtins.range(self.parallelism):
            # JDBC-style split: bounds set the STRIDE; the edge partitions
            # are unbounded so rows outside [lower_bound, upper_bound)
            # still land somewhere instead of silently vanishing
            lo = None if i == 0 else self.lower_bound + span * i
            hi = (None if i == self.parallelism - 1
                  else self.lower_bound + span * (i + 1))
            # numeric literals, not driver placeholders: paramstyle varies
            # across DB-API drivers (sqlite qmark, psycopg2 pyformat, ...)
            # and the bounds are framework-generated numbers, never user
            # strings
            preds = []
            if lo is not None:
                preds.append(f"{self.partition_column} >= {float(lo)!r}")
            if hi is not None:
                preds.append(f"{self.partition_column} < {float(hi)!r}")
            part = (f"SELECT * FROM ({self.sql}) AS _rt_sub "
                    f"WHERE {' AND '.join(preds)}")
            producers.append(functools.partial(
                _sql_read, self._select(part), self.connection_factory))
        return producers

    def num_blocks(self) -> int:
        if self.partition_column is None or self.parallelism <= 1:
            return 1
        return self.parallelism

    def with_columns(self, columns: List[str]) -> "SQLDatasource":
        for c in columns:
            _validate_sql_identifier(c)  # reject before it reaches a query
        return SQLDatasource(self.sql, self.connection_factory,
                             self.parallelism, self.partition_column,
                             self.lower_bound, self.upper_bound, columns)

    def describe(self) -> str:
        extra = f", columns={self.columns}" if self.columns else ""
        return f"sql: parallelism={self.parallelism}{extra}"


def _sql_read(sql, connection_factory):
    conn = connection_factory()
    try:
        cur = conn.cursor()
        cur.execute(sql)
        cols = [d[0] for d in cur.description]
        rows = cur.fetchall()
    finally:
        conn.close()
    return {c: np.asarray([r[i] for r in rows])
            for i, c in enumerate(cols)}


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------


def _chunk_bounds(n: int, k: int):
    # NB: module-level `range()` below shadows the builtin (API parity with
    # ray.data.range), hence builtins.range here
    return [((n * i) // k, (n * (i + 1)) // k) for i in builtins.range(k)]


def range(n: int, *, parallelism: int = -1) -> Dataset:  # noqa: A001 — API parity
    """Dataset of {"id": int64} rows 0..n-1 (reference: ray.data.range)."""
    k = parallelism if parallelism > 0 else min(max(1, n // 1000), 200)
    return Dataset._from_datasource(RangeDatasource(n, k))


def _range_block(lo: int, hi: int):
    return {"id": np.arange(lo, hi, dtype=np.int64)}


def from_items(items: Sequence[Any], *, parallelism: int = -1) -> Dataset:
    """Dataset from a local list (reference: ray.data.from_items)."""
    from ray_tpu.data.block import rows_to_block

    items = list(items)
    k = parallelism if parallelism > 0 else min(max(1, len(items) // 1000), 200)
    k = max(1, min(k, len(items) or 1))
    blocks = [
        rows_to_block(items[lo:hi]) for lo, hi in _chunk_bounds(len(items), k)
    ]
    return Dataset._from_datasource(SimpleDatasource(
        [functools.partial(_identity, b) for b in blocks],
        num_rows=len(items), label="items"))


def _identity(b):
    return b


def from_numpy(arr: np.ndarray, *, column: str = "data",
               parallelism: int = -1) -> Dataset:
    k = parallelism if parallelism > 0 else min(max(1, len(arr) // 100_000), 200)
    return Dataset._from_datasource(SimpleDatasource(
        [
            functools.partial(_identity, {column: arr[lo:hi]})
            for lo, hi in _chunk_bounds(len(arr), k)
        ],
        num_rows=len(arr), label="numpy"))


def _expand_paths(paths: Union[str, Sequence[str]], suffixes=None) -> List[str]:
    if isinstance(paths, str):
        paths = [paths]
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _, files in os.walk(p):
                out.extend(os.path.join(root, f) for f in sorted(files))
        elif any(c in p for c in "*?["):
            out.extend(sorted(glob_mod.glob(p)))
        else:
            out.append(p)
    if suffixes:
        out = [p for p in out if any(p.endswith(s) for s in suffixes)]
    if not out:
        raise FileNotFoundError(f"no files matched {paths}")
    return out


def read_parquet(paths: Union[str, Sequence[str]], *, columns=None) -> Dataset:
    """One block per parquet file, columnar numpy (reference: read_parquet).
    `columns=` narrows the read up front; projection/predicate pushdown
    narrow it further from the plan."""
    files = _expand_paths(paths, suffixes=[".parquet"])
    return Dataset._from_datasource(ParquetDatasource(files, columns))


def _read_parquet_file(path: str, columns, filters=None):
    import pyarrow.parquet as pq

    table = pq.read_table(path, columns=columns, filters=filters)
    return {
        name: col.to_numpy(zero_copy_only=False)
        for name, col in zip(table.column_names, table.columns)
    }


def read_csv(paths: Union[str, Sequence[str]], **pandas_kwargs) -> Dataset:
    files = _expand_paths(paths, suffixes=[".csv"])
    return Dataset._from_datasource(SimpleDatasource(
        [functools.partial(_read_csv_file, f, pandas_kwargs) for f in files],
        label="csv"))


def _read_csv_file(path: str, pandas_kwargs):
    import pandas as pd

    df = pd.read_csv(path, **pandas_kwargs)
    return {c: df[c].to_numpy() for c in df.columns}


def read_json(paths: Union[str, Sequence[str]], *, lines: bool = True) -> Dataset:
    files = _expand_paths(paths, suffixes=[".json", ".jsonl"])
    return Dataset._from_datasource(SimpleDatasource(
        [functools.partial(_read_json_file, f, lines) for f in files],
        label="json"))


def _read_json_file(path: str, lines: bool):
    import pandas as pd

    df = pd.read_json(path, lines=lines)
    return {c: df[c].to_numpy() for c in df.columns}


def read_binary_files(paths: Union[str, Sequence[str]],
                      *, include_paths: bool = False,
                      parallelism: int = -1) -> Dataset:
    files = _expand_paths(paths)
    k = parallelism if parallelism > 0 else min(len(files), 64)
    return Dataset._from_datasource(SimpleDatasource(
        [
            functools.partial(_read_binary_chunk, files[lo:hi], include_paths)
            for lo, hi in _chunk_bounds(len(files), k)
        ],
        label="binary"))


def _read_binary_chunk(files: List[str], include_paths: bool):
    rows = []
    for f in files:
        with open(f, "rb") as fh:
            data = fh.read()
        rows.append({"path": f, "bytes": data} if include_paths else {"bytes": data})
    return rows


def read_images(paths: Union[str, Sequence[str]], *, size=None,
                mode: str = "RGB", parallelism: int = -1) -> Dataset:
    """Decode images into {"image": uint8 HWC} rows; `size=(h, w)` resizes so
    blocks stack into one array (reference: ray.data.read_images)."""
    files = _expand_paths(
        paths, suffixes=[".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp"]
    )
    k = parallelism if parallelism > 0 else min(len(files), 64)
    return Dataset._from_datasource(SimpleDatasource(
        [
            functools.partial(_read_image_chunk, files[lo:hi], size, mode)
            for lo, hi in _chunk_bounds(len(files), k)
        ],
        label="images"))


def _read_image_chunk(files: List[str], size, mode: str):
    from PIL import Image

    arrays = []
    for f in files:
        img = Image.open(f).convert(mode)
        if size is not None:
            img = img.resize((size[1], size[0]))
        arrays.append(np.asarray(img))
    if size is not None:
        return {"image": np.stack(arrays)}
    return [{"image": a} for a in arrays]


def _validate_sql_identifier(name: str) -> str:
    """Quote `partition_column` (or a pushed-down column name) as a SQL
    identifier. Only plain identifiers (letters/digits/underscore, possibly
    dotted) are accepted — the name is spliced into the query text, so
    anything else is rejected rather than passed through."""
    import re

    if not isinstance(name, str) or not re.fullmatch(
            r"[A-Za-z_][A-Za-z0-9_]*(\.[A-Za-z_][A-Za-z0-9_]*)?", name):
        raise ValueError(
            f"column {name!r} is not a plain SQL identifier "
            "(letters, digits, underscores, optional single dot)")
    # standard SQL double-quoting; the dotted form quotes each part
    return ".".join('"%s"' % part for part in name.split("."))


def _validate_sql_bound(value, which: str) -> float:
    """Range bounds must be real numbers: they are spliced as numeric
    literals (paramstyle varies across DB-API drivers), and range
    partitioning itself is numeric-only."""
    import numbers

    if isinstance(value, bool) or not isinstance(value, numbers.Real):
        raise TypeError(
            f"read_sql {which} must be a real number for numeric range "
            f"partitioning, got {type(value).__name__}: {value!r}. "
            "String/timestamp/date partition columns are not supported — "
            "partition on a numeric key (e.g. an integer id) instead.")
    return float(value)


def read_sql(sql: str, connection_factory, *, parallelism: int = 1,
             partition_column: Optional[str] = None,
             lower_bound=None, upper_bound=None) -> Dataset:
    """Read a SQL query through a DB-API connection factory (reference:
    python/ray/data/read_api.py read_sql / datasource/sql_datasource.py).

    `connection_factory` is a zero-arg callable returning a DB-API 2.0
    connection (sqlite3.connect(...), psycopg2.connect(...), ...) — it runs
    INSIDE the read tasks, so the connection never pickles. With
    `partition_column` + bounds, `parallelism` tasks each read one range
    slice of the query (the standard JDBC-style range split); otherwise one
    task reads the whole result.

    Range partitioning is NUMERIC-ONLY: `partition_column` must hold real
    numbers and `lower_bound`/`upper_bound` must be numbers (they become
    numeric literals in the generated predicates). The column name must be
    a plain identifier; it is validated and quoted before being spliced
    into the query."""
    if parallelism > 1 and partition_column is None:
        raise ValueError("parallel read_sql needs partition_column + bounds")
    if partition_column is not None:
        partition_column = _validate_sql_identifier(partition_column)
    if partition_column is not None and parallelism > 1:
        if lower_bound is None or upper_bound is None:
            raise ValueError("parallel read_sql needs lower_bound/upper_bound")
        lower_bound = _validate_sql_bound(lower_bound, "lower_bound")
        upper_bound = _validate_sql_bound(upper_bound, "upper_bound")
        if upper_bound < lower_bound:
            raise ValueError(
                f"read_sql upper_bound ({upper_bound}) must be >= "
                f"lower_bound ({lower_bound})")
    return Dataset._from_datasource(SQLDatasource(
        sql, connection_factory, parallelism, partition_column,
        lower_bound, upper_bound))
